"""Console entry points (SURVEY.md §3 C13 — the reference's ``cmd/``).

The reference ships daemon mains (device-plugin, extender) plus flag
parsing; forks add inspection tooling. Here:

  tpukube-plugin    node agent: device discovery, kubelet registration,
                    ListAndWatch/Allocate gRPC service, health watch,
                    /metrics, node-topology annotation emission
  tpukube-extender  scheduler extender HTTP daemon (filter/prioritize/bind
                    + /metrics + /state/* + /trace)
  tpukube-sim       run a BASELINE config scenario against the real stack
                    and print its metrics as one JSON line
  tpukubectl        inspect a live extender: topo / alloc / gangs /
                    metrics, and offline trace replay
  tpukube-obs       offline observability tooling: `timeline` converts a
                    JSONL decision trace to Chrome trace-event JSON
                    (Perfetto-loadable per-pod scheduling timelines)

All commands take ``--config <yaml>`` (same schema as TpuKubeConfig) and
honor TPUKUBE_* env overrides, mirroring the reference's flag+config-file
pattern (SURVEY.md §6 config system).
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import urllib.request
from typing import Any, Optional

from tpukube.core.config import TpuKubeConfig, load_config

log = logging.getLogger("tpukube.cli")


def _base_parser(prog: str, description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog, description=description)
    p.add_argument("--config", metavar="YAML", default=None,
                   help="config file (TpuKubeConfig schema); TPUKUBE_* env wins")
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="-v info, -vv debug (glog-style leveled logging)")
    return p


def _setup(args: argparse.Namespace) -> TpuKubeConfig:
    level = (logging.WARNING, logging.INFO, logging.DEBUG)[min(args.verbose, 2)]
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    return load_config(yaml_path=args.config)


def _add_kube_api_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--kube-api", metavar="URL", default=None,
                   help="Kubernetes apiserver base URL (default: in-cluster "
                        "KUBERNETES_SERVICE_HOST autodetect; 'off' disables "
                        "the apiserver channel)")
    p.add_argument("--kube-token-file", default=None,
                   help="bearer token file (default: serviceaccount token)")
    p.add_argument("--kube-ca-file", default=None,
                   help="apiserver CA bundle (default: serviceaccount ca.crt)")


def _make_apiserver(args: argparse.Namespace,
                    cfg: Optional[TpuKubeConfig] = None, journal=None):
    """RestApiServer from flags / in-cluster env, or None when no
    apiserver is reachable-by-configuration (sim/dev runs).

    With ``cfg``, every unary request runs under the unified retry
    policy (retry_* knobs) and — when circuit_failure_threshold > 0 —
    behind a circuit breaker; ``journal`` receives the
    RetryExhausted/CircuitOpen/CircuitClosed events. The built
    Retrier/CircuitBreaker ride on the returned server as
    ``api.retrier`` / ``api.circuit`` for metrics and degraded-mode
    wiring."""
    if args.kube_api == "off":
        return None
    from tpukube.apiserver import (
        ApiServerError,
        RestApiServer,
        transient_api_error,
    )
    from tpukube.core import retry

    retrier = circuit = None
    if cfg is not None:
        circuit = retry.CircuitBreaker(
            failure_threshold=cfg.circuit_failure_threshold,
            reset_seconds=cfg.circuit_reset_seconds,
            half_open_probes=cfg.circuit_half_open_probes,
            name="apiserver", journal=journal,
        )
        retrier = retry.Retrier(
            retry.policy_from_config(cfg), name="apiserver",
            retryable=transient_api_error, journal=journal,
        )
    try:
        return RestApiServer(
            base_url=args.kube_api,
            token_path=args.kube_token_file,
            ca_path=args.kube_ca_file,
            retrier=retrier,
            circuit=circuit,
        )
    except ApiServerError as e:
        if args.kube_api:  # explicitly requested: configuration error
            raise
        log.info("no apiserver channel (%s); running standalone", e)
        return None


def _install_stop_handlers() -> threading.Event:
    """Install SIGINT/SIGTERM handlers NOW (before any serving starts, so a
    supervisor's early TERM still shuts down cleanly); returns the event the
    main thread should wait on."""
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    return stop


# -- tpukube-plugin ----------------------------------------------------------

def main_plugin(argv: Optional[list[str]] = None) -> int:
    p = _base_parser("tpukube-plugin", "TPU node agent / device plugin daemon")
    p.add_argument("--socket", default=None,
                   help="override plugin unix socket path")
    p.add_argument("--no-register", action="store_true",
                   help="serve without dialing the kubelet (sim/debug)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve /metrics on this port (0 = ephemeral)")
    p.add_argument("--annotation-out", metavar="FILE", default="-",
                   help="write the node-topology annotation JSON here "
                        "('-' = stdout); tpukube-syncer applies it")
    _add_kube_api_args(p)
    args = p.parse_args(argv)
    cfg = _setup(args)
    stop = _install_stop_handlers()

    import os

    from tpukube.core import codec
    from tpukube.device.tpu import TpuDeviceManager
    from tpukube.metrics import MetricsServer, render_plugin_metrics
    from tpukube.plugin.server import (
        DevicePluginServer,
        HealthWatcher,
        KubeletSessionWatcher,
    )

    host = os.environ.get("NODE_NAME")
    if host and cfg.backend == "sim" and not cfg.sim_host_origin:
        # the sim backend derives this host's chip-coord origin from the
        # host-i-j-k naming convention; a free-form cluster node name
        # needs TPUKUBE_SIM_HOST_ORIGIN — without it, keep the default
        # host name rather than crash at startup
        try:
            cfg.sim_mesh().host_origin(host)
        except ValueError:
            log.warning(
                "NODE_NAME %r is not host-i-j-k and sim_host_origin is "
                "unset; using the default sim host name", host,
            )
            host = None
    with TpuDeviceManager(cfg, host=host) as device:
        server = DevicePluginServer(cfg, device, socket_path=args.socket)
        server.start()

        def write_annotation() -> None:
            # SURVEY §4.1's "write NodeInfo annotation" step, re-run on
            # every health/link transition so the SCHEDULER (via the
            # syncer's Node PATCH) sees faults, not just the kubelet.
            # Atomic publish via a PER-WRITER temp file + rename: the
            # syncer polls this file from another process, and a shared
            # fixed temp name could be truncated by a concurrent writer
            # mid-publish.
            import tempfile

            anno = codec.annotate_node(device.node_info(), device.mesh)
            payload = json.dumps(anno)
            if args.annotation_out == "-":
                print(payload, flush=True)
                return
            out_dir = os.path.dirname(os.path.abspath(args.annotation_out))
            fd, tmp_path = tempfile.mkstemp(dir=out_dir, suffix=".tmp")
            try:
                # mkstemp files are 0600; the syncer sidecar reading this
                # file may run as a different user — restore umask-style
                # world-readability before publish
                os.fchmod(fd, 0o644)
                with os.fdopen(fd, "w") as f:
                    f.write(payload + "\n")
                os.replace(tmp_path, args.annotation_out)
            except BaseException:
                try:
                    os.unlink(tmp_path)  # no orphaned temp per failure
                except OSError:
                    pass
                raise

        # initial emit BEFORE the watcher starts: exactly one writer at a
        # time touches the annotation file
        write_annotation()
        # node-local observability: structured event journal + per-chip
        # telemetry sampler (obs/events.py, obs/health.py). The sampler
        # emits ChipUnhealthy/ChipRecovered/LinkFault events; the
        # annotation refresh (incl. the health summary the extender's
        # fleet rollup reads) stays on the HealthWatcher's transition
        # hook — one writer, no duplicate rewrites.
        from tpukube.obs.events import EventJournal
        from tpukube.obs.health import HealthSampler

        journal = EventJournal(capacity=cfg.events_capacity,
                               path=cfg.events_path or None,
                               max_sink_bytes=cfg.events_sink_max_bytes)
        server.events = journal
        sampler = HealthSampler(device, journal=journal)
        sampler.start()
        watcher = HealthWatcher(device, server,
                                on_transition=write_annotation)
        watcher.start()
        kubelet_watch = None
        if not args.no_register:
            kubelet_watch = KubeletSessionWatcher(server)
            kubelet_watch.events = journal

        # (initial annotation already emitted above, before the watcher
        # started; transitions re-emit through the watcher hook)

        # the extender<->kubelet device-id loop: feed bound pods' planned
        # allocs into GetPreferredAllocation steering, report divergent
        # kubelet choices back onto the pod (apiserver channel optional —
        # the sim drives these objects directly)
        intent_watch = None
        api = _make_apiserver(args, cfg, journal=journal)
        if api is not None:
            from tpukube.apiserver import (
                AllocIntentWatcher,
                alloc_divergence_reporter,
            )

            server.set_alloc_reporter(alloc_divergence_reporter(api))
            intent_watch = AllocIntentWatcher(
                api, device.host, server,
                poll_seconds=cfg.health_poll_seconds,
            )
            intent_watch.start()
        from tpukube.obs.statusz import plugin_statusz

        metrics = MetricsServer(
            lambda: render_plugin_metrics(
                server, health=watcher, kubelet_watch=kubelet_watch,
                intent_watch=intent_watch, sampler=sampler,
                events=journal,
            ),
            port=args.metrics_port,
            statusz=lambda: plugin_statusz(
                server, device=device, health=watcher,
                kubelet_watch=kubelet_watch, intent_watch=intent_watch,
                sampler=sampler, events=journal,
            ),
        )
        metrics.start()

        if kubelet_watch is not None:
            try:
                # jittered backoff + max attempts via the unified retry
                # policy (retry_* config knobs) — the session watcher's
                # poll-cadence retry remains the outer safety net
                kubelet_watch.retrier.journal = journal
                kubelet_watch.retrier.call(server.register_with_kubelet)
            except Exception as e:
                # kubelet not up yet (DaemonSet boot race): the session
                # watcher registers on a later poll — do not crash-loop
                log.warning(
                    "initial kubelet registration failed (%s); the session "
                    "watcher will retry", e,
                )
                kubelet_watch.mark_unregistered()
            kubelet_watch.start()
        log.warning(
            "plugin serving %s on %s (metrics :%d)",
            server.resource_name, server.socket_path, metrics.port,
        )
        try:
            stop.wait()
        finally:
            if intent_watch is not None:
                intent_watch.stop()
            if kubelet_watch is not None:
                kubelet_watch.stop()
            watcher.stop()
            sampler.stop()
            journal.close()
            metrics.stop()
            server.stop()
    return 0


# -- tpukube-syncer ----------------------------------------------------------

def main_syncer(argv: Optional[list[str]] = None) -> int:
    """Annotation syncer sidecar: applies the plugin's node-annotation file
    to the Node object through the apiserver (SURVEY.md §4.1's 'write
    NodeInfo annotation to apiserver' step — the component the DaemonSet's
    /var/run/tpukube mount exists for)."""
    import os

    p = _base_parser(
        "tpukube-syncer",
        "apply the node agent's annotation file to the Node via the apiserver",
    )
    p.add_argument("--annotation-file", metavar="FILE", required=True,
                   help="the plugin's --annotation-out file to watch")
    p.add_argument("--node", default=None,
                   help="Node object name (default: $NODE_NAME)")
    p.add_argument("--poll", type=float, default=5.0,
                   help="file poll interval seconds")
    p.add_argument("--once", action="store_true",
                   help="apply once and exit (init-container mode)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve /metrics on this port (0 = disabled)")
    _add_kube_api_args(p)
    args = p.parse_args(argv)
    cfg = _setup(args)
    node = args.node or os.environ.get("NODE_NAME")
    if not node:
        p.error("--node or $NODE_NAME required")

    from tpukube.apiserver import NodeAnnotationSyncer

    api = _make_apiserver(args, cfg)
    if api is None:
        p.error("no apiserver: pass --kube-api or run in-cluster")
    syncer = NodeAnnotationSyncer(
        api, node, args.annotation_file, poll_seconds=args.poll
    )
    if args.once:
        return 0 if syncer.check_once() else 1
    stop = _install_stop_handlers()
    syncer.start()
    metrics = None
    if args.metrics_port:
        from tpukube.metrics import MetricsServer, render_syncer_metrics

        metrics = MetricsServer(lambda: render_syncer_metrics(syncer),
                                port=args.metrics_port)
        metrics.start()
    log.warning("syncing %s -> node %s", args.annotation_file, node)
    try:
        stop.wait()
    finally:
        if metrics is not None:
            metrics.stop()
        syncer.stop()
    return 0


# -- tpukube-extender --------------------------------------------------------

def main_extender(argv: Optional[list[str]] = None) -> int:
    p = _base_parser("tpukube-extender", "scheduler extender HTTP daemon")
    p.add_argument("--host", default=None, help="override extender_host")
    p.add_argument("--port", type=int, default=None, help="override extender_port")
    # The extender's surface mutates the ledger (/bind executes
    # preemption!) and discloses placement (/state, /trace) — it must not
    # serve anonymous callers. Two auth modes, pick per client fleet:
    #   mTLS  (--tls-cert/--tls-key/--tls-client-ca): the TLS layer
    #         rejects peers without a CA-signed client cert — what stock
    #         kube-scheduler speaks (extender tlsConfig certFile/keyFile).
    #   bearer (--auth-token-file): application-level token on every
    #         route except /healthz and /metrics — for tpukubectl and
    #         setups where the scheduler sits behind an injecting proxy.
    p.add_argument("--tls-cert", default=None, metavar="PEM",
                   help="serve HTTPS with this certificate chain")
    p.add_argument("--tls-key", default=None, metavar="PEM",
                   help="private key for --tls-cert")
    p.add_argument("--tls-client-ca", default=None, metavar="PEM",
                   help="require client certs signed by this CA (mTLS)")
    p.add_argument("--auth-token-file", default=None, metavar="FILE",
                   help="require 'Authorization: Bearer <token>' matching "
                        "this file's content on all non-probe routes")
    p.add_argument("--probe-port", type=int, default=0, metavar="PORT",
                   help="serve /healthz and /metrics ONLY on this extra "
                        "plain-HTTP port (required with --tls-client-ca: "
                        "kubelet probes and Prometheus cannot present "
                        "client certs; 0 = disabled)")
    _add_kube_api_args(p)
    args = p.parse_args(argv)
    cfg = _setup(args)

    if cfg.planner_replicas > 1:
        # the slice-partitioned plane (sched/shard.py) deploys as ONE
        # extender daemon per replica — each with its slice set and
        # journal segment — behind the routing contract the in-process
        # ShardRouter defines; a single daemon asked to be N replicas
        # would shard nothing (one process, one GIL, one failure
        # domain). See README "Sharded control plane".
        p.error(
            "planner_replicas > 1 is a deployment topology, not a "
            "daemon flag: run one `tpukube-shard-worker` per replica "
            "behind the router webhook front (deploy/README's "
            "multi-daemon sketch; the in-process ShardRouter serves "
            "the sim/bench plane — `tpukube-sim 14`)"
        )

    ssl_ctx = None
    if args.tls_cert or args.tls_key:
        import ssl

        if not (args.tls_cert and args.tls_key):
            p.error("--tls-cert and --tls-key must be given together")
        ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ssl_ctx.load_cert_chain(args.tls_cert, args.tls_key)
        if args.tls_client_ca:
            ssl_ctx.load_verify_locations(args.tls_client_ca)
            ssl_ctx.verify_mode = ssl.CERT_REQUIRED
    elif args.tls_client_ca:
        p.error("--tls-client-ca requires --tls-cert/--tls-key")
    auth_token = None
    if args.auth_token_file:
        with open(args.auth_token_file) as f:
            auth_token = f.read().strip()
        if not auth_token:
            p.error(f"--auth-token-file {args.auth_token_file} is empty")

    from aiohttp import web

    from tpukube.sched.extender import (
        Extender,
        make_app,
        make_probe_app,
        run_probe_server,
    )

    host = args.host or cfg.extender_host
    port = args.port if args.port is not None else cfg.extender_port
    extender = Extender(cfg)
    loops = []
    reconcile = evictions = node_refresh = lifecycle = None
    pod_informer = None
    api = _make_apiserver(args, cfg, journal=extender.events)
    if api is not None:
        from tpukube.apiserver import (
            AllocReconcileLoop,
            EvictionExecutor,
            NodeTopologyRefreshLoop,
            PodAdmissionFeed,
            PodInformer,
            PodLifecycleReleaseLoop,
            pod_binder,
            rebuild_extender,
        )

        # restart story (SURVEY §6 / ISSUE 11): reconstruct the ledger
        # + gang reservations BEFORE serving — a freshly-restarted
        # extender otherwise re-plans chips that are already running
        # someone's containers. With journal_enabled the durable
        # journal recovers O(Δ-since-checkpoint) (checkpoint + WAL
        # replay + apiserver reconcile); a journal that cannot produce
        # a trustworthy base falls back to the legacy O(fleet) rebuild
        # on a FRESH extender — degraded, never wrong.
        recovered = False
        if extender.journal is not None:
            from tpukube.sched.journal import (
                JournalError,
                recover_extender,
            )

            try:
                rstats = recover_extender(extender, api)
                log.warning(
                    "journal recovery: %d allocation(s) known after "
                    "checkpoint+replay+reconcile (%d record(s) "
                    "replayed, %.3fs)",
                    len(extender.state.allocations()),
                    rstats["replayed"], rstats["recovery_s"],
                )
                recovered = True
            except JournalError as e:
                log.error("journal recovery failed (%s); falling back "
                          "to the legacy full rebuild", e)
                extender.journal.crash()
                extender = Extender(cfg)
                api = _make_apiserver(args, cfg, journal=extender.events)
        # nodeCacheCapable webhooks carry names only: without this loop,
        # health/link faults would never reach the node cache (built
        # before the rebuild so the rebuild can prime it)
        node_refresh = NodeTopologyRefreshLoop(
            extender, api, poll_seconds=cfg.health_poll_seconds
        )
        if recovered:
            # prime the refresh loop with the recovered node payloads
            # (its first poll must not re-dispatch 10k unchanged
            # upsert_node decisions)
            for name in extender.state.node_names():
                view = extender.state.node(name)
                if view is not None:
                    node_refresh.note_applied(name, view.raw_payload)
        else:
            if extender.journal is not None:
                # detach while the O(fleet) rebuild runs: every one of
                # its commits would otherwise serialize a WAL record
                # the checkpoint below immediately truncates away
                extender.state.set_journal(None)
                extender.gang.set_journal(None)
            restored = rebuild_extender(extender, api,
                                        refresh=node_refresh)
            if restored:
                log.warning("rebuilt %d allocation(s) from the "
                            "apiserver", restored)
            if extender.journal is not None:
                # fallback rebuilds still end at a durable point so the
                # NEXT restart recovers warm
                extender.state.set_journal(extender.journal)
                extender.gang.set_journal(extender.journal)
                extender.journal.write_checkpoint_sync(
                    extender.checkpoint_doc()
                )
        # with bindVerb delegated here, the extender must create the real
        # Binding — kube-scheduler won't
        extender.binder = pod_binder(api)
        # the channel's retry/circuit objects ride on the extender so
        # /metrics exports tpukube_retry_* / tpukube_circuit_*
        extender.api_retrier = api.retrier
        extender.api_circuit = api.circuit
        if api.circuit is not None and api.circuit.enabled:
            # degraded mode: while the apiserver circuit is open, fail
            # filter/bind safe (no bind, no preemption plan) — an
            # extender that cannot effect decisions must not make them
            extender.degraded_gate = (
                lambda: ("apiserver circuit open"
                         if api.circuit.is_open() else None)
            )

        # PDB precheck (dry-run Eviction POST): a preemption plan with a
        # PDB-blocked victim is refused before any irreversible eviction
        def _precheck(pod_key: str):
            namespace, name = pod_key.split("/", 1)
            return api.evict_pod(namespace, name, dry_run=True)

        extender.evict_precheck = _precheck
        reconcile = AllocReconcileLoop(
            extender, api, poll_seconds=cfg.health_poll_seconds
        )
        # the effector for preemption/rollback decisions: without it a
        # victim pod keeps running on chips the ledger shows free
        evictions = EvictionExecutor(extender, api)
        # the release effector: completed/deleted pods' chips return to
        # the ledger — without it every finished job leaks its chips.
        # Its watch also confirms the executor's in-flight terminations
        # (one DELETED event instead of a per-key GET poll).
        lifecycle = PodLifecycleReleaseLoop(extender, api,
                                            evictions=evictions)
        informer_children = [lifecycle, reconcile]
        if cfg.batch_enabled:
            # feed the batch scheduling queue from the SAME pod stream:
            # pending TPU pods reach the cycle planner the moment their
            # watch event lands, instead of waiting for their /filter
            # webhook — batching stops being sim/webhook-only
            informer_children.append(
                PodAdmissionFeed(extender, api,
                                 poll_seconds=cfg.health_poll_seconds)
            )
        # ONE pod stream for all pod loops: the informer lists and
        # watches once, fanning events to lifecycle + reconcile (+ the
        # batch admission feed when batching is on)
        pod_informer = PodInformer(api, informer_children,
                                   poll_seconds=cfg.health_poll_seconds)
        # watch-stream reconnects land in the event journal: frequent
        # WatchReconnected events mean DELETED events are being missed
        # in backoff windows — the first thing to check when releases lag
        node_refresh.journal = extender.events
        pod_informer.journal = extender.events
        loops = [evictions, node_refresh, pod_informer]
        for loop in loops:
            loop.start()
    if ssl_ctx is None and auth_token is None:
        log.warning(
            "extender serving WITHOUT transport or bearer auth — anyone "
            "reaching this port can bind pods and execute preemption; "
            "use --tls-cert/--tls-key (+ --tls-client-ca for mTLS) or "
            "--auth-token-file outside of dev/sim"
        )
    if args.tls_client_ca and not args.probe_port:
        log.warning(
            "mTLS without --probe-port: kubelet httpGet probes and "
            "Prometheus scrapes cannot present client certificates and "
            "will be rejected at the handshake — serve them with "
            "--probe-port (the deploy/ manifests use 12346)"
        )
    stop_probe = None
    if args.probe_port:
        stop_probe = run_probe_server(
            make_probe_app(extender, reconcile=reconcile,
                           evictions=evictions, node_refresh=node_refresh,
                           lifecycle=lifecycle),
            host, args.probe_port,
        )
    log.warning("extender serving on %s:%d (score_mode=%s, tls=%s, "
                "mtls=%s, bearer=%s, probe_port=%d)",
                host, port, cfg.score_mode, ssl_ctx is not None,
                bool(args.tls_client_ca), auth_token is not None,
                args.probe_port)
    try:
        web.run_app(make_app(extender, reconcile=reconcile,
                             evictions=evictions,
                             node_refresh=node_refresh,
                             lifecycle=lifecycle,
                             auth_token=auth_token,
                             informer=pod_informer),
                    host=host, port=port, ssl_context=ssl_ctx,
                    print=None, handle_signals=True)
    finally:
        if stop_probe is not None:
            stop_probe()
        for loop in loops:
            loop.stop()
        # drain the capture sinks so a post-mortem read sees every event
        if extender.trace is not None:
            extender.trace.close()
        if extender.decisions is not None:
            extender.decisions.close()
        if extender.capacity is not None:
            extender.capacity.close()
        extender.events.close()
    return 0


# -- tpukube shard-worker ----------------------------------------------------

def main_shard_worker(argv: Optional[list[str]] = None) -> int:
    """One planner replica of the process-parallel sharded control
    plane (sched/shardworker.py): a plain extender daemon serving the
    webhook app plus the /worker/* transport routes. The ShardRouter's
    subprocess transport spawns these; production runs one per replica
    behind the router webhook front."""
    from tpukube.sched.shardworker import main_worker

    return main_worker(argv)


# -- tpukube-sim -------------------------------------------------------------

def main_sim(argv: Optional[list[str]] = None) -> int:
    p = _base_parser(
        "tpukube-sim",
        "run a BASELINE config scenario against the real control-plane stack",
    )
    p.add_argument("scenario", type=int, choices=range(1, 16),
                   help="BASELINE config number (1..5), 6 = the "
                        "steady-state churn benchmark (completions -> "
                        "release loop -> re-scheduling), 7 = fault "
                        "telemetry (chip + ICI link faults through the "
                        "telemetry pipeline: events, per-chip metrics, "
                        "fleet rollup, SLO scrape), 8 = apiserver chaos "
                        "under churn (seeded fault schedule, retry/"
                        "circuit/degraded mode; chaos_seed config), "
                        "9 = extender crash + cold restart mid-gang-"
                        "commit (rebuild_from_pods + reconcile repair), "
                        "15 = maintenance storm (seeded maintenance + "
                        "spot churn over graceful drains, the "
                        "autoscaler loop, and a sharded rebalance-away; "
                        "chaos_seed config)")
    args = p.parse_args(argv)
    cfg = _setup(args)

    from tpukube.sim import scenarios

    # dynamic lock-order detection (tpukube.analysis.lockgraph): the
    # config flag wraps every tpukube-created Lock/RLock for the whole
    # scenario run, whatever topology the scenario itself loads, and
    # the result JSON gains the acquisition-order graph + any deadlock
    # cycles. Off by default — zero overhead unless asked for.
    monitor = None
    if cfg.lock_monitor:
        from tpukube.analysis import lockgraph

        monitor = lockgraph.install()
    try:
        # without --config each scenario uses its canonical BASELINE
        # topology; with it, the user's topology/config drives it
        result = scenarios.run(args.scenario, cfg if args.config else None)
    finally:
        if monitor is not None:
            from tpukube.analysis import lockgraph

            lockgraph.uninstall()
    if monitor is not None:
        result["lock_graph"] = monitor.report()
        if result["lock_graph"]["cycles"]:
            log.error("lock-order cycles detected: %s",
                      result["lock_graph"]["cycles"])
    print(json.dumps(result))
    return 1 if monitor is not None and monitor.cycles() else 0


# -- tpukube-obs -------------------------------------------------------------

def _since_arg(text: str) -> float:
    """argparse type for ``--since``: epoch seconds, a bare relative
    number, or a suffixed duration (15m, 2h, 90s, 1d) — the shared
    parser lives in tpukube.obs.capacity."""
    from tpukube.obs.capacity import parse_since

    try:
        return parse_since(text)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from None


def main_obs(argv: Optional[list[str]] = None) -> int:
    """Offline observability tooling: ``timeline`` converts a JSONL
    decision trace to Chrome trace-event JSON (Perfetto-loadable
    per-pod scheduling timelines); ``events`` queries a structured
    event-journal capture (events_path sink, or an /events dump saved
    one JSON object per line) with pod/node/reason/since filters;
    ``slo`` evaluates the burn-rate SLOs against a live /metrics
    endpoint or a captured snapshot."""
    p = argparse.ArgumentParser(
        prog="tpukube-obs",
        description="offline observability tooling "
                    "(timeline / events / capacity / slo)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    tp = sub.add_parser(
        "timeline",
        help="convert a JSONL decision trace (trace_path capture, or a "
             "/trace dump) to Chrome trace-event JSON",
    )
    tp.add_argument("trace_file", nargs="+",
                    help="JSONL capture(s); pass several with --merge "
                         "(the router's .router sink plus each "
                         "replica's own capture)")
    tp.add_argument("-o", "--out", default="-", metavar="FILE",
                    help="output file ('-' = stdout)")
    tp.add_argument("--merge", action="store_true",
                    help="stitch several per-process captures into ONE "
                         "Chrome trace: one process lane per file "
                         "(named for it), a shared time zero, and the "
                         "router's fan-out spans rendered as true "
                         "wall-clock slices enclosing the worker spans "
                         "they fanned out to")
    tp.add_argument("--stats", action="store_true",
                    help="also print per-phase timing stats (JSON) to stderr")

    ep = sub.add_parser(
        "events",
        help="query a JSONL event-journal capture (events_path sink)",
    )
    ep.add_argument("events_file")
    ep.add_argument("--pod", default=None, help="filter by pod key")
    ep.add_argument("--node", default=None, help="filter by node name")
    ep.add_argument("--reason", default=None,
                    help="filter by reason (e.g. ChipUnhealthy)")
    ep.add_argument("--replica", default=None,
                    help="filter by source replica (r0, r1, ...) in a "
                         "federated /events dump — the router stamps "
                         "each merged event with its source replica")
    ep.add_argument("--since", type=_since_arg, default=None, metavar="T",
                    help="absolute unix timestamp, a relative duration "
                         "(15m, 2h, 90s, 1d), or a bare number < 1e9 = "
                         "seconds before the newest event in the capture")
    ep.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON object per event instead of text lines")

    cp = sub.add_parser(
        "capacity",
        help="render a capacity flight-recorder capture or a live "
             "/capacity endpoint (sparkline / csv / json)",
    )
    cp.add_argument("capacity_file", nargs="*",
                    help="capacity_path JSONL capture(s); pass several "
                         "with --merge (one per replica)")
    cp.add_argument("--url", default=None,
                    help="live extender OR shard-router base URL "
                         "(reads /capacity; a router answers the "
                         "federated merge with per-replica "
                         "attribution)")
    cp.add_argument("--token-file", default=None, metavar="FILE",
                    help="bearer token file for an --auth-token-file "
                         "extender (/capacity sits behind its auth)")
    cp.add_argument("--merge", action="store_true",
                    help="stitch several per-replica captures into one "
                         "fleet view (each file becomes a replica lane "
                         "named for it)")
    cp.add_argument("--since", type=_since_arg, default=None,
                    metavar="T",
                    help="absolute unix timestamp, a relative duration "
                         "(15m, 2h), or a bare number < 1e9 = seconds "
                         "before the newest sample")
    cp.add_argument("--format", default="sparkline",
                    choices=("sparkline", "csv", "json"),
                    help="output rendering (default: sparkline)")
    cp.add_argument("--probe-count", type=int, default=None,
                    metavar="N",
                    help="with --url: what-if probe for N contiguous "
                         "chips (/capacity/probe) instead of the "
                         "recorder document")
    cp.add_argument("--probe-shape", default=None, metavar="XxYxZ",
                    help="with --url: what-if probe for a shaped box "
                         "(e.g. 4x4x4)")

    xp = sub.add_parser(
        "explain",
        help="why-pending / why-here / why-denied for one pod, from "
             "the decision-provenance layer (decisions_enabled)",
    )
    xp.add_argument("pod",
                    help="pod key (namespace/name; a bare name means "
                         "default/<name>)")
    xsrc = xp.add_mutually_exclusive_group(required=True)
    xsrc.add_argument("--url", default=None,
                      help="live extender OR shard-router base URL "
                           "(reads /explain; a router resolves the "
                           "owning replicas transparently and answers "
                           "the stitched federated chain)")
    xsrc.add_argument("--file", default=None, metavar="JSONL",
                      help="decisions_path JSONL sink capture to "
                           "assemble offline")
    xp.add_argument("--token-file", default=None, metavar="FILE",
                    help="bearer token file for an --auth-token-file "
                         "extender (/explain sits behind its auth)")
    xp.add_argument("--json", action="store_true", dest="as_json",
                    help="raw explain document instead of text")

    sp = sub.add_parser(
        "slo",
        help="evaluate the latency SLOs (burn rates) from /metrics",
    )
    src = sp.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", default=None,
                     help="live /metrics endpoint to scrape")
    src.add_argument("--snapshot", default=None, metavar="FILE",
                     help="captured /metrics text to evaluate offline")
    sp.add_argument("--window", type=float, default=0.0, metavar="SECONDS",
                    help="with --url: scrape twice this far apart and "
                         "report the windowed burn rate (0 = single "
                         "scrape, lifetime burn)")
    args = p.parse_args(argv)

    if args.cmd == "timeline":
        import os as os_mod

        from tpukube import trace as trace_mod
        from tpukube.obs import timeline

        if len(args.trace_file) > 1 and not args.merge:
            p.error("multiple trace files require --merge")
        if args.merge:
            captures = [
                (os_mod.path.basename(path), trace_mod.load(path))
                for path in args.trace_file
            ]
            text = json.dumps(timeline.merged_chrome_trace(captures),
                              sort_keys=True) + "\n"
            if args.out == "-":
                sys.stdout.write(text)
            else:
                with open(args.out, "w") as f:
                    f.write(text)
            if args.stats:
                merged = [e for _, evs in captures for e in evs]
                print(json.dumps(timeline.phase_stats(merged),
                                 indent=2), file=sys.stderr)
            return 0
        events = trace_mod.load(args.trace_file[0])
        if args.out == "-":
            timeline.dump_chrome_trace(events, sys.stdout)
        else:
            with open(args.out, "w") as f:
                timeline.dump_chrome_trace(events, f)
        if args.stats:
            print(json.dumps(timeline.phase_stats(events), indent=2),
                  file=sys.stderr)
        return 0

    if args.cmd == "explain":
        from urllib.parse import quote

        from tpukube.obs import decisions as decisions_mod

        pod = args.pod if "/" in args.pod else f"default/{args.pod}"
        if args.url:
            url = f"{args.url}/explain?pod={quote(pod, safe='/')}"
            req = urllib.request.Request(url)
            if args.token_file:
                with open(args.token_file) as f:
                    req.add_header("Authorization",
                                   f"Bearer {f.read().strip()}")
            with urllib.request.urlopen(req, timeout=10) as r:
                doc = json.loads(r.read())
        else:
            doc = decisions_mod.explain_doc(
                decisions_mod.load(args.file), pod
            )
        if args.as_json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(decisions_mod.format_explain(doc))
        # composes into scripts: a pod with NO provenance (unsampled,
        # rotated out, or provenance off) exits non-zero
        return 0 if doc.get("stages") else 1

    if args.cmd == "events":
        from tpukube.obs import events as events_mod

        evs = events_mod.load(args.events_file)
        since = args.since
        if since is not None and since < 1e9:
            newest = max(
                (float(e.get("last_ts", 0)) for e in evs
                 if isinstance(e, dict)), default=0.0,
            )
            since = newest - since
        evs = events_mod.filter_events(
            evs, reason=args.reason, pod=args.pod, node=args.node,
            since=since, replica=args.replica,
        )
        for ev in evs:
            if args.as_json:
                print(json.dumps(ev, sort_keys=True))
            else:
                print(events_mod.format_event(ev))
        return 0

    if args.cmd == "capacity":
        import os as os_mod

        from tpukube import trace as trace_mod
        from tpukube.obs import capacity as capacity_mod

        if args.url:
            if args.capacity_file:
                p.error("--url and capture files are exclusive")

            def fetch(path: str) -> dict:
                req = urllib.request.Request(f"{args.url}{path}")
                if args.token_file:
                    with open(args.token_file) as f:
                        req.add_header("Authorization",
                                       f"Bearer {f.read().strip()}")
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            if args.probe_count is not None or args.probe_shape:
                q = (f"count={args.probe_count}"
                     if args.probe_count is not None
                     else f"shape={args.probe_shape}")
                doc = fetch(f"/capacity/probe?{q}")
                print(json.dumps(doc, indent=2, sort_keys=True))
                # composes into scripts: exit 0 only when the demand
                # fits somewhere (contiguous or via the DCN fallback)
                return 0 if (doc.get("fits")
                             or (doc.get("dcn") or {}).get("fits")) \
                    else 1
            since = f"?since={args.since}" if args.since is not None \
                else ""
            print(capacity_mod.format_capacity(
                fetch(f"/capacity{since}"), args.format))
            return 0
        if not args.capacity_file:
            p.error("a capture file or --url is required")
        if args.probe_count is not None or args.probe_shape:
            p.error("--probe-count/--probe-shape need --url (a probe "
                    "runs against a live snapshot)")
        if len(args.capacity_file) > 1 and not args.merge:
            p.error("multiple capture files require --merge")
        since = args.since
        if args.merge:
            per = [(os_mod.path.basename(path),
                    {"samples": trace_mod.load(path)})
                   for path in args.capacity_file]
            doc = capacity_mod.merge_capacity_docs(per)
        else:
            doc = {"samples": trace_mod.load(args.capacity_file[0])}
        samples = doc.get("samples") or []
        if since is not None:
            if since < 1e9:
                newest = max((float(s.get("ts", 0.0))
                              for s in samples), default=0.0)
                since = newest - since
            doc["samples"] = [s for s in samples
                              if float(s.get("ts", 0.0)) >= since]
        print(capacity_mod.format_capacity(doc, args.format))
        return 0

    # slo
    import time as time_mod

    from tpukube.obs import slo as slo_mod

    def scrape(url: str) -> str:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read().decode()

    if args.snapshot:
        with open(args.snapshot) as f:
            text = f.read()
        result = slo_mod.evaluate(text)
    elif args.window > 0:
        first = scrape(args.url)
        time_mod.sleep(args.window)
        second = scrape(args.url)
        result = slo_mod.evaluate(second, prev_text=first,
                                  window_seconds=args.window)
    else:
        result = slo_mod.evaluate(scrape(args.url))
    print(json.dumps(result, indent=2, sort_keys=True))
    # exit non-zero when any SLO is burning at page rate, so the
    # command composes into scripts/CI gates
    burning = any(("page" in v["alerts"]) for v in result.values())
    return 1 if burning else 0


# -- tpukubectl --------------------------------------------------------------

def _fetch(server: str, path: str, token: Optional[str] = None,
           ssl_ctx=None) -> Any:
    req = urllib.request.Request(f"{server}{path}")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=10, context=ssl_ctx) as r:
        body = r.read()
    if path == "/metrics":
        return body.decode()
    return json.loads(body)


def _ctl_ssl_context(args: argparse.Namespace):
    """Client-side TLS for a secured extender (mirrors the server's two
    modes): --cacert pins the serving cert, --cert/--key presents the
    client certificate mTLS demands."""
    if args.key and not args.cert:
        raise SystemExit("--key requires --cert")
    if not (args.cacert or args.cert):
        return None
    import ssl

    ctx = ssl.create_default_context(cafile=args.cacert)
    if args.cert:
        if not args.key:
            raise SystemExit("--cert requires --key")
        ctx.load_cert_chain(args.cert, args.key)
    return ctx


def _render_topo(topo: dict[str, Any], out) -> None:
    """ASCII mesh occupancy map: one grid per z-plane per ICI slice,
    one cell per chip (coords are slice-local)."""
    glyph = {"free": ".", "allocated": "#", "reserved": "+", "unhealthy": "X"}
    # mesh_dims is null on a multi-slice cluster (coords are slice-local;
    # the per-slice headers below carry each slice's dims instead)
    mesh = (f"mesh {topo['mesh_dims']}  "
            if topo.get("mesh_dims") else "")
    print(
        f"{mesh}util {topo['utilization_percent']}%  "
        f"alloc {topo['chips_allocated']}/{topo['chips_total']}  "
        f"reserved {topo['chips_reserved_unbound']}  "
        f"unhealthy {topo['chips_unhealthy']}",
        file=out,
    )
    slices = topo.get("slices") or []
    multi = len(slices) > 1
    for sl in slices:
        dx, dy, dz = sl["mesh_dims"]
        grid = {}
        for node in topo["nodes"]:
            if node["slice"] != sl["id"]:
                continue
            for chip in node["chips"]:
                x, y, z = chip["coord"]
                grid[(x, y, z)] = glyph.get(chip["status"], "?")
        if multi:
            print(f"slice {sl['id']}  {sl['mesh_dims']}  "
                  f"util {sl['utilization_percent']}%", file=out)
        for z in range(dz):
            print(f"z={z}  ({glyph['free']} free {glyph['allocated']} alloc "
                  f"{glyph['reserved']} reserved {glyph['unhealthy']} "
                  f"unhealthy)", file=out)
            for y in range(dy):
                print("  " + " ".join(grid.get((x, y, z), " ")
                                      for x in range(dx)), file=out)
    # nodes whose inventory rode the static generation table instead of
    # runtime introspection: their HBM/core facts are guesses
    fallback = [n["name"] for n in topo["nodes"]
                if str(n.get("source", "")).startswith("table")]
    if fallback:
        print(f"table-fallback nodes: {', '.join(sorted(fallback))}",
              file=out)


def main_ctl(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpukubectl",
        description="inspect a live tpukube extender / replay decision traces",
    )
    p.add_argument("--server", default="http://127.0.0.1:12345",
                   help="extender base URL (https:// for a TLS extender)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="raw JSON output")
    # the client half of the extender's auth modes (main_extender):
    p.add_argument("--token-file", default=None, metavar="FILE",
                   help="bearer token file for an --auth-token-file extender")
    p.add_argument("--cacert", default=None, metavar="PEM",
                   help="CA bundle pinning the extender's serving cert")
    p.add_argument("--cert", default=None, metavar="PEM",
                   help="client certificate for an mTLS extender")
    p.add_argument("--key", default=None, metavar="PEM",
                   help="private key for --cert")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("topo", help="cluster topology + occupancy map")
    sub.add_parser("alloc", help="committed allocations")
    sub.add_parser("gangs", help="live gang reservations")
    sub.add_parser("metrics", help="prometheus metrics dump")
    rp = sub.add_parser("replay", help="replay a JSONL decision trace and "
                                       "report determinism divergences")
    rp.add_argument("trace_file")
    rp.add_argument("--config", default=None,
                    help="config YAML for the scratch scheduler")
    args = p.parse_args(argv)

    if args.cmd == "replay":
        from tpukube import trace as trace_mod

        cfg = load_config(yaml_path=args.config)
        events = trace_mod.load(args.trace_file)
        divergences = trace_mod.replay(events, config=cfg)
        if not divergences:
            print(f"replay ok: {len(events)} events, 0 divergences")
            return 0
        for d in divergences:
            print(d)
        return 1

    token = None
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    data = _fetch(args.server, {
        "topo": "/state/topology",
        "alloc": "/state/allocs",
        "gangs": "/state/gangs",
        "metrics": "/metrics",
    }[args.cmd], token=token, ssl_ctx=_ctl_ssl_context(args))
    if args.cmd == "metrics":
        sys.stdout.write(data)
        return 0
    if args.as_json:
        print(json.dumps(data, indent=2))
        return 0
    if args.cmd == "topo":
        _render_topo(data, sys.stdout)
    elif args.cmd == "alloc":
        if not data:
            print("no allocations")
        for a in data:
            print(f"{a['pod']:40s} {a['node']:16s} prio={a['priority']:<4d} "
                  f"{','.join(a['devices'])}")
    elif args.cmd == "gangs":
        if not data:
            print("no gang reservations")
        for g in data:
            state = "committed" if g["committed"] else "assembling"
            chips = sum(len(cs) for cs in g["slices"].values())
            where = "+".join(sorted(g["slices"]))
            gate = ""
            if g.get("victims_terminating"):
                gate = (f" [waiting on {g['victims_terminating']} "
                        f"terminating victim(s)]")
            elif g.get("victims_pending"):
                gate = (f" [{g['victims_pending']} preemption victim(s) "
                        f"planned, not yet evicted]")
            print(f"{g['namespace']}/{g['group']:24s} {state:10s} "
                  f"{g['members_bound']}/{g['min_member']} bound "
                  f"prio={g['priority']} chips={chips} in {where}{gate}")
    return 0


if __name__ == "__main__":  # python -m tpukube.cli <tool> ...
    tools = {
        "plugin": main_plugin,
        "extender": main_extender,
        "shard-worker": main_shard_worker,
        "sim": main_sim,
        "ctl": main_ctl,
        "obs": main_obs,
    }
    if len(sys.argv) < 2 or sys.argv[1] not in tools:
        print(f"usage: python -m tpukube.cli {{{'|'.join(tools)}}} ...",
              file=sys.stderr)
        raise SystemExit(2)
    raise SystemExit(tools[sys.argv[1]](sys.argv[2:]))

"""Apiserver channel — node annotations up, pod alloc intents down.

The reference's node agent writes its NodeInfo into a Node annotation
through the Kubernetes apiserver, and the extender's alloc results ride Pod
annotations back to the node (SURVEY.md §2 C8, §4.1-§4.3). This environment
has no cluster and no kubernetes client package, so the channel is a small
pluggable interface with two implementations:

  * :class:`FakeApiServer`  — in-memory, thread-safe; the sim's apiserver.
  * :class:`RestApiServer`  — real GET/PATCH against the Kubernetes REST
    API using the in-cluster serviceaccount token over urllib (merge-patch;
    the heavyweight kubernetes client package is deliberately NOT a
    dependency of this framework).

On top of the interface sit the two loops that close SURVEY §4's open ends:

  * :class:`NodeAnnotationSyncer` — tails the plugin's ``--annotation-out``
    file and PATCHes it onto the Node (the reference's "write NodeInfo
    annotation to apiserver" step, §4.1). Runs as the DaemonSet's syncer
    sidecar.
  * :class:`AllocIntentWatcher` — feeds bound pods' planned alloc
    annotations to the device plugin, so ``GetPreferredAllocation`` steers
    the kubelet onto exactly the chips the extender planned; when the
    kubelet allocates something else anyway, the plugin's divergence
    reporter (:func:`alloc_divergence_reporter`) writes the ACTUAL ids back
    onto the pod, and :class:`AllocReconcileLoop` folds them into the
    extender's ledger — truth flows both ways, so the gang's contiguity
    score and the container's real chips can never silently diverge.
"""

from __future__ import annotations

import json
import logging
import os
import copy
import queue
from collections import deque
import socket
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Optional

from tpukube.core import codec, retry

log = logging.getLogger("tpukube.apiserver")

# The node agent's report of what the kubelet ACTUALLY allocated, when it
# diverged from the planned ``tpu.qiniu.com/alloc`` annotation. Cleared by
# the extender's reconcile loop once folded into the ledger.
ANNO_ALLOC_ACTUAL = codec.ANNO_PREFIX + "alloc-actual"

# In-cluster serviceaccount defaults (mounted into every pod by kubelet).
SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiServerError(RuntimeError):
    """Apiserver request failure; ``code`` carries the HTTP status when the
    server answered (None for transport errors), so callers can branch on
    429 (PDB-blocked eviction) / 404 (already gone)."""

    def __init__(self, message: str, code: Optional[int] = None) -> None:
        super().__init__(message)
        self.code = code


def transient_api_error(exc: BaseException) -> bool:
    """The retry classifier every apiserver seam shares: transport
    errors (no HTTP code) and 5xx are transient; everything else —
    404, 409, 410, 429 — is a real answer the caller must handle, and
    retrying it would only mask the logic error."""
    if isinstance(exc, ApiServerError):
        return exc.code is None or exc.code >= 500
    return isinstance(exc, (OSError, ConnectionError))


def encode_alloc_actual(device_ids: list[str]) -> str:
    return json.dumps({"v": 1, "devices": sorted(device_ids)},
                      separators=(",", ":"))


def decode_alloc_actual(payload: str) -> list[str]:
    try:
        obj = json.loads(payload)
        if obj.get("v") != 1:
            raise ValueError(f"unsupported version {obj.get('v')!r}")
        return [str(d) for d in obj["devices"]]
    except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
        raise codec.CodecError(f"alloc-actual: {e}") from e


class FakeApiServer:
    """In-memory apiserver: Node/Pod metadata only, which is all this
    framework reads or writes. Thread-safe; the sim's source of truth."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._nodes: dict[str, dict[str, str]] = {}
        self._pods: dict[str, dict[str, Any]] = {}
        self.patch_log: list[tuple[str, str]] = []  # (kind, name) for tests
        # pod keys whose eviction a PodDisruptionBudget would deny (the
        # fake's stand-in for the real apiserver's 429): tests add keys
        # here to exercise the executor's requeue path
        self.pdb_blocked: set[str] = set()
        # pod keys that terminate GRACEFULLY on eviction: the accepted
        # eviction stamps deletionTimestamp and the object lingers until
        # finish_termination() — the real apiserver's behavior, and the
        # window the gang bind termination gate exists for
        self.graceful: set[str] = set()
        # live watch subscriptions (watch_pods): each holds an event queue
        self._watch_queues: list = []
        # the informer contract's versioning half: every pod mutation bumps
        # the resourceVersion and lands in a bounded history, so a watch
        # started FROM a list's version replays the events that raced into
        # the list->watch gap instead of silently dropping them (exactly
        # what the REST path's resourceVersion parameter does)
        self._rv = 0
        self._history: deque = deque(maxlen=4096)
        # node-watch half (NodeTopologyRefreshLoop's informer), same
        # versioning contract as pods but its own stream
        self._node_rv = 0
        self._node_history: deque = deque(maxlen=4096)
        self._node_watch_queues: list = []

    def _notify_node(self, etype: str, name: str) -> None:
        """Fan a Node event out to node watchers (under self._lock)."""
        self._node_rv += 1
        obj = {"metadata": {"name": name,
                            "annotations": dict(self._nodes.get(name, {}))}}
        self._node_history.append((self._node_rv, etype, obj))
        for q in self._node_watch_queues:
            q.put((etype, copy.deepcopy(obj)))

    def _notify(self, etype: str, pod: dict[str, Any]) -> None:
        """Fan a pod event out to live watchers (call under self._lock).
        Each watcher gets its OWN copy (a consumer mutating its event
        must not corrupt siblings or the replay history)."""
        self._rv += 1
        snap = copy.deepcopy(pod)
        self._history.append((self._rv, etype, snap))
        for q in self._watch_queues:
            q.put((etype, copy.deepcopy(snap)))

    # -- nodes -------------------------------------------------------------
    def patch_node_annotations(
        self, name: str, annotations: dict[str, str]
    ) -> None:
        with self._lock:
            etype = "MODIFIED" if name in self._nodes else "ADDED"
            self._nodes.setdefault(name, {}).update(annotations)
            self.patch_log.append(("node", name))
            self._notify_node(etype, name)

    def get_node_annotations(self, name: str) -> dict[str, str]:
        with self._lock:
            return dict(self._nodes.get(name, {}))

    def node_names(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def list_nodes(self) -> list[dict[str, Any]]:
        return self.node_objects()

    def node_objects(self) -> list[dict[str, Any]]:
        """Node list in the webhook wire shape (the sim's kube-scheduler
        builds ExtenderArgs from this)."""
        with self._lock:
            return [
                {"metadata": {"name": n, "annotations": dict(a)}}
                for n, a in sorted(self._nodes.items())
            ]

    # -- pods --------------------------------------------------------------
    def upsert_pod(self, pod: dict[str, Any]) -> None:
        meta = pod["metadata"]
        key = f"{meta.get('namespace', 'default')}/{meta['name']}"
        with self._lock:
            etype = "MODIFIED" if key in self._pods else "ADDED"
            self._pods[key] = pod
            self._notify(etype, pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop(f"{namespace}/{name}", None)
            if pod is not None:
                self._notify("DELETED", pod)

    def evict_pod(
        self, namespace: str, name: str, dry_run: bool = False
    ) -> bool:
        """Eviction-subresource semantics: True once the eviction is
        accepted (or the pod is already gone), False when a
        PodDisruptionBudget blocks it — the same contract RestApiServer
        derives from 2xx/404 vs 429. ``dry_run`` only answers the PDB
        question (the real API's dryRun=All). Keys in ``graceful`` get a
        deletionTimestamp and linger until finish_termination(); others
        delete instantly (grace 0)."""
        key = f"{namespace}/{name}"
        with self._lock:
            if key in self.pdb_blocked:
                return False
            if dry_run:
                return True
            if key in self.graceful:
                pod = self._pods.get(key)
                if pod is not None:
                    pod["metadata"].setdefault(
                        "deletionTimestamp", "2026-01-01T00:00:00Z"
                    )
                    self._notify("MODIFIED", pod)
            else:
                pod = self._pods.pop(key, None)
                if pod is not None:
                    self._notify("DELETED", pod)
            self.patch_log.append(("evict", key))
        return True

    def finish_termination(self, namespace: str, name: str) -> None:
        """A graceful pod's containers finally stopped: the object goes
        away (kubelet finishing the eviction the subresource started)."""
        with self._lock:
            pod = self._pods.pop(f"{namespace}/{name}", None)
            if pod is not None:
                self._notify("DELETED", pod)

    def get_pod(self, namespace: str, name: str) -> Optional[dict[str, Any]]:
        with self._lock:
            return self._pods.get(f"{namespace}/{name}")

    def watch_pods(self, node_name: Optional[str] = None,
                   timeout_seconds: int = 300,
                   handle_box: Optional[list] = None,
                   resource_version: Optional[str] = None):
        """The fake's watch half of the informer contract: yields
        (event_type, pod) for every mutation after ``resource_version``
        (a list_pods_with_rv result — events that raced into the
        list->watch gap are REPLAYED from the bounded history, exactly
        like the REST path's resourceVersion parameter) or, without a
        version, after this call. Subscription and replay snapshot happen
        atomically under the store lock — not at the generator's first
        next() — so no event can slip between them. Honors the
        spec.nodeName field selector. The handle placed in ``handle_box``
        exposes close() (enqueues a poison pill), so a loop's stop()
        unblocks a quiet watch exactly as it does the REST stream."""
        def pod_filter(pod: dict[str, Any]) -> bool:
            if node_name is None:
                return True
            return (pod.get("spec") or {}).get("nodeName") == node_name

        return self._subscribe_watch(
            self._watch_queues, self._history, resource_version,
            handle_box, timeout_seconds, pod_filter,
        )

    def _subscribe_watch(self, queues: list, history: deque,
                         resource_version: Optional[str],
                         handle_box: Optional[list],
                         timeout_seconds: int, keep) -> Any:
        """Shared machinery of the pod and node watch halves: atomic
        replay-from-history + subscription under the store lock, a
        close() handle (poison pill), the server-timeout deadline, and
        unsubscription when the generator ends."""
        q: queue.SimpleQueue = queue.SimpleQueue()

        class _Handle:
            def close(self) -> None:
                q.put(None)

        try:
            since = int(resource_version) if resource_version else None
        except ValueError:
            since = None
        with self._lock:
            if since is not None:
                if history and history[0][0] > since + 1:
                    # events between `since` and the oldest retained entry
                    # were evicted from the bounded history: replaying
                    # would silently skip them. The real apiserver answers
                    # 410 Gone; the informer's reconnect then resyncs with
                    # a fresh list — same contract here.
                    raise ApiServerError(
                        f"resourceVersion {since} too old "
                        f"(history starts at {history[0][0]})", code=410,
                    )
                for rv, etype, obj in history:
                    if rv > since:
                        q.put((etype, copy.deepcopy(obj)))
            queues.append(q)
        if handle_box is not None:
            handle_box.append(_Handle())

        def _events():
            try:
                deadline = time.monotonic() + timeout_seconds
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return  # server timeout; caller reconnects
                    try:
                        ev = q.get(timeout=remaining)
                    except queue.Empty:
                        return
                    if ev is None:
                        return  # closed via the handle
                    etype, obj = ev
                    if keep(obj):
                        yield etype, obj
            finally:
                with self._lock:
                    if q in queues:
                        queues.remove(q)

        return _events()

    def bind_pod(
        self, namespace: str, name: str, node: str,
        annotations: Optional[dict[str, str]] = None,
    ) -> None:
        """The Binding-subresource equivalent: conflict check FIRST (a pod
        bound elsewhere must not be touched at all — not even its
        annotations), then annotations (the pod is still Pending —
        retry-safe), then nodeName; 404s like the real apiserver. Already
        bound to the SAME node = idempotent-retry success (mirroring
        RestApiServer.bind_pod)."""
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                raise ApiServerError(f"pod {key} not found", code=404)
            spec = pod.setdefault("spec", {})
            bound_to = spec.get("nodeName")
            if bound_to and bound_to != node:
                raise ApiServerError(
                    f"pod {key} is already bound to {bound_to!r}, "
                    f"not {node!r}", code=409,
                )
            if annotations:
                pod["metadata"].setdefault("annotations", {}).update(
                    annotations
                )
            spec["nodeName"] = node
            self.patch_log.append(("bind", key))
            self._notify("MODIFIED", pod)

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: dict[str, Optional[str]]
    ) -> None:
        """Merge-patch semantics: a None value deletes the key (exactly how
        a JSON merge-patch null behaves on the real apiserver)."""
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                raise ApiServerError(f"pod {key} not found")
            annos = pod["metadata"].setdefault("annotations", {})
            for k, v in annotations.items():
                if v is None:
                    annos.pop(k, None)
                else:
                    annos[k] = v
            self.patch_log.append(("pod", key))
            self._notify("MODIFIED", pod)

    def list_pods(self, node_name: Optional[str] = None) -> list[dict[str, Any]]:
        with self._lock:
            out = []
            for pod in self._pods.values():
                if (node_name is None
                        or pod.get("spec", {}).get("nodeName") == node_name):
                    out.append(pod)
            return out

    def list_pods_with_rv(
        self, node_name: Optional[str] = None
    ) -> tuple[list[dict[str, Any]], str]:
        """(pods, resourceVersion) — list half of the informer contract
        (mirrors RestApiServer): watch from the returned version and no
        event between the list and the watch is lost."""
        with self._lock:
            out = [
                pod for pod in self._pods.values()
                if (node_name is None
                    or pod.get("spec", {}).get("nodeName") == node_name)
            ]
            return out, str(self._rv)

    def list_nodes_with_rv(self) -> tuple[list[dict[str, Any]], str]:
        """(nodes, resourceVersion) — the node informer's list half."""
        with self._lock:
            out = [
                {"metadata": {"name": n, "annotations": dict(a)}}
                for n, a in sorted(self._nodes.items())
            ]
            return out, str(self._node_rv)

    def watch_nodes(self, node_name: Optional[str] = None,
                    timeout_seconds: int = 300,
                    handle_box: Optional[list] = None,
                    resource_version: Optional[str] = None):
        """Node-object watch, same informer contract as watch_pods
        (``node_name`` accepted for signature symmetry; Node watches have
        no field selector)."""
        return self._subscribe_watch(
            self._node_watch_queues, self._node_history, resource_version,
            handle_box, timeout_seconds, lambda obj: True,
        )


class RestApiServer:
    """The same surface over the Kubernetes REST API, with no client
    library: merge-patches and field-selector GETs via urllib, the
    in-cluster serviceaccount token, and the cluster CA.

    Built for the DaemonSet sidecar (NodeAnnotationSyncer) and the node
    agent (AllocIntentWatcher); exercised in tests against a local HTTP
    stand-in since no cluster exists in this environment.
    """

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        token_path: Optional[str] = None,
        ca_path: Optional[str] = None,
        timeout: float = 10.0,
        retrier: Optional[retry.Retrier] = None,
        circuit: Optional[retry.CircuitBreaker] = None,
    ) -> None:
        """``retrier``/``circuit`` route every unary request through
        the unified policy (core/retry.py): transient failures
        (transport errors, 5xx) retry with jittered backoff and feed
        the breaker; while the breaker is open, requests fail fast as
        ApiServerError instead of stacking timeouts. Both default None
        — the legacy single-attempt behavior. Watch STREAMS are not
        retried here; the informer loops own reconnects (with their
        own capped backoff)."""
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise ApiServerError(
                    "no apiserver URL: pass base_url or run in-cluster"
                )
            base_url = f"https://{host}:{port}"
        self._base = base_url.rstrip("/")
        if token is None:
            path = token_path or os.path.join(SA_DIR, "token")
            if os.path.exists(path):
                with open(path) as f:
                    token = f.read().strip()
        self._token = token
        self._timeout = timeout
        if ca_path is None:
            default_ca = os.path.join(SA_DIR, "ca.crt")
            ca_path = default_ca if os.path.exists(default_ca) else None
        if self._base.startswith("https"):
            self._ssl: Optional[ssl.SSLContext] = ssl.create_default_context(
                cafile=ca_path
            )
        else:
            self._ssl = None
        self.retrier = retrier
        self.circuit = circuit
        if retrier is not None and retrier.policy.attempt_timeout > 0:
            # the policy's per-attempt deadline caps the transport
            # timeout — a retried request must not spend its whole
            # overall deadline waiting out one hung attempt
            self._timeout = min(self._timeout,
                                retrier.policy.attempt_timeout)

    def _authed_request(
        self, method: str, path: str, data: Optional[bytes] = None,
        content_type: Optional[str] = None,
    ) -> urllib.request.Request:
        """One place for bearer auth + headers — the long-lived watch
        path and the unary path must never drift apart."""
        headers = {"Accept": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        if content_type is not None:
            headers["Content-Type"] = content_type
        return urllib.request.Request(
            self._base + path, data=data, headers=headers, method=method
        )

    def _request_once(
        self, method: str, path: str, body: Optional[dict] = None,
        content_type: str = "application/merge-patch+json",
    ) -> Any:
        data = ctype = None
        if body is not None:
            data = json.dumps(body).encode()
            ctype = content_type
        req = self._authed_request(method, path, data=data,
                                   content_type=ctype)
        try:
            with urllib.request.urlopen(
                req, timeout=self._timeout, context=self._ssl
            ) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            raise ApiServerError(
                f"{method} {path}: HTTP {e.code} {e.read()[:200]!r}",
                code=e.code,
            ) from e
        except urllib.error.URLError as e:
            raise ApiServerError(f"{method} {path}: {e.reason}") from e
        return json.loads(payload) if payload else None

    def _request(
        self, method: str, path: str, body: Optional[dict] = None,
        content_type: str = "application/merge-patch+json",
    ) -> Any:
        """One unary request through the unified retry/circuit layer
        (when wired): each attempt consults the breaker, transient
        outcomes feed it, and an open circuit fails fast. Retrying a
        lost-response write is safe by the surface's own contract —
        merge-patches re-apply, bind_pod treats already-bound-to-us as
        success, evict_pod treats 404 as done."""
        if self.retrier is None and self.circuit is None:
            return self._request_once(method, path, body, content_type)

        def attempt() -> Any:
            if self.circuit is not None:
                self.circuit.before_call()  # CircuitOpenError when open
            try:
                out = self._request_once(method, path, body, content_type)
            except retry.CircuitOpenError:
                raise
            except Exception as e:
                if self.circuit is not None:
                    if transient_api_error(e):
                        self.circuit.on_failure()
                    else:
                        # the server ANSWERED (404/409/429/...): the
                        # channel is healthy, only the request lost
                        self.circuit.on_success()
                raise
            except BaseException:
                # interrupted, not answered: release any half-open
                # probe slot so the breaker cannot wedge half-open
                if self.circuit is not None:
                    self.circuit.abort_probe()
                raise
            if self.circuit is not None:
                self.circuit.on_success()
            return out

        try:
            if self.retrier is not None:
                return self.retrier.call(attempt)
            return attempt()
        except retry.CircuitOpenError as e:
            # preserve the surface's error contract: callers catch
            # ApiServerError; a fast-failed request is a transport-
            # level failure with no HTTP code
            raise ApiServerError(f"{method} {path}: {e}") from e

    # -- interface ---------------------------------------------------------
    def patch_node_annotations(
        self, name: str, annotations: dict[str, str]
    ) -> None:
        self._request(
            "PATCH", f"/api/v1/nodes/{name}",
            {"metadata": {"annotations": annotations}},
        )

    def get_node_annotations(self, name: str) -> dict[str, str]:
        obj = self._request("GET", f"/api/v1/nodes/{name}")
        return dict(obj.get("metadata", {}).get("annotations", {}) or {})

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: dict[str, Optional[str]]
    ) -> None:
        self._request(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}",
            {"metadata": {"annotations": annotations}},
        )

    # chunked LISTs: big enough that small clusters stay one request,
    # small enough that a v5p-128-scale cluster's poll never materializes
    # thousands of objects in one apiserver response
    LIST_PAGE_LIMIT = 500

    def _list_paginated(
        self, base: str
    ) -> tuple[list[dict[str, Any]], str]:
        """Follow the apiserver's limit/continue protocol; returns the
        concatenation of all pages plus the list's resourceVersion (the
        consistent point a watch should start from). ``base`` already
        carries its query string (limit, selectors)."""
        items: list[dict[str, Any]] = []
        cont = rv = ""
        while True:
            path = base + (f"&continue={urllib.parse.quote(cont)}" if cont
                           else "")
            obj = self._request("GET", path)
            items.extend(obj.get("items", []) or [])
            meta = obj.get("metadata") or {}
            rv = meta.get("resourceVersion") or rv
            cont = meta.get("continue") or ""
            if not cont:
                return items, rv

    def _pods_base(self, node_name: Optional[str]) -> str:
        base = f"/api/v1/pods?limit={self.LIST_PAGE_LIMIT}"
        if node_name is not None:
            base += f"&fieldSelector=spec.nodeName%3D{node_name}"
        return base

    def list_pods(self, node_name: Optional[str] = None) -> list[dict[str, Any]]:
        """Pod list, paginated so reconcile-loop polls on large clusters
        ask for bounded chunks instead of one giant LIST."""
        return self._list_paginated(self._pods_base(node_name))[0]

    def list_pods_with_rv(
        self, node_name: Optional[str] = None
    ) -> tuple[list[dict[str, Any]], str]:
        """(pods, resourceVersion) — the informer contract's list half:
        watch from the returned version and no event between the list
        and the watch is lost."""
        return self._list_paginated(self._pods_base(node_name))

    def list_nodes(self) -> list[dict[str, Any]]:
        """Node list, paginated like list_pods (startup rebuild reads
        every node's topology annotation)."""
        return self._list_paginated(
            f"/api/v1/nodes?limit={self.LIST_PAGE_LIMIT}"
        )[0]

    def list_nodes_with_rv(self) -> tuple[list[dict[str, Any]], str]:
        """(nodes, resourceVersion) — the node informer's list half."""
        return self._list_paginated(
            f"/api/v1/nodes?limit={self.LIST_PAGE_LIMIT}"
        )

    def watch_nodes(self, node_name: Optional[str] = None,
                    timeout_seconds: int = 300,
                    handle_box: Optional[list] = None,
                    resource_version: Optional[str] = None):
        """Node-object watch stream (NodeTopologyRefreshLoop's informer
        transport): a health re-annotation reaches a nodeCacheCapable
        extender within milliseconds instead of a poll interval — the
        §4.4 fault path's end-to-end latency. ``node_name`` accepted for
        signature symmetry with watch_pods; Node watches have no field
        selector."""
        path = f"/api/v1/nodes?watch=1&timeoutSeconds={timeout_seconds}"
        yield from self._watch_stream(
            "nodes", path, timeout_seconds, handle_box, resource_version
        )

    def watch_pods(self, node_name: Optional[str] = None,
                   timeout_seconds: int = 300,
                   handle_box: Optional[list] = None,
                   resource_version: Optional[str] = None):
        """One watch request (the informer pattern's transport): yields
        (event_type, pod) as the apiserver streams them, ending when the
        server closes the stream at ``timeoutSeconds`` — callers loop to
        reconnect, resyncing with list_pods in between. This is what
        makes intent steering real on a live cluster: a 5s LIST poll
        loses the race against the kubelet's Allocate; a watch delivers
        the bound pod's alloc annotation within milliseconds."""
        path = f"/api/v1/pods?watch=1&timeoutSeconds={timeout_seconds}"
        if node_name is not None:
            path += f"&fieldSelector=spec.nodeName%3D{node_name}"
        yield from self._watch_stream(
            "pods", path, timeout_seconds, handle_box, resource_version
        )

    def _watch_stream(self, what: str, path: str, timeout_seconds: int,
                      handle_box: Optional[list],
                      resource_version: Optional[str]):
        """Shared transport of the pod and node watches: one chunked GET,
        one {"type","object"} event per line, ending when the server
        closes at timeoutSeconds."""
        if resource_version:
            # the informer contract: watching FROM the list's version
            # closes the list->watch gap (without it, a watch starts at
            # "most recent" and events in the gap are silently lost); a
            # too-old version gets HTTP 410, which the caller's reconnect
            # resolves with a fresh list
            path += (
                f"&resourceVersion={urllib.parse.quote(resource_version)}"
            )
        req = self._authed_request("GET", path)
        try:
            with urllib.request.urlopen(
                req, timeout=timeout_seconds + 30, context=self._ssl
            ) as r:
                if handle_box is not None:
                    # the caller's stop() closes this to interrupt a
                    # blocked read (the stream is otherwise uninterruptible
                    # for up to the socket timeout)
                    handle_box.append(r)
                for line in r:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError as e:
                        log.warning("watch %s: unparsable event line: %s",
                                    what, e)
                        continue
                    yield str(ev.get("type", "")), dict(ev.get("object") or {})
        except urllib.error.HTTPError as e:
            raise ApiServerError(
                f"watch {what}: HTTP {e.code}", code=e.code
            ) from e
        except urllib.error.URLError as e:
            raise ApiServerError(f"watch {what}: {e.reason}") from e

    def get_pod(self, namespace: str, name: str) -> Optional[dict[str, Any]]:
        """One pod object, or None when it does not exist (404)."""
        try:
            return self._request(
                "GET", f"/api/v1/namespaces/{namespace}/pods/{name}"
            )
        except ApiServerError as e:
            if e.code == 404:
                return None
            raise

    def delete_pod(self, namespace: str, name: str) -> None:
        """Hard delete (no PDB check). The eviction executor uses
        :meth:`evict_pod`; this exists for operator tooling parity with
        FakeApiServer."""
        try:
            self._request(
                "DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}"
            )
        except ApiServerError as e:
            if e.code != 404:  # already gone is success
                raise

    def bind_pod(
        self, namespace: str, name: str, node: str,
        annotations: Optional[dict[str, str]] = None,
    ) -> None:
        """POST the Binding subresource (what kube-scheduler does for
        non-extender pods). With bindVerb delegated to the extender, THIS
        is what actually starts the pod on its node.

        Ordering is load-bearing: the alloc annotation is PATCHed FIRST,
        while the pod is still Pending — so the node agent's intent
        watcher can see the plan before the kubelet's Allocate, and a
        partial failure always leaves the pod unbound (safe to retry).
        A 409 on the Binding POST means the pod is already bound; that is
        idempotent success ONLY if it is bound to the node we asked for
        (our earlier retry landed) — bound elsewhere is a real conflict
        (e.g. a re-planned bind after an extender restart) that must
        surface. The bound-elsewhere check runs BEFORE the annotation
        PATCH, so a conflicting pod running on another host is never
        touched at all — not even its annotations."""
        current = self.get_pod(namespace, name)
        bound_to = ((current or {}).get("spec") or {}).get("nodeName")
        if bound_to and bound_to != node:
            raise ApiServerError(
                f"pod {namespace}/{name} is already bound to "
                f"{bound_to!r}, not {node!r}", code=409,
            )
        if annotations:
            self.patch_pod_annotations(namespace, name, dict(annotations))
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node},
        }
        try:
            self._request(
                "POST",
                f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
                body, content_type="application/json",
            )
        except ApiServerError as e:
            if e.code != 409:
                raise
            # a binding raced in between our check and POST: success only
            # if it targets our node
            pod = self.get_pod(namespace, name)
            bound_to = ((pod or {}).get("spec") or {}).get("nodeName")
            if bound_to != node:
                raise ApiServerError(
                    f"pod {namespace}/{name} is already bound to "
                    f"{bound_to!r}, not {node!r}", code=409,
                ) from e

    def evict_pod(
        self, namespace: str, name: str, dry_run: bool = False
    ) -> bool:
        """POST the policy/v1 Eviction subresource — the polite way to
        delete a preemption victim, because it lets the apiserver enforce
        PodDisruptionBudgets. Returns True once the eviction is accepted
        (2xx, or 404 = already deleted), False when a PDB blocks it right
        now (HTTP 429: retry later, exactly what the executor's requeue
        does). ``dry_run`` sends deleteOptions.dryRun=["All"] — the PDB
        answer without starting a termination (the extender's preemption
        precheck)."""
        body: dict[str, Any] = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        if dry_run:
            body["deleteOptions"] = {"dryRun": ["All"]}
        try:
            self._request(
                "POST",
                f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
                body, content_type="application/json",
            )
        except ApiServerError as e:
            if e.code == 429:
                return False
            if e.code == 404:
                return True
            raise
        return True


class _PollLoop:
    """start/stop/check_once scaffolding shared by the sync loops (the same
    deterministic-step pattern as HealthWatcher/KubeletSessionWatcher)."""

    def __init__(self, poll_seconds: float, name: str) -> None:
        self._poll = poll_seconds
        self._name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self) -> bool:  # pragma: no cover - overridden
        raise NotImplementedError

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError(f"{self._name} already started")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=self._name
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_once()
            except Exception:
                log.exception("%s poll failed", self._name)


class NodeAnnotationSyncer(_PollLoop):
    """Applies the plugin's node-annotation file to the Node object.

    ``tpukube-plugin --annotation-out FILE`` writes the node-topology
    annotation JSON; this loop (the DaemonSet's sidecar, sharing the
    /var/run/tpukube mount) PATCHes it through the apiserver whenever the
    content changes — including health-fault re-annotations, which is how
    the extender learns about dead chips on a real cluster."""

    def __init__(
        self, api, node_name: str, path: str, poll_seconds: float = 5.0
    ) -> None:
        super().__init__(poll_seconds, "tpukube-annotation-sync")
        self._api = api
        self._node = node_name
        self._path = path
        self._last_applied: Optional[str] = None
        self.syncs = 0  # applied patches (tests/metrics)

    def check_once(self) -> bool:
        """One poll; True if a patch was applied."""
        try:
            with open(self._path) as f:
                raw = f.read().strip()
        except OSError:
            return False  # agent not up yet
        if not raw or raw == self._last_applied:
            return False
        try:
            annotations = json.loads(raw)
        except json.JSONDecodeError as e:
            log.warning("annotation file %s unparsable: %s", self._path, e)
            return False
        if not isinstance(annotations, dict):
            log.warning("annotation file %s: not a JSON object", self._path)
            return False
        self._api.patch_node_annotations(self._node, annotations)
        # commit only after the PATCH succeeded, so a failed apply retries
        self._last_applied = raw
        self.syncs += 1
        log.info("synced node annotation for %s (%d bytes)",
                 self._node, len(raw))
        return True


class _ResyncNeeded(Exception):
    """Raised by a _WatchLoop subclass's event/resync handler when it
    left work unfinished (e.g. a failed ack PATCH): the loop closes the
    stream, backs off one poll interval, and resyncs — restoring the
    poll mode's convergence bound instead of waiting out the watch
    stream's server timeout (~300s)."""


class _WatchLoop(_PollLoop):
    """Informer-pattern scaffolding shared by the watching loops:
    list-resync at every (re)connect, then a watch FROM the list's
    resourceVersion, with the poll loop as the no-watch fallback.
    Subclasses implement ``_resync()`` (full list reconciliation,
    returning ``(changed, resourceVersion)``) and
    ``_apply_watch_event(etype, obj)``; ``watch_method`` names the api's
    stream ("watch_pods" for the pod loops, "watch_nodes" for the node
    topology loop)."""

    def __init__(
        self, name: str, api, node_name: Optional[str],
        poll_seconds: float, use_watch: bool,
        watch_method: str = "watch_pods",
    ) -> None:
        super().__init__(poll_seconds, name)
        self._api = api
        self._node = node_name
        self._watch_method = watch_method
        self._use_watch = use_watch and hasattr(api, watch_method)
        self._box_supported = True  # False after a handle_box TypeError
        # Stream liveness, NOT thread liveness: a watch thread is alive
        # through reconnect backoff and list-resync windows where DELETED
        # events are silently missed (ADVICE round 5 low). True only
        # between a successful (resync, stream open) and the stream's
        # end/failure; last_event_time (wall clock) stamps the stream
        # connect and every delivered event — exported on /statusz.
        self._stream_connected = False
        self.last_event_time: Optional[float] = None
        # optional EventJournal (obs/events.py), wired by the daemon
        # main: a WatchReconnected event per stream re-establishment —
        # frequent reconnects mean events are being missed in backoff
        # windows, the first thing to check when releases lag
        self.journal = None
        self._connects = 0
        self.reconnects = 0
        # reconnect pacing: one poll interval after the FIRST failure,
        # then jittered exponential growth up to 16x — a down apiserver
        # (or a 410-Gone storm) must not be hammered at a fixed cadence
        # by every informer in the fleet at once. Reset the moment a
        # stream actually (re)connects.
        self._reconnect_backoff = retry.Backoff(
            base=poll_seconds, cap=poll_seconds * 16, jitter=0.5,
        )

    def _resync(self) -> tuple[bool, Optional[str]]:  # pragma: no cover
        raise NotImplementedError

    def _apply_watch_event(
        self, etype: str, pod: dict[str, Any]
    ) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def check_once(self) -> bool:
        """One full resync; True if anything changed."""
        return self._resync()[0]

    def _needs_resync(self) -> bool:
        """Subclass hook, consulted after each watch-mode resync: True
        when the resync left work unfinished (retry after one poll
        interval instead of entering the watch)."""
        return False

    def stream_connected(self) -> bool:
        """True while the watch stream is actually open and delivering —
        NOT during reconnect backoff or a failed resync."""
        return self._stream_connected

    def watch_status(self) -> dict[str, Any]:
        """Liveness document for /statusz."""
        return {
            "name": self._name,
            "mode": "watch" if self._use_watch else "poll",
            "thread_alive": (self._thread is not None
                             and self._thread.is_alive()),
            "stream_connected": self._stream_connected,
            "last_event_ts": self.last_event_time,
            "reconnects": self.reconnects,
            # consecutive reconnect failures driving the current
            # backoff (0 while healthy): non-zero here plus a stale
            # last_event_ts is "the apiserver is down", not "quiet"
            "reconnect_failures": self._reconnect_backoff.failures,
        }

    def _list_pods_rv(
        self, node_name: Optional[str] = None
    ) -> tuple[list[dict[str, Any]], Optional[str]]:
        """The informer contract's list half, with the plain-list
        fallback for apis without resourceVersions (one definition for
        every pod loop — watching then starts at 'now', and the
        periodic resync covers the gap)."""
        if hasattr(self._api, "list_pods_with_rv"):
            return self._api.list_pods_with_rv(node_name)
        return self._api.list_pods(node_name), None

    def _run(self) -> None:
        if not self._use_watch:
            return super()._run()
        while not self._stop.is_set():
            box: list = []
            self._stream_box = box
            delay = self._poll
            stream_t0: Optional[float] = None
            try:
                # resync at every (re)connect, then watch FROM the list's
                # resourceVersion — no event in the list->watch gap is lost
                _, rv = self._resync()
                if self._needs_resync():
                    raise _ResyncNeeded
                watch = getattr(self._api, self._watch_method)
                try:
                    gen = watch(
                        self._node, handle_box=box, resource_version=rv
                    )
                except TypeError:  # test stubs without the full signature
                    self._box_supported = False
                    gen = watch(self._node)
                # connected from here until the stream ends or fails:
                # the resync landed and the watch is (about to be) open —
                # the REST transport dials on first iteration, which
                # happens immediately below. The reconnect backoff does
                # NOT reset here: the REST generator is lazy, so nothing
                # has actually dialed yet — a dial that fails every lap
                # must keep escalating. Reset happens on demonstrated
                # liveness: a delivered event, a clean stream end, or a
                # stream that survived at least one poll interval.
                self._stream_connected = True
                self.last_event_time = time.time()
                stream_t0 = time.monotonic()
                self._connects += 1
                if self._connects > 1:
                    self.reconnects += 1
                    if self.journal is not None:
                        try:
                            self.journal.emit(
                                "WatchReconnected",
                                obj=f"watch/{self._name}",
                                message=f"stream re-established "
                                        f"(reconnect #{self.reconnects}); "
                                        f"resync covered the gap",
                            )
                        except Exception:
                            log.exception("event emit failed: "
                                          "WatchReconnected")
                try:
                    for etype, pod in gen:
                        if self._stop.is_set():
                            return
                        self.last_event_time = time.time()
                        if self._reconnect_backoff.failures:
                            # the stream is demonstrably delivering
                            self._reconnect_backoff.reset()
                        self._apply_watch_event(etype, pod)
                finally:
                    self._stream_connected = False
                # clean end at the server timeout: the dial worked
                self._reconnect_backoff.reset()
            except _ResyncNeeded:
                # expected control flow, not a failure: back off one
                # poll and resync (bounded retry for unfinished work)
                log.info("%s: resync forced (pending retry)", self._name)
            except Exception:
                if self._stop.is_set():
                    return  # stop() closed the stream under us
                # consecutive failures (down apiserver, 410 storms)
                # escalate the reconnect delay instead of replaying a
                # fixed-cadence hammer; the list-resync at the next
                # (re)connect covers every event missed meanwhile
                if (stream_t0 is not None
                        and time.monotonic() - stream_t0 > self._poll):
                    # an idle-but-open stream that lived at least a poll
                    # interval before dying is a FRESH outage, not a
                    # continuation of a dial-failure streak
                    self._reconnect_backoff.reset()
                delay = self._reconnect_backoff.next()
                log.exception(
                    "%s watch failed (consecutive failure %d); "
                    "reconnecting in %.1fs", self._name,
                    self._reconnect_backoff.failures, delay,
                )
            self._stop.wait(delay)  # backoff, then reconnect

    def stop(self) -> None:
        self._stop.set()
        # a watch thread blocked mid-read can't see the stop event, and
        # close() alone does NOT wake a thread parked in recv() — only a
        # socket shutdown does; then close for good measure. The stream
        # handle lands in the box at the thread's FIRST read, so grace a
        # moment for a connection that is mid-handshake (otherwise the
        # shutdown below has nothing to act on and join stalls).
        deadline = time.monotonic() + 2.0
        while (self._use_watch and self._box_supported
               and not (getattr(self, "_stream_box", None))
               and self._thread is not None and self._thread.is_alive()
               and time.monotonic() < deadline):
            time.sleep(0.02)
        for r in getattr(self, "_stream_box", []) or []:
            try:
                sock = getattr(getattr(r, "fp", None), "raw", None)
                sock = getattr(sock, "_sock", None)
                if sock is not None:
                    sock.shutdown(socket.SHUT_RDWR)
            # tpukube: allow(exception-hygiene) best-effort unblock of the watch thread at stop(); the handle may already be half-closed by the peer
            except Exception:
                pass
            try:
                r.close()
            # tpukube: allow(exception-hygiene) second best-effort close on the same dying handle; nothing to surface at shutdown
            except Exception:
                pass
        super().stop()


class AllocIntentWatcher(_WatchLoop):
    """Feeds the extender's planned allocations to the device plugin.

    Watches pods bound to this node; every ``tpu.qiniu.com/alloc``
    annotation becomes an intent in the plugin's :class:`~tpukube.plugin.
    server.AllocIntentCache`, which GetPreferredAllocation serves back to
    the kubelet — closing the loop the reference closes with its annotation
    channel (SURVEY §4.3): the kubelet's id choice converges on the chips
    the gang's contiguity score was computed for."""

    def __init__(
        self, api, node_name: str, server, poll_seconds: float = 5.0,
        use_watch: bool = True,
    ) -> None:
        # watch mode (the informer pattern): intents land within ms of
        # the bind instead of a poll interval later — steering would
        # otherwise routinely lose the race against the kubelet's
        # Allocate on a real cluster. Both apiserver implementations
        # (REST and fake) speak the watch protocol; poll mode remains
        # for deterministic stepping (check_once) in tests/sim.
        super().__init__("tpukube-alloc-intents", api, node_name,
                         poll_seconds, use_watch)
        self._server = server
        self.watch_events = 0  # processed watch events (tests/metrics)

    @staticmethod
    def _intent_of(pod: dict[str, Any]):
        """(pod_key, device_ids) from a pod's alloc annotation, or None."""
        meta = pod.get("metadata", {})
        payload = (meta.get("annotations") or {}).get(codec.ANNO_ALLOC)
        if not payload:
            return None
        try:
            alloc = codec.decode_alloc(payload)
        except codec.CodecError as e:
            log.warning("pod %s: bad alloc annotation: %s",
                        meta.get("name"), e)
            return None
        return alloc.pod_key, list(alloc.device_ids)

    def _resync(self) -> tuple[bool, Optional[str]]:
        """Full list resync; returns (changed, resourceVersion) — the
        version is the watch's safe starting point (None when the api
        doesn't expose it)."""
        pods, rv = self._list_pods_rv(self._node)
        intents: dict[str, list[str]] = {}
        for pod in pods:
            entry = self._intent_of(pod)
            if entry is not None:
                intents[entry[0]] = entry[1]
        return self._server.intents.sync(intents), rv

    def _apply_watch_event(self, etype: str, pod: dict[str, Any]) -> None:
        if etype == "DELETED":
            # the pod key needs no annotation decode (the final object's
            # annotation may be corrupt; the intent must still die NOW,
            # not at the next reconnect resync)
            meta = pod.get("metadata") or {}
            name = meta.get("name")
            if name:
                self.watch_events += 1
                self._server.intents.remove(
                    f"{meta.get('namespace', 'default')}/{name}"
                )
            return
        entry = self._intent_of(pod)
        if entry is None:
            return
        self.watch_events += 1
        # offer, not put: a consumed intent must not be resurrected by
        # the pod's later MODIFIED events / reconnect replays
        self._server.intents.offer(entry[0], entry[1])


# pod phases whose containers have stopped for good — their devices are
# free even while the pod object lingers (phase is monotonic once terminal)
TERMINAL_PHASES = frozenset({"Succeeded", "Failed"})


def _pod_key_of(pod: dict[str, Any]) -> Optional[str]:
    meta = pod.get("metadata") or {}
    name = meta.get("name")
    if not name:
        return None
    return f"{meta.get('namespace', 'default')}/{name}"


class PodLifecycleReleaseLoop(_WatchLoop):
    """The release effector for pod lifecycle — SURVEY §4.4's recovery loop
    ("pods on dead device fail → controller reschedules") structurally
    requires it, and so does every long-lived cluster.

    kube-scheduler only talks to the extender about pods it is *placing*;
    nothing in the webhook protocol ever says a placed pod finished.
    Without this loop a completed or deleted pod's chips stay committed in
    the ledger forever: utilization reads 100% while the hardware idles,
    later gangs cannot fit, and preemption plans evict pods that no longer
    exist. This loop watches pod lifecycle cluster-wide and turns each
    ending into the extender's recorded ``release`` decision:

      * ``DELETED``                  → release (object gone, devices freed)
      * phase ``Succeeded``/``Failed`` → release (containers stopped; the
        object lingers until a controller or operator deletes it, but the
        kubelet has already returned the devices)

    A pod carrying only a ``deletionTimestamp`` is NOT released: graceful
    termination means its containers may still hold the chips — the same
    conservative rule :class:`EvictionExecutor` applies before counting a
    preemption victim as evicted.

    The resync (every (re)connect; every poll in no-watch mode) closes
    watch gaps from both directions: listed pods in a terminal phase are
    released directly, and ledger allocations whose pod is absent from the
    list are released only after a confirming GET — the GET, not the list,
    is the authority, because the list snapshot may predate a just-bound
    pod's creation (pods are always created before they are scheduled, so
    a pod the GET still finds is alive, not leaked).

    Gang note: only committed allocations are released here. A gang
    member holding a pre-bind *reservation* (assigned, never bound) whose
    pod vanishes is rolled back by the gang layer's own TTL — the
    documented path for half-assembled gangs.
    """

    def __init__(
        self, extender, api, poll_seconds: float = 5.0,
        use_watch: bool = True, evictions: Optional["EvictionExecutor"] = None,
    ) -> None:
        super().__init__("tpukube-pod-lifecycle", api, None,
                         poll_seconds, use_watch)
        self._extender = extender
        # termination-detection unification: this loop already sees every
        # pod DELETED event, so it confirms the eviction executor's
        # in-flight terminations for free — while this loop's watch runs,
        # the executor stretches its per-key GET poll to a 30s missed-
        # event safety net (attach_watch_confirmer)
        self._evictions = evictions
        if evictions is not None:
            evictions.attach_watch_confirmer(self)
        self.released = 0  # lifecycle releases applied (tests/metrics)
        # resync release batching (ISSUE 14): while set, _release
        # defers its dispatch into this buffer and _resync_from flushes
        # ONE extender.release_many call — against a process-mode
        # ShardRouter that is one fanned-out round-trip per replica
        # instead of one per released pod (a churn wave releases
        # thousands). None = dispatch inline (watch events, plain
        # extenders).
        self._release_buffer: Optional[list[str]] = None
        # generation-based incremental resync (ISSUE 15): instead of
        # reading the FULL ledger every resync (per churn wave — over
        # the process transport that serialized every replica's whole
        # alloc set per wave), keep a mirror advanced by the ledger's
        # allocs_since change log. A cursor the log cannot cover (gap,
        # restart) degrades to a counted full read — never stale. The
        # counters feed tpukube_resync_{full,incremental}_total and
        # tpukube_resync_bytes_total; None mirror = feature off or not
        # yet bootstrapped.
        self._alloc_cursor = None
        self._alloc_mirror: Optional[dict[str, Any]] = None
        self.resync_full = 0
        self.resync_incremental = 0
        self.resync_bytes = 0

    def watch_alive(self) -> bool:
        """True while DELETED events can actually flow (the executor's
        cue to defer its GET confirms here) — this loop's own stream, or
        the shared PodInformer's. Requires a CURRENTLY-CONNECTED stream,
        not merely a live thread: during reconnect backoff and list-
        resync windows events are silently missed, and deferring the GET
        net on a dead stream gates gang binds up to 30s per missed event
        (ADVICE round 5 low)."""
        host = getattr(self, "_host_loop", None) or self
        return (host._use_watch and host._thread is not None
                and host._thread.is_alive() and host.stream_connected())

    def _confirm_eviction(self, pod_key: str) -> None:
        if self._evictions is not None:
            self._evictions.confirm_deleted(pod_key)

    def _release(self, pod_key: str, why: str, uid: str = "") -> bool:
        alloc = self._extender.state.allocation(pod_key)
        if alloc is None:
            return False
        if alloc.uid and uid and alloc.uid != uid:
            # pod names recur (StatefulSet members): this signal is about
            # a DIFFERENT incarnation than the ledger entry — a stale
            # DELETED event or stale list entry must not free the chips a
            # recreated, live pod is holding
            log.info("lifecycle signal for %s ignored: uid %s is not the "
                     "ledger's %s", pod_key, uid, alloc.uid)
            return False
        if self._release_buffer is not None:
            self._release_buffer.append(pod_key)
        else:
            self._extender.handle("release", {"pod_key": pod_key})
        self.released += 1
        log.info("released %s (%s)", pod_key, why)
        return True

    def _apply_watch_event(self, etype: str, pod: dict[str, Any]) -> None:
        key = _pod_key_of(pod)
        if key is None:
            return
        uid = str((pod.get("metadata") or {}).get("uid") or "")
        if etype == "DELETED":
            self._confirm_eviction(key)
            self._release(key, "pod deleted", uid=uid)
            return
        phase = (pod.get("status") or {}).get("phase")
        if phase in TERMINAL_PHASES:
            self._release(key, f"phase {phase}", uid=uid)

    def _resync(self) -> tuple[bool, Optional[str]]:
        pods, rv = self._list_pods_rv()
        return self._resync_from(pods), rv

    def _resync_from(self, pods: list[dict[str, Any]]) -> bool:
        """Reconcile against an already-fetched pod list (the shared
        PodInformer fetches once for all its children)."""
        release_many = getattr(self._extender, "release_many", None)
        if release_many is not None:
            self._release_buffer = []
        try:
            return self._resync_scan(pods)
        finally:
            buffer, self._release_buffer = self._release_buffer, None
            if buffer:
                release_many(buffer)

    def _ledger_allocations(self) -> list:
        """The committed allocations the resync reconciles against —
        served O(Δ) from the generation-log mirror when the extender's
        ledger supports ``allocs_since`` (ISSUE 15), the legacy full
        read otherwise. The mirror is exactly as fresh as a full read
        taken at the answer's cursor: a gap or restart produces a full
        answer from the source, never a stale or partial mirror."""
        state = self._extender.state
        since = getattr(state, "allocs_since", None)
        if since is None:
            return state.allocations()
        delta = since(self._alloc_cursor)
        if delta is None:  # log disabled: legacy full read, uncounted
            return state.allocations()
        self._alloc_cursor = delta["cursor"]
        self.resync_bytes += int(delta.get("bytes", 0))
        if "full" in delta:
            self.resync_full += 1
            self._alloc_mirror = {a.pod_key: a for a in delta["full"]}
        else:
            self.resync_incremental += 1
            mirror = self._alloc_mirror
            if mirror is None:  # defensive: treat as bootstrap
                mirror = self._alloc_mirror = {}
            for key in delta["removes"]:
                mirror.pop(key, None)
            for alloc in delta["adds"]:
                mirror[alloc.pod_key] = alloc
        return list(self._alloc_mirror.values())

    def resync_stats(self) -> dict[str, Any]:
        """The resync counters (scenario results + /statusz): full vs
        incremental reads and the wire-shape bytes they moved. The
        hit ratio excludes the one unavoidable bootstrap full read —
        any ADDITIONAL full is a gap/restart fallback."""
        reads = self.resync_full + self.resync_incremental
        return {
            "full": self.resync_full,
            "incremental": self.resync_incremental,
            "bytes": self.resync_bytes,
            "incremental_hit_ratio": (
                round(self.resync_incremental / max(1, reads - 1), 4)
                if reads > 1 else None
            ),
        }

    def _resync_scan(self, pods: list[dict[str, Any]]) -> bool:
        present: dict[str, str] = {}  # key -> listed uid
        changed = False
        for pod in pods:
            key = _pod_key_of(pod)
            if key is None:
                continue
            uid = str((pod.get("metadata") or {}).get("uid") or "")
            present[key] = uid
            if (pod.get("status") or {}).get("phase") in TERMINAL_PHASES:
                changed |= self._release(key, "terminal phase (resync)",
                                         uid=uid)
        for alloc in self._ledger_allocations():
            listed_uid = present.get(alloc.pod_key)
            if listed_uid is not None:
                if not (alloc.uid and listed_uid
                        and alloc.uid != listed_uid):
                    continue  # same (or indeterminate) incarnation — alive
                # a same-name pod with a DIFFERENT uid: the allocation's
                # incarnation is gone; holding its entry would 409 the
                # newcomer's bind forever (phantom allocation)
                changed |= self._release(alloc.pod_key,
                                         "pod replaced (resync)")
                continue
            namespace, name = alloc.pod_key.split("/", 1)
            try:
                pod = self._api.get_pod(namespace, name)
            except Exception as e:
                log.warning("lifecycle confirm of %s failed, retrying: %s",
                            alloc.pod_key, e)
                continue
            if pod is not None:
                cur_uid = str((pod.get("metadata") or {}).get("uid") or "")
                if not (alloc.uid and cur_uid and alloc.uid != cur_uid):
                    # same (or indeterminate) incarnation — but a pod the
                    # stale LIST missed may ALREADY be terminal: trust the
                    # GET's phase, or the chips wait a full reconnect
                    # interval for release
                    phase = (pod.get("status") or {}).get("phase")
                    if phase in TERMINAL_PHASES:
                        changed |= self._release(
                            alloc.pod_key, f"phase {phase} (resync confirm)",
                            uid=cur_uid,
                        )
                    continue
                changed |= self._release(alloc.pod_key,
                                         "pod replaced (resync)")
                continue
            # (executor-tracked eviction victims never reach this loop —
            # their ledger entries were released before queueing; a
            # DELETED event missed in a reconnect gap is recovered by the
            # executor's own stretched GET net, WATCH_CONFIRM_GRACE_S)
            changed |= self._release(alloc.pod_key, "pod absent (resync)")
        return changed


class PodAdmissionFeed(_WatchLoop):
    """Routes informer-delivered PENDING pods into the extender's batch
    scheduling queue (``Extender.admit``) — the ROADMAP follow-up that
    makes batching real-cluster-fed, not sim/webhook-only.

    Without this feed, the scheduling queue only fills from /filter
    webhooks (one pod per kube-scheduler pop) or the sim's batch
    driver: an arrival storm still pays a webhook round-trip before a
    pod even reaches the batch planner. Fed from the shared
    PodInformer, pending TPU pods are admitted the moment their ADDED/
    MODIFIED event lands, so the next cycle drains the real backlog in
    one epoch-pinned plan and their /filter webhooks answer from it.

    Admission is conservative and idempotent: only unbound (no
    ``spec.nodeName``), non-terminal pods with a TPU/vTPU request are
    admitted; ``Extender.admit`` is a no-op without batching and dedups
    per pod key, and the tenancy gate (when on) runs inside it. DELETED
    events need no handling — a deleted pod's queue entry is superseded
    at plan time and its plan expires on the reservation-TTL janitor,
    with the lifecycle loop's recorded release unwinding any assumed
    allocation."""

    def __init__(self, extender, api, poll_seconds: float = 5.0,
                 use_watch: bool = True) -> None:
        super().__init__("tpukube-pod-admission", api, None,
                         poll_seconds, use_watch)
        self._extender = extender
        self.admitted = 0  # pods routed into the queue (tests/metrics)

    def _admit(self, pod: dict[str, Any]) -> bool:
        from tpukube.core.types import RESOURCE_TPU, RESOURCE_VTPU
        from tpukube.sched import kube

        if (pod.get("spec") or {}).get("nodeName"):
            return False  # already bound: the queue is for pending pods
        if (pod.get("status") or {}).get("phase") in TERMINAL_PHASES:
            return False
        try:
            info = kube.pod_from_k8s(pod)
        except kube.KubeSchemaError:
            return False  # not a schedulable pod object
        req = info.requests()
        if not (req.get(RESOURCE_TPU, 0) or req.get(RESOURCE_VTPU, 0)):
            return False  # not ours to schedule
        if not self._extender.admit(info):
            # tenancy refusal, or the pod already has a live plan (an
            # informer re-delivery): nothing entered the queue
            return False
        self.admitted += 1
        return True

    def _apply_watch_event(self, etype: str, pod: dict[str, Any]) -> None:
        if etype == "DELETED":
            return
        self._admit(pod)

    def _resync(self) -> tuple[bool, Optional[str]]:
        pods, rv = self._list_pods_rv()
        return self._resync_from(pods), rv

    def _resync_from(self, pods: list[dict[str, Any]]) -> bool:
        changed = False
        for pod in pods:
            changed |= self._admit(pod)
        return changed


class PodInformer(_WatchLoop):
    """ONE cluster-wide pod list+watch fanned out to the extender's pod
    loops (lifecycle release + alloc reconcile).

    Each of those loops is a correct standalone informer, but running
    both means two full paginated LISTs per reconnect and two concurrent
    watch streams each carrying — and decoding — every pod mutation in
    the cluster. The daemon runs this composite instead: one stream, one
    list, events dispatched to every child's handler. Children are
    constructed normally but never started; their counters/metrics stay
    theirs."""

    def __init__(self, api, children, poll_seconds: float = 5.0,
                 use_watch: bool = True) -> None:
        super().__init__("tpukube-pod-informer", api, None,
                         poll_seconds, use_watch)
        self._children = list(children)
        for c in self._children:
            # watch_alive() consumers (eviction confirmation deferral)
            # must see THIS loop's thread as the live stream
            c._host_loop = self

    def _apply_watch_event(self, etype: str, pod: dict[str, Any]) -> None:
        resync = False
        for c in self._children:
            try:
                c._apply_watch_event(etype, pod)
            except _ResyncNeeded:
                resync = True  # finish fanning out, then force resync
            except Exception:
                # a standalone loop would hit _run's generic handler and
                # reconnect+resync within one poll — a child under the
                # informer must keep that retry bound, not wait out the
                # watch stream's server timeout
                log.exception("%s: %s handler failed on %s",
                              self._name, c._name, etype)
                resync = True
        if resync:
            raise _ResyncNeeded

    def _resync(self) -> tuple[bool, Optional[str]]:
        pods, rv = self._list_pods_rv()
        changed = False
        for c in self._children:
            try:
                changed |= c._resync_from(pods)
            except Exception:
                log.exception("%s: %s resync failed", self._name, c._name)
                self._child_failed = True
        return changed, rv

    def _needs_resync(self) -> bool:
        flags = [c._needs_resync() for c in self._children]  # consume ALL
        failed, self._child_failed = getattr(self, "_child_failed",
                                             False), False
        return failed or any(flags)


class NodeTopologyRefreshLoop(_WatchLoop):
    """Keeps a nodeCacheCapable extender's node cache fresh.

    With ``nodeCacheCapable: true``, kube-scheduler sends only NodeNames —
    the extender would never see node-annotation updates (health faults,
    link faults, share-mode changes) after its startup rebuild. This loop
    watches the Node objects (informer pattern, poll fallback) and
    applies CHANGED topology annotations as recorded ``upsert_node``
    decisions, so live captures still replay deterministically against a
    fresh extender. Watch mode closes the §4.4 fault path's last latency
    gap: a node agent's health re-annotation reaches the scheduler's
    cache within milliseconds instead of a poll interval later."""

    def __init__(self, extender, api, poll_seconds: float = 5.0,
                 use_watch: bool = True) -> None:
        super().__init__("tpukube-node-refresh", api, None, poll_seconds,
                         use_watch, watch_method="watch_nodes")
        self._extender = extender
        self._applied: dict[str, str] = {}  # name -> applied topo payload
        self._rejected: dict[str, str] = {}  # name -> rejected payload
        self.refreshed = 0  # applied annotation changes (tests/metrics)

    def note_applied(self, name: str, payload: Optional[str]) -> None:
        """Prime the loop with a topology payload some OTHER path already
        dispatched (rebuild_extender at startup): without priming, the
        first poll re-records an upsert_node decision for every node the
        rebuild just applied — duplicate trace records and an inflated
        ``refreshed`` counter on every restart."""
        if payload is not None:
            self._applied[name] = payload

    def note_rejected(self, name: str, payload: Optional[str]) -> None:
        """Same priming for a payload another path already dispatched and
        saw REJECTED — the first poll must not re-record the identical
        error decision."""
        if payload is not None:
            self._rejected[name] = payload

    def _apply_node(self, obj: dict[str, Any]) -> bool:
        """Dispatch one Node object's topology annotation if it changed;
        True when applied."""
        meta = obj.get("metadata") or {}
        name = meta.get("name")
        if not name:
            return False
        annotations = dict(meta.get("annotations") or {})
        payload = annotations.get(codec.ANNO_NODE_TOPOLOGY)
        if payload is None or payload == self._applied.get(name):
            return False
        if payload == self._rejected.get(name):
            # a persistently-bad annotation must not re-record an
            # identical error decision (trace spam) every poll;
            # re-dispatch only when the payload changes
            return False
        out = self._extender.handle(
            "upsert_node", {"name": name, "annotations": annotations}
        )
        if out.get("error"):
            log.warning("node refresh for %s rejected: %s",
                        name, out["error"])
            self._rejected[name] = payload
            return False
        self._rejected.pop(name, None)
        self._applied[name] = payload
        self.refreshed += 1
        return True

    def _apply_watch_event(self, etype: str, obj: dict[str, Any]) -> None:
        if etype == "DELETED":
            # forget bookkeeping so a recreated same-name node re-applies
            name = (obj.get("metadata") or {}).get("name")
            if name:
                self._applied.pop(name, None)
                self._rejected.pop(name, None)
            return
        self._apply_node(obj)

    def _resync(self) -> tuple[bool, Optional[str]]:
        if hasattr(self._api, "list_nodes_with_rv"):
            nodes, rv = self._api.list_nodes_with_rv()
        else:
            nodes, rv = self._api.list_nodes(), None
        did = False
        for obj in nodes:
            did |= self._apply_node(obj)
        return did, rv


def rebuild_extender(extender, api, refresh=None) -> int:
    """Reconstruct a restarted extender's ledger AND gang reservations
    from the apiserver (SURVEY §6 restart story, wired to the real
    channel): node topology annotations first — the ledger can only
    commit onto known nodes — then every *live, bound* pod's alloc
    annotation. Lifecycle-filtered: terminal-phase pods, unbound pods
    (bind partial-failure residue), and pods whose bound node contradicts
    their annotation are skipped loudly — restoring any of them would
    resurrect a dead or phantom allocation. A node whose annotation is
    malformed is skipped loudly; its pods then fail to restore (also
    loudly) and the reconcile machinery takes over.
    Pass the daemon's NodeTopologyRefreshLoop as ``refresh`` to prime it
    with the payloads applied here — its first poll then dispatches
    nothing the rebuild already did.
    Returns the number of allocations restored."""
    for obj in api.list_nodes():
        meta = obj.get("metadata") or {}
        name = meta.get("name")
        if not name:
            continue
        annotations = dict(meta.get("annotations") or {})
        # recorded upsert_node decisions, not bare state mutation: a
        # names-mode capture that starts right after rebuild must replay
        # with the same node state the live extender had
        out = extender.handle(
            "upsert_node", {"name": name, "annotations": annotations},
        )
        if out.get("error"):
            log.error("rebuild: node %s annotation rejected: %s",
                      name, out["error"])
            if refresh is not None:
                refresh.note_rejected(
                    name, annotations.get(codec.ANNO_NODE_TOPOLOGY)
                )
        elif refresh is not None:
            refresh.note_applied(
                name, annotations.get(codec.ANNO_NODE_TOPOLOGY)
            )
    pods = [annos for annos, _, _ in live_alloc_pods(api.list_pods())]
    return extender.rebuild_from_pods(pods)


def live_alloc_pods(
    pods: list[dict[str, Any]],
) -> list[tuple[dict[str, str], Optional[Any], Optional[str]]]:
    """The restart story's lifecycle filter, shared by the legacy full
    rebuild above and the journal recovery's reconcile pass
    (sched/journal.py) — they must never test different sets. Returns
    (annotations, decoded alloc or None when undecodable, pod key) for
    every pod whose alloc annotation SHOULD be restored: live, bound,
    non-terminal, annotation matching its binding and uid. Skips are
    loud; an undecodable payload passes through with ``None`` so
    ``rebuild_from_pods`` logs the decode failure itself."""
    out: list[tuple[dict[str, str], Optional[Any], Optional[str]]] = []
    for p in pods:
        meta = p.get("metadata") or {}
        annos = dict(meta.get("annotations") or {})
        payload = annos.get(codec.ANNO_ALLOC)
        if not payload:
            continue
        key = _pod_key_of(p)
        if key is None:
            continue
        phase = (p.get("status") or {}).get("phase")
        if phase in TERMINAL_PHASES:
            # the pod finished; its devices are free. Restoring it would
            # re-import exactly the leak PodLifecycleReleaseLoop exists to
            # close. (A pod with only a deletionTimestamp IS restored: its
            # containers may still hold the chips through graceful
            # termination, and the lifecycle loop releases it on DELETED.)
            log.warning("rebuild: skipping %s (phase %s — chips are free)",
                        key, phase)
            continue
        node_name = (p.get("spec") or {}).get("nodeName")
        if not node_name:
            # the bind effector's designed partial-failure residue: the
            # annotation PATCH landed but the Binding POST failed, so the
            # ledger was released and the scheduler retries. Restoring it
            # would plant a phantom allocation that 409s every bind retry,
            # pinning the pod Pending and leaking its chips.
            log.warning("rebuild: skipping %s (alloc annotation on an "
                        "unbound pod — bind partial-failure residue)", key)
            continue
        try:
            planned = codec.decode_alloc(payload)
        except codec.CodecError:
            planned = None  # rebuild_from_pods logs the decode loudly
        if planned is not None and planned.node_name != node_name:
            log.warning("rebuild: skipping %s (alloc says node %s but the "
                        "pod is bound to %s — stale annotation)",
                        key, planned.node_name, node_name)
            continue
        pod_uid = str(meta.get("uid") or "")
        if (planned is not None and planned.uid and pod_uid
                and planned.uid != pod_uid):
            log.warning("rebuild: skipping %s (alloc was for uid %s; the "
                        "pod is a recreation with uid %s)",
                        key, planned.uid, pod_uid)
            continue
        out.append((annos, planned, key))
    return out


def pod_binder(api) -> Callable[[Any], None]:
    """The extender's bind effector: ``extender.binder = pod_binder(api)``
    makes a successful /bind create the real Binding (pod starts on its
    node) and persist the alloc annotation the node agent's intent watcher
    reads. Raises ApiServerError upward — the extender undoes its ledger
    commit and the scheduler re-runs the cycle."""

    def bind(alloc) -> None:
        namespace, name = alloc.pod_key.split("/", 1)
        annotations = {codec.ANNO_ALLOC: codec.encode_alloc(alloc)}
        # gang env ALSO rides as per-key annotations: the downward API
        # projects each into its TPU_KUBE_GANG_* container env var
        # (deploy/gang-job-example.yaml) — a JSON blob inside one env
        # var would make the in-pod runtime parse annotations itself
        annotations.update(codec.gang_env_annotations(alloc.env))
        api.bind_pod(namespace, name, alloc.node_name, annotations)

    return bind


def alloc_divergence_reporter(api) -> Callable[[str, list[str], list[str]], None]:
    """The plugin's report channel for kubelet-side id divergence: write
    the ACTUAL allocated ids onto the pod for the extender's reconcile
    loop. Used as ``server.set_alloc_reporter(alloc_divergence_reporter(api))``."""

    def report(pod_key: str, planned: list[str], actual: list[str]) -> None:
        namespace, name = pod_key.split("/", 1)
        try:
            api.patch_pod_annotations(
                namespace, name,
                {ANNO_ALLOC_ACTUAL: encode_alloc_actual(actual)},
            )
            log.warning(
                "reported alloc divergence for %s: kubelet chose %s, "
                "plan was %s", pod_key, sorted(actual), sorted(planned),
            )
        except ApiServerError as e:
            log.error("divergence report for %s failed: %s", pod_key, e)

    return report


class AllocReconcileLoop(_WatchLoop):
    """Extender-side half of the device-id loop: folds reported
    ``alloc-actual`` annotations into the ledger (via the extender's
    recorded ``reconcile`` decision) and rewrites the pod's ``alloc``
    annotation to match reality, clearing the report. Watch-driven
    (informer pattern, poll fallback) like the other pod loops: a
    divergence report lands as the MODIFIED event that carries it,
    instead of up to a poll interval later — and the extender stops
    LISTing every pod every few seconds looking for a rare annotation
    the apiserver cannot field-select on."""

    def __init__(
        self, extender, api, poll_seconds: float = 5.0,
        use_watch: bool = True,
    ) -> None:
        super().__init__("tpukube-alloc-reconcile", api, None,
                         poll_seconds, use_watch)
        self._extender = extender
        # a failed ack PATCH left a folded-but-uncleared report: force a
        # resync after one poll interval instead of waiting for the next
        # event / the watch stream's server timeout
        self._ack_retry = False
        self.reconciled = 0  # ledger amendments applied (tests/metrics)

    def _reconcile_pod(self, pod: dict[str, Any]) -> bool:
        """Fold one pod's alloc-actual report, if it carries one; True
        when the ledger was amended and the report cleared. A failing
        pod never blocks the batch."""
        meta = pod.get("metadata", {})
        annos = meta.get("annotations") or {}
        payload = annos.get(ANNO_ALLOC_ACTUAL)
        if not payload:
            return False
        namespace = meta.get("namespace", "default")
        name = meta["name"]
        pod_key = f"{namespace}/{name}"
        try:
            actual = decode_alloc_actual(payload)
        except codec.CodecError as e:
            log.warning("pod %s: bad alloc-actual: %s", pod_key, e)
            return False
        self._extender.handle(
            "reconcile", {"pod_key": pod_key, "devices": actual}
        )
        patch: dict[str, Optional[str]] = {ANNO_ALLOC_ACTUAL: None}
        alloc = self._extender.state.allocation(pod_key)
        if alloc is not None:
            patch[codec.ANNO_ALLOC] = codec.encode_alloc(alloc)
        try:
            self._api.patch_pod_annotations(namespace, name, patch)
        except ApiServerError as e:
            # pod deleted mid-event, transient apiserver error: the
            # reconcile above is idempotent; flag a forced resync so the
            # retry comes within one poll interval, not at the watch
            # stream's server timeout
            log.warning("reconcile ack for %s failed: %s", pod_key, e)
            self._ack_retry = True
            return False
        self.reconciled += 1
        return True

    def _apply_watch_event(self, etype: str, pod: dict[str, Any]) -> None:
        if etype == "DELETED":
            return  # a deleted pod's report is moot
        # the clearing PATCH triggers one more MODIFIED event, which
        # finds no alloc-actual and no-ops — no feedback loop
        self._reconcile_pod(pod)
        if self._ack_retry:
            self._ack_retry = False
            raise _ResyncNeeded

    def _resync(self) -> tuple[bool, Optional[str]]:
        pods, rv = self._list_pods_rv()
        return self._resync_from(pods), rv

    def _resync_from(self, pods: list[dict[str, Any]]) -> bool:
        did = False
        for pod in pods:
            did |= self._reconcile_pod(pod)
        return did

    def _needs_resync(self) -> bool:
        # consumed AFTER the whole resync list was processed — one pod's
        # failing ack must not starve the batch
        retry, self._ack_retry = self._ack_retry, False
        return retry


class EvictionExecutor(_PollLoop):
    """The effector for the extender's eviction decisions.

    Preemption and gang rollback leave victim pod keys on
    ``extender.pending_evictions`` — the ledger already shows their chips
    free, so a victim left running would double-allocate on first reuse.
    This loop drains the queue through the apiserver channel's
    ``evict_pod`` (the Eviction subresource on a real cluster). A
    PDB-blocked or transiently-failing eviction is requeued and retried
    every poll, forever: eviction is a correctness obligation, not
    best-effort, so the only terminal states are "pod gone" and "operator
    intervened". The sim harness's ``drain_evictions`` is a thin wrapper
    over :meth:`drain`."""

    def __init__(self, extender, api, poll_seconds: float = 1.0,
                 clock=None) -> None:
        from tpukube.core.clock import SYSTEM

        super().__init__(poll_seconds, "tpukube-evictions")
        self._extender = extender
        self._api = api
        # eviction-confirm ages and the watch-confirm grace window are
        # scheduling-semantic time: injectable (core/clock.py) so the
        # discrete-event sim drives them on compressed time
        self._clock = clock if clock is not None else SYSTEM
        # eviction accepted by the apiserver but deletion not yet
        # confirmed: a 2xx on the Eviction subresource only STARTS
        # graceful termination; the pod keeps its devices until its
        # containers actually stop, so "evicted" is only counted once the
        # pod object is gone. Guarded by _state_lock: the lifecycle
        # watch's confirm_deleted runs on its own thread.
        self._terminating: set[str] = set()
        # keys whose eviction POST is IN FLIGHT right now: an instantly-
        # deleted victim's DELETED event can reach the lifecycle watch
        # (confirm_deleted) before drain() regains the lock to add the
        # key to _terminating — without pre-registration that confirm
        # would miss and the gang would wait out the 30s GET net
        self._expecting: set[str] = set()
        self._confirmed_early: set[str] = set()
        self._state_lock = threading.Lock()
        # pod key -> monotonic time of its FIRST drain attempt; feeds the
        # oldest-age gauge operators alarm on (a PDB-wedged eviction is
        # a capacity leak in progress)
        self._pending_since: dict[str, float] = {}
        # a live pod watch that calls confirm_deleted (the lifecycle
        # loop): while it is running, the per-key GET confirm only covers
        # keys the watch has had ample time to see — O(1) confirmation
        # traffic instead of one GET per victim per poll
        self._watch_confirmer = None
        # optional core/retry.Retrier for the per-key GET confirms: a
        # transient apiserver blip then retries within this poll
        # instead of gating a gang bind a whole extra interval. None
        # (the default) keeps the poll-cadence-only legacy behavior.
        self.retrier: Optional[retry.Retrier] = None
        self.evicted = 0   # pods confirmed gone (tests/metrics)
        self.blocked = 0   # PDB 429s requeued (tests/metrics)
        self.failures = 0  # transport/API errors requeued (tests/metrics)

    # while a watch confirmer runs, GET-confirm only keys older than this
    # (the watch delivers DELETED within ms; the stretched GET is the
    # missed-event safety net, not the primary channel)
    WATCH_CONFIRM_GRACE_S = 30.0

    def attach_watch_confirmer(self, loop) -> None:
        """Called by PodLifecycleReleaseLoop when wired with this
        executor: its DELETED events become the primary termination
        confirmation channel."""
        self._watch_confirmer = loop

    def depth(self) -> int:
        """Evictions not yet confirmed done: queued + terminating."""
        with self._state_lock:
            return (len(self._extender.pending_evictions)
                    + len(self._terminating))

    def oldest_age_seconds(self, now: Optional[float] = None) -> float:
        """Age of the oldest unconfirmed eviction (0.0 when idle),
        measured from its first drain attempt."""
        with self._state_lock:
            if not self._pending_since:
                return 0.0
            now = self._clock.monotonic() if now is None else now
            return max(0.0, now - min(self._pending_since.values()))

    def pending_snapshot(
        self, now: Optional[float] = None
    ) -> list[dict[str, Any]]:
        """Every unconfirmed eviction with its state and age (seconds
        since first drain attempt; None before the first attempt) — the
        /statusz rendering of the queue the depth gauge only counts."""
        now = self._clock.monotonic() if now is None else now
        with self._state_lock:
            out = []
            for pod_key in list(self._extender.pending_evictions):
                since = self._pending_since.get(pod_key)
                out.append({
                    "pod": pod_key, "state": "queued",
                    "age_seconds": (round(now - since, 3)
                                    if since is not None else None),
                })
            for pod_key in sorted(self._terminating):
                since = self._pending_since.get(pod_key)
                out.append({
                    "pod": pod_key, "state": "terminating",
                    "age_seconds": (round(now - since, 3)
                                    if since is not None else None),
                })
            return out

    def _confirmed(self, pod_key: str) -> None:
        """Bookkeeping for a victim whose pod object is gone (call with
        _state_lock held for the set mutation done by callers); tells the
        extender through the recorded ``victim_gone`` decision so gated
        gang binds unblock deterministically."""
        self.evicted += 1
        self._pending_since.pop(pod_key, None)

    def _notify_gone(self, pod_key: str) -> None:
        handle = getattr(self._extender, "handle", None)
        if handle is not None:
            try:
                handle("victim_gone", {"pod_key": pod_key})
            except Exception:
                log.exception("victim_gone dispatch for %s failed", pod_key)

    def confirm_deleted(self, pod_key: str) -> bool:
        """Out-of-band confirmation from the lifecycle watch: it saw the
        pod's DELETED event, so the GET poll for this key is redundant
        (and _confirm_terminated defers to this channel while the watch
        runs — see WATCH_CONFIRM_GRACE_S). Returns True if the key was
        being tracked: terminating, its eviction POST in flight, or
        still queued on pending_evictions awaiting its first drain."""
        never_posted = False
        with self._state_lock:
            if pod_key in self._terminating:
                self._terminating.discard(pod_key)
            elif pod_key in self._expecting:
                # the DELETED event outran the eviction call's return:
                # count it now; drain() sees _confirmed_early and will
                # not track (or requeue) the already-gone pod
                self._confirmed_early.add(pod_key)
            elif pod_key in self._extender.pending_evictions:
                # queued but not yet drained: the victim is already
                # gone, so the eviction POST is moot — drop the key from
                # the queue NOW (a side marker would linger and cancel a
                # later legitimate eviction of a reused pod name), and
                # never track a deletion the watch has already delivered
                # (re-tracking would gate the gang on the 30s GET net)
                try:
                    self._extender.pending_evictions.remove(pod_key)
                    never_posted = True
                except ValueError:
                    # drain() popped it between the membership check and
                    # the remove; its POST is about to fly — same
                    # handling as the _expecting race above
                    self._confirmed_early.add(pod_key)
            else:
                return False
            # ``evicted`` counts pods confirmed GONE (the gang-unblock
            # event), not Eviction POSTs executed — a queued victim that
            # exits on its own still resolves its eviction obligation
            self._confirmed(pod_key)
        self._notify_gone(pod_key)
        if never_posted:
            # no Eviction POST ever flew: don't log an eviction that
            # would have no apiserver audit record to correlate with
            log.warning("victim %s gone before its eviction was posted "
                        "(confirmed by lifecycle watch)", pod_key)
        else:
            log.warning("evicted %s (confirmed by lifecycle watch)", pod_key)
        return True

    def check_once(self) -> bool:
        """One poll; True if any pod was evicted."""
        return bool(self.drain())

    def drain(self) -> list[str]:
        """Attempt every currently-queued eviction once; returns the pod
        keys whose deletion is CONFIRMED (object absent from the
        apiserver). Blocked/failed keys go back on the queue, accepted-
        but-still-terminating keys wait in ``_terminating`` — a key only
        leaves the executor as a confirmed deletion, never dropped."""
        q = self._extender.pending_evictions
        requeue: list[str] = []
        try:
            # bounded by the snapshot length: keys appended by other
            # threads mid-drain, like requeued keys, wait for the next poll
            for _ in range(len(q)):
                try:
                    pod_key = q.popleft()
                except IndexError:  # racing consumer emptied it
                    break
                with self._state_lock:
                    self._pending_since.setdefault(
                        pod_key, self._clock.monotonic()
                    )
                    self._expecting.add(pod_key)
                ok = None
                err = None
                try:
                    namespace, name = pod_key.split("/", 1)
                    ok = self._api.evict_pod(namespace, name)
                # tpukube: allow(exception-hygiene) the error is carried to the requeue branch below, which logs it and bumps the failures counter
                except Exception as e:
                    err = e
                with self._state_lock:
                    self._expecting.discard(pod_key)
                    if pod_key in self._confirmed_early:
                        # the watch confirmed the pod gone mid-call:
                        # nothing left to track or requeue, whatever the
                        # call's own outcome was. Drop the age entry too:
                        # when confirm_deleted's queued-key remove lost
                        # the race to our popleft, its _confirmed()
                        # bookkeeping ran BEFORE our setdefault above —
                        # without this pop the orphan entry inflates
                        # oldest_age_seconds() forever
                        self._pending_since.pop(pod_key, None)
                        self._confirmed_early.discard(pod_key)
                        continue
                    if ok:
                        self._terminating.add(pod_key)
                if err is not None:
                    # broad on purpose: ANY failure (transport timeout,
                    # junk response body, ...) must requeue, not drop —
                    # a lost key is a silent double-allocation
                    log.warning("eviction of %s failed, requeued: %s",
                                pod_key, err)
                    self.failures += 1
                    requeue.append(pod_key)
                elif not ok:
                    self.blocked += 1
                    requeue.append(pod_key)
                    log.warning("eviction of %s blocked by PDB, requeued",
                                pod_key)
        finally:
            q.extend(requeue)
        return self._confirm_terminated()

    def _confirm_terminated(self) -> list[str]:
        """Count a terminating pod as evicted once its object is gone —
        one tiny GET per in-flight key, not a cluster-wide list. A
        same-name pod WITHOUT a deletionTimestamp also confirms: the
        apiserver stamps deletionTimestamp the moment it accepts an
        eviction, so an unstamped pod is a controller's recreation (e.g.
        a StatefulSet member) — the original is gone and the newcomer is
        someone else's allocation, not our victim."""
        done = []
        watch_live = (self._watch_confirmer is not None
                      and self._watch_confirmer.watch_alive())
        now = self._clock.monotonic()
        with self._state_lock:
            tracked = sorted(
                pod_key for pod_key in self._terminating
                if not watch_live
                or (now - self._pending_since.get(pod_key, now)
                    > self.WATCH_CONFIRM_GRACE_S)
            )
        for pod_key in tracked:
            namespace, name = pod_key.split("/", 1)
            try:
                if self.retrier is not None:
                    pod = self.retrier.call(
                        lambda ns=namespace, n=name: self._api.get_pod(ns, n)
                    )
                else:
                    pod = self._api.get_pod(namespace, name)
            except Exception as e:
                log.warning("eviction confirm of %s failed, retrying: %s",
                            pod_key, e)
                continue
            if pod is not None and (
                (pod.get("metadata") or {}).get("deletionTimestamp")
            ):
                continue  # graceful termination still running
            with self._state_lock:
                if pod_key not in self._terminating:
                    continue  # confirm_deleted raced in and won
                self._terminating.discard(pod_key)
                self._confirmed(pod_key)
            self._notify_gone(pod_key)
            done.append(pod_key)
            log.warning("evicted %s (extender preemption/rollback)", pod_key)
        return done

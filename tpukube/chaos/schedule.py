"""Seeded fault schedule: the chaos layer's single source of randomness.

Every injection point (ChaosApiServer unary calls, watch-event fates)
asks the schedule what to do; the schedule draws from one
``random.Random(seed)`` in call order and logs what it injected. Same
seed + same call sequence = same faults — which is what makes the chaos
scenarios assertable instead of flaky.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from random import Random
from typing import Any, Optional

#: unary fault kinds, in the order one uniform draw is partitioned
#: (order is part of the determinism contract — do not reorder)
TORN, ERROR, TIMEOUT, SLOW = "torn", "error", "timeout", "slow"


@dataclass(frozen=True)
class ChaosSpec:
    """Per-call fault probabilities. All default 0.0 = no injection.

    ``torn_rate`` only applies to mutating ops (the write is APPLIED,
    then the response is "lost" — the ambiguous-outcome case real
    apiservers produce under connection resets, and the reason every
    writer must be idempotent-retry-safe). ``gone_rate`` applies to
    watch subscriptions (410 Gone -> list+watch resync).
    """

    error_rate: float = 0.0     # injected HTTP 503
    timeout_rate: float = 0.0   # injected transport error (code None)
    torn_rate: float = 0.0      # write applied, response lost
    slow_rate: float = 0.0      # response delayed by slow_seconds
    slow_seconds: float = 0.005
    gone_rate: float = 0.0      # 410 Gone on watch subscribe
    drop_event_rate: float = 0.0
    dup_event_rate: float = 0.0


@dataclass
class InjectedFault:
    seq: int
    op: str
    kind: str
    detail: str = ""

    def as_dict(self) -> dict[str, Any]:
        return {"seq": self.seq, "op": self.op, "kind": self.kind,
                "detail": self.detail}


class FaultSchedule:
    """Draws fault decisions deterministically and records them.

    ``budget`` caps the total number of injected faults (None =
    unlimited): scenarios set it so the storm provably ends and the
    convergence assertions run against a quiet control plane.
    ``stop()`` ends injection early (the scenario's "chaos off"
    switch); draws keep consuming the RNG identically either way, so
    toggling the budget does not reshuffle later decisions.
    """

    def __init__(self, seed: int, spec: ChaosSpec,
                 budget: Optional[int] = None) -> None:
        self.seed = seed
        self.spec = spec
        self.budget = budget
        self._rng = Random(seed)
        self._lock = threading.Lock()
        self._stopped = False
        self.faults: list[InjectedFault] = []

    # -- control -----------------------------------------------------------
    def stop(self) -> None:
        """Cease injecting (draws still consume the RNG)."""
        with self._lock:
            self._stopped = True

    def resume(self, spec: Optional[ChaosSpec] = None) -> None:
        with self._lock:
            self._stopped = False
            if spec is not None:
                self.spec = spec

    def _armed_locked(self) -> bool:
        if self._stopped:
            return False
        return self.budget is None or len(self.faults) < self.budget

    def _note_locked(self, op: str, kind: str, detail: str = "") -> None:
        self.faults.append(
            InjectedFault(len(self.faults) + 1, op, kind, detail)
        )

    # -- draws -------------------------------------------------------------
    def draw_unary(self, op: str, mutating: bool) -> Optional[str]:
        """Fault kind for one unary API call, or None. One uniform per
        call, partitioned torn|error|timeout|slow in declared order."""
        with self._lock:
            r = self._rng.random()  # always consumed: determinism
            if not self._armed_locked():
                return None
            spec = self.spec
            edge = spec.torn_rate if mutating else 0.0
            if r < edge:
                self._note_locked(op, TORN)
                return TORN
            edge += spec.error_rate
            if r < edge:
                self._note_locked(op, ERROR)
                return ERROR
            edge += spec.timeout_rate
            if r < edge:
                self._note_locked(op, TIMEOUT)
                return TIMEOUT
            edge += spec.slow_rate
            if r < edge:
                self._note_locked(op, SLOW)
                return SLOW
            return None

    def draw_watch_gone(self, op: str) -> bool:
        """True = reject this watch subscription with 410 Gone."""
        with self._lock:
            r = self._rng.random()
            if not self._armed_locked():
                return False
            if r < self.spec.gone_rate:
                self._note_locked(op, "gone", "410 on subscribe")
                return True
            return False

    def event_fate(self, op: str) -> str:
        """'deliver' | 'drop' | 'dup' for one watch event."""
        with self._lock:
            r = self._rng.random()
            if not self._armed_locked():
                return "deliver"
            spec = self.spec
            if r < spec.drop_event_rate:
                self._note_locked(op, "drop_event")
                return "drop"
            if r < spec.drop_event_rate + spec.dup_event_rate:
                self._note_locked(op, "dup_event")
                return "dup"
            return "deliver"

    # -- reporting ---------------------------------------------------------
    def injected(self) -> int:
        with self._lock:
            return len(self.faults)

    def by_kind(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for f in self.faults:
                out[f.kind] = out.get(f.kind, 0) + 1
            return out

    def report(self) -> dict[str, Any]:
        """JSON-able summary for scenario results."""
        with self._lock:
            out: dict[str, int] = {}
            for f in self.faults:
                out[f.kind] = out.get(f.kind, 0) + 1
            return {
                "seed": self.seed,
                "injected": len(self.faults),
                "by_kind": out,
                "budget": self.budget,
            }

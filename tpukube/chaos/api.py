"""ChaosApiServer — fault-injecting proxy over any apiserver surface.

Wraps a FakeApiServer (or the sim harness's pod-store adapter) and
injects the failure modes a real apiserver produces, on the schedule's
deterministic draw:

  * ``error``   — ApiServerError HTTP 503 (server sick; retryable)
  * ``timeout`` — ApiServerError with ``code=None`` (transport error:
    the request may or may not have reached the server — here it did
    NOT, the torn kind covers the did-land half)
  * ``torn``    — the mutation is APPLIED, then the response is
    "lost" (raised as a transport error). The ambiguous-outcome case
    every writer must be idempotent against: a retried bind must
    tolerate 409-already-bound-to-us, a retried patch must re-apply
    harmlessly.
  * ``slow``    — the response is delayed by ``slow_seconds``
  * watch faults — 410 Gone at subscribe (forcing the informer's
    list+watch resync) and per-event drop/duplicate fates (what a
    flaky stream actually does; resyncs must repair both).

Methods not listed in the fault tables pass straight through, and
unknown attributes delegate to the wrapped server — the proxy is
surface-agnostic so the same wrapper chaoses FakeApiServer in informer
tests and the sim pod store in scenario 8.
"""

from __future__ import annotations

import copy
import time
from typing import Any

from tpukube.apiserver import ApiServerError
from tpukube.chaos.schedule import ERROR, SLOW, TIMEOUT, TORN, FaultSchedule

#: unary ops with read-only semantics (torn never applies)
READ_OPS = frozenset({
    "get_pod", "list_pods", "list_pods_with_rv", "list_nodes",
    "list_nodes_with_rv", "get_node_annotations", "node_objects",
    "node_names",
})

#: unary ops that mutate (torn = applied-but-response-lost)
WRITE_OPS = frozenset({
    "patch_node_annotations", "patch_pod_annotations", "bind_pod",
    "evict_pod", "delete_pod", "upsert_pod", "finish_termination",
})

#: watch subscriptions (410-Gone + event-fate injection)
WATCH_OPS = frozenset({"watch_pods", "watch_nodes"})


class ChaosApiServer:
    """Fault-injecting decorator; see module docstring."""

    def __init__(self, inner: Any, schedule: FaultSchedule,
                 sleep=time.sleep) -> None:
        self._inner = inner
        self._schedule = schedule
        self._sleep = sleep

    @property
    def inner(self) -> Any:
        """The wrapped server (assertions read ground truth here)."""
        return self._inner

    @property
    def schedule(self) -> FaultSchedule:
        return self._schedule

    def __getattr__(self, name: str) -> Any:
        target = getattr(self._inner, name)
        if name in READ_OPS or name in WRITE_OPS:
            mutating = name in WRITE_OPS

            def unary(*args, **kwargs):
                kind = self._schedule.draw_unary(name, mutating)
                if kind == SLOW:
                    self._sleep(self._schedule.spec.slow_seconds)
                elif kind == ERROR:
                    raise ApiServerError(
                        f"chaos: injected 503 on {name}", code=503
                    )
                elif kind == TIMEOUT:
                    raise ApiServerError(
                        f"chaos: injected transport timeout on {name}"
                    )
                out = target(*args, **kwargs)
                if kind == TORN:
                    # the write landed; the caller only sees a dead
                    # connection — it MUST retry into idempotency
                    raise ApiServerError(
                        f"chaos: response lost after {name} applied "
                        f"(torn write)"
                    )
                return out

            return unary
        if name in WATCH_OPS:

            def watch(*args, **kwargs):
                if self._schedule.draw_watch_gone(name):
                    raise ApiServerError(
                        f"chaos: injected 410 Gone on {name}", code=410,
                    )
                gen = target(*args, **kwargs)
                return self._event_stream(name, gen)

            return watch
        return target

    def _event_stream(self, op: str, gen):
        for etype, obj in gen:
            fate = self._schedule.event_fate(op)
            if fate == "drop":
                continue
            yield etype, obj
            if fate == "dup":
                yield etype, copy.deepcopy(obj)

"""Control-plane chaos harness (ISSUE 4 tentpole).

Deterministic, seeded fault injection for the apiserver and
plugin/kubelet seams: API errors (injected 503s, transport timeouts,
410 Gone resyncs), dropped/duplicated watch events, torn annotation
patches (the write lands, the response is lost), slow responses, and
process "crashes" (extender teardown + cold restart mid-gang-commit,
via the chaos cluster's crash/restart helpers).

The schedule draws every fault decision from one seeded RNG in call
order, so a scenario replays the same fault sequence for the same seed
— chaos runs are regression tests, not dice rolls. Scenarios 8 and 9
(`tpukube-sim 8|9`) drive this end to end and assert the recovery
invariants: zero leaked gang reservations and zero ledger/apiserver
divergence after the dust settles.

Sharded-plane chaos (ISSUE 13): on a ``planner_replicas > 1`` cluster,
``replica_crash_recover`` kills ONE planner replica mid-flight — e.g.
mid-gang-commit of a two-phase DCN rendezvous — drives the router's
all-or-nothing abort, cold-restarts the replica via
``rebuild_from_pods``, and reports the zero-leak convergence the
acceptance asserts; ``SimCluster.partition_replica``/``heal_replica``
cover the partition half (tests/test_shard.py).
"""

from tpukube.chaos.api import ChaosApiServer
from tpukube.chaos.cluster import (
    ChaosSimCluster,
    converge,
    leaked_reservations,
    ledger_divergence,
    replica_crash_recover,
    transient_api_error,
)
from tpukube.chaos.crash import CrashSchedule
from tpukube.chaos.maintenance import (
    MaintenanceSchedule,
    SpotChurnSchedule,
)
from tpukube.chaos.schedule import ChaosSpec, FaultSchedule

__all__ = [
    "ChaosApiServer",
    "ChaosSimCluster",
    "ChaosSpec",
    "CrashSchedule",
    "FaultSchedule",
    "MaintenanceSchedule",
    "SpotChurnSchedule",
    "converge",
    "leaked_reservations",
    "ledger_divergence",
    "replica_crash_recover",
    "transient_api_error",
]

"""Maintenance-event and spot-churn chaos (ISSUE 19).

Region-scale fleets lose capacity two ways that scenario 15 must
reproduce deterministically:

  * **maintenance events** — a whole ICI slice leaves for planned work
    (firmware, recabling) and usually RETURNS later. The graceful path
    is the drain choreography (``sched/drain.py``); the chaos schedule
    decides WHICH slice goes next and whether it comes back.
  * **spot churn** — individual nodes vanish with no notice (preempted
    spot/ephemeral capacity): no cordon, no budgeted migration — the
    pods are simply gone and the control plane must converge anyway.

Both schedules follow the chaos layer's determinism contract
(:mod:`tpukube.chaos.schedule`): one seeded RNG drawn in call order,
draws consume the RNG even while stopped, every injected event is
recorded for the scenario report. Same seed + same call sequence =
the same storm.
"""

from __future__ import annotations

import threading
from random import Random
from typing import Any, Optional


class MaintenanceSchedule:
    """Seeded chooser of the next slice to take for maintenance.

    The first ``len(slice_ids)`` picks are a seeded permutation of ALL
    slices — a storm with at least that many events provably maintains
    every slice — and later picks are uniform. ``returns`` draws
    whether the slice's capacity comes back afterwards (probability
    ``return_rate``); a storm mixing both arms exercises scale-down
    (gone for good) and maintenance (drain, then re-ingest).
    """

    def __init__(self, seed: int, slice_ids, return_rate: float = 0.5,
                 budget: Optional[int] = None) -> None:
        self.seed = seed
        self.return_rate = return_rate
        self.budget = budget
        self._rng = Random(seed)
        self._slices = tuple(slice_ids)
        first = list(self._slices)
        self._rng.shuffle(first)
        self._first = first
        self._lock = threading.Lock()
        self._stopped = False
        self.events: list[dict[str, Any]] = []

    def stop(self) -> None:
        """Cease injecting (draws still consume the RNG)."""
        with self._lock:
            self._stopped = True

    def resume(self) -> None:
        with self._lock:
            self._stopped = False

    def _armed_locked(self) -> bool:
        if self._stopped:
            return False
        return self.budget is None or len(self.events) < self.budget

    def next_event(self) -> Optional[tuple[str, bool]]:
        """(slice_id, returns) for the next maintenance event, or None
        when stopped/out of budget. Both draws always consume the RNG
        so toggling the budget never reshuffles later decisions."""
        with self._lock:
            if self._first:
                sid = self._first.pop(0)
            else:
                sid = self._slices[self._rng.randrange(len(self._slices))]
            returns = self._rng.random() < self.return_rate
            if not self._armed_locked():
                return None
            self.events.append(
                {"seq": len(self.events) + 1, "slice": sid,
                 "returns": returns})
            return sid, returns

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "seed": self.seed,
                "events": len(self.events),
                "slices": [e["slice"] for e in self.events],
                "returned": sum(1 for e in self.events if e["returns"]),
            }


class SpotChurnSchedule:
    """Seeded no-notice node killer: each ``draw_kill`` decides whether
    ONE node of the offered set vanishes right now. Exactly two RNG
    draws per call (the kill coin and the victim index) whether or not
    a kill fires — the determinism contract again."""

    def __init__(self, seed: int, kill_rate: float,
                 budget: Optional[int] = None) -> None:
        self.seed = seed
        self.kill_rate = kill_rate
        self.budget = budget
        self._rng = Random(seed)
        self._lock = threading.Lock()
        self._stopped = False
        self.kills: list[dict[str, Any]] = []

    def stop(self) -> None:
        with self._lock:
            self._stopped = True

    def resume(self) -> None:
        with self._lock:
            self._stopped = False

    def _armed_locked(self) -> bool:
        if self._stopped:
            return False
        return self.budget is None or len(self.kills) < self.budget

    def draw_kill(self, node_names) -> Optional[str]:
        """The node to rip out with no notice, or None."""
        names = sorted(node_names)
        with self._lock:
            r = self._rng.random()
            idx = self._rng.randrange(len(names)) if names else 0
            if not names or r >= self.kill_rate:
                return None
            if not self._armed_locked():
                return None
            victim = names[idx]
            self.kills.append(
                {"seq": len(self.kills) + 1, "node": victim})
            return victim

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "seed": self.seed,
                "kills": len(self.kills),
                "nodes": [k["node"] for k in self.kills],
            }

"""CrashSchedule — deterministic crash-at-every-journal-seam chaos
(ISSUE 11).

The durable-state journal (sched/journal.py) has a small set of
on-disk outcomes a process death can leave behind, each mapping to a
seam in the append/checkpoint pipeline:

  * ``clean``          — died between records; the WAL ends on a
    record boundary (the after-append seam).
  * ``lost_tail``      — died BEFORE the drain thread wrote the last
    enqueued record(s): the mutation applied in memory but never hit
    disk (the before-append seam — the WAL under-reports, and the
    apiserver reconcile must supply the missing truth).
  * ``torn_tail``      — died mid-``write``: the final line is half a
    record (torn write; the loader must truncate, not crash).
  * ``corrupt_tail``   — bit rot / partial sector: the final line
    parses but fails its CRC (the loader must refuse it).
  * ``torn_checkpoint``— died mid-checkpoint-write AFTER the rename
    raced (or the file was later mangled): the checkpoint is
    undecodable, and recovery must fall back to replaying the whole
    WAL — never trust a checkpoint that fails its CRC.

:class:`CrashSchedule` draws one outcome per crash cycle from a single
seeded RNG in call order (the same determinism contract as
:class:`~tpukube.chaos.schedule.FaultSchedule`), and the module's
helpers apply the corresponding mutilation to the journal files AFTER
the sim's ``crash_extender()`` — byte-level, exactly what the loader
will face. Scenario 13 (``tpukube-sim 13``) drives ≥8 such cycles
under the scenario-8 apiserver storm; tests/test_journal.py drives the
``lost_tail`` seam exhaustively (a crash at EVERY record boundary).
"""

from __future__ import annotations

import os
from random import Random
from typing import Optional

#: crash outcomes, in draw-partition order (determinism contract)
CRASH_SEAMS = ("clean", "lost_tail", "torn_tail", "corrupt_tail",
               "torn_checkpoint")


class CrashSchedule:
    """Seeded crash-outcome chooser; one draw per crash cycle. The
    first ``len(seams)`` draws are a seeded permutation of ALL seams —
    a storm with at least that many cycles provably exercises every
    outcome — and later draws are uniform."""

    def __init__(self, seed: int,
                 seams: tuple[str, ...] = CRASH_SEAMS) -> None:
        self.seed = seed
        self._rng = Random(seed)
        self._seams = seams
        first = list(seams)
        self._rng.shuffle(first)
        self._first = first
        self.chosen: list[str] = []

    def next_seam(self) -> str:
        if self._first:
            seam = self._first.pop(0)
        else:
            seam = self._seams[self._rng.randrange(len(self._seams))]
        self.chosen.append(seam)
        return seam

    def apply(self, seam: str, wal_path: str) -> None:
        """Mutilate the journal files for one crash outcome (call after
        the process "died" — i.e. after ``crash_extender()``)."""
        if seam == "clean":
            return
        if seam == "lost_tail":
            drop_wal_records(wal_path, drop=1 + self._rng.randrange(2))
        elif seam == "torn_tail":
            tear_wal_tail(wal_path)
        elif seam == "corrupt_tail":
            corrupt_wal_tail(wal_path)
        elif seam == "torn_checkpoint":
            tear_checkpoint(wal_path + ".ckpt")
        else:
            raise ValueError(f"unknown crash seam {seam!r}")


def _read_lines(path: str) -> Optional[list[bytes]]:
    try:
        with open(path, "rb") as f:
            return f.read().splitlines(keepends=True)
    except OSError:
        return None


def drop_wal_records(path: str, drop: int = 1) -> int:
    """Remove the last ``drop`` complete records — the before-append
    crash: mutations applied in memory whose records never hit disk.
    Returns how many were actually dropped."""
    lines = _read_lines(path)
    if not lines:
        return 0
    drop = min(drop, len(lines))
    with open(path, "wb") as f:
        f.writelines(lines[: len(lines) - drop])
    return drop


def tear_wal_tail(path: str) -> bool:
    """Cut the final record mid-line — the torn-write crash. True if a
    line was actually torn."""
    lines = _read_lines(path)
    if not lines:
        return False
    last = lines[-1]
    if len(last) < 4:
        return False
    with open(path, "wb") as f:
        f.writelines(lines[:-1])
        f.write(last[: len(last) // 2])
    return True


def corrupt_wal_tail(path: str) -> bool:
    """Flip bytes inside the final record's CRC digits so the line
    still parses as JSON but fails verification."""
    lines = _read_lines(path)
    if not lines:
        return False
    last = lines[-1].rstrip(b"\n")
    marker = b'"c":'
    at = last.rfind(marker)
    if at < 0:
        return False
    digits = bytearray(last)
    i = at + len(marker)
    while i < len(digits) and digits[i : i + 1].isdigit():
        # 9s-complement each digit: always a DIFFERENT digit, so the
        # crc value provably changes and the line stays valid JSON
        digits[i] = ord("9") - (digits[i] - ord("0"))
        i += 1
    with open(path, "wb") as f:
        f.writelines(lines[:-1])
        f.write(bytes(digits) + b"\n")
    return True


def tear_checkpoint(path: str) -> bool:
    """Truncate the checkpoint mid-byte (a mid-write crash whose rename
    raced, or later corruption): the loader must refuse it and recovery
    must fall back to replaying the whole WAL."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size < 8:
        return False
    with open(path, "rb+") as f:
        f.truncate(size // 2)
    return True

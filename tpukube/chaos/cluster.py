"""ChaosSimCluster — a SimCluster whose control-plane seams run through
the fault schedule, plus the convergence checks the chaos scenarios
assert (zero leaked reservations, zero ledger/apiserver divergence).

The chaos cluster wires what a hardened production extender wires:

  * the pod store wrapped in :class:`~tpukube.chaos.api.ChaosApiServer`
    (evictions, lifecycle GET-confirms, and the bind effector all take
    injected faults);
  * a real bind effector (``apiserver``-style ``bind_pod``) behind a
    :class:`~tpukube.core.retry.Retrier` + :class:`~tpukube.core.retry.
    CircuitBreaker` — torn bind writes retry into idempotency instead
    of leaving a bound pod the ledger forgot;
  * the eviction executor's GET confirms behind the same retry policy;
  * the extender's degraded gate on the apiserver circuit: while the
    circuit is open, /filter and /bind fail safe (no bind, no
    preemption plan) and ``DegradedMode`` lands in the journal.
"""

from __future__ import annotations

import logging
from random import Random
from typing import Any

from tpukube.apiserver import (
    ApiServerError,
    TERMINAL_PHASES,
    pod_binder,
    transient_api_error,
)
from tpukube.chaos.api import ChaosApiServer
from tpukube.chaos.schedule import FaultSchedule
from tpukube.core import codec, retry
from tpukube.sim.harness import SimCluster

log = logging.getLogger("tpukube.chaos")


class ChaosSimCluster(SimCluster):
    """SimCluster + chaos wiring; see module docstring. ``schedule_``
    drives every injection; the retry/circuit knobs come from the
    config's ``retry_*`` / ``circuit_*`` fields (with fast-test
    overrides below, since scenario walls are seconds, not minutes)."""

    # scenario-scale retry/circuit shape: the production defaults wait
    # tens of seconds; the sim exercises the same code paths at ms
    # scale so `tpukube-sim 8` stays a smoke test
    BIND_POLICY = retry.RetryPolicy(
        max_attempts=6, base_delay=0.001, max_delay=0.01,
        jitter=0.5, deadline=0.0,
    )
    CIRCUIT_THRESHOLD = 3
    CIRCUIT_RESET_S = 0.02

    def __init__(self, config, fault_schedule: FaultSchedule,
                 **kwargs: Any) -> None:
        self._fault_schedule = fault_schedule
        super().__init__(config, **kwargs)

    def _make_store_api(self):
        return ChaosApiServer(super()._make_store_api(),
                              self._fault_schedule)

    def _wire_extender(self) -> None:
        super()._wire_extender()
        threshold = (self.config.circuit_failure_threshold
                     or self.CIRCUIT_THRESHOLD)
        self.circuit = retry.CircuitBreaker(
            failure_threshold=threshold,
            reset_seconds=self.CIRCUIT_RESET_S,
            half_open_probes=self.config.circuit_half_open_probes,
            name="apiserver", journal=self.extender.events,
        )
        self.bind_retrier = retry.Retrier(
            self.BIND_POLICY, name="bind-effector",
            retryable=transient_api_error, circuit=self.circuit,
            rng=Random(self._fault_schedule.seed + 1),
            journal=self.extender.events,
        )
        self.confirm_retrier = retry.Retrier(
            self.BIND_POLICY, name="eviction-confirm",
            retryable=transient_api_error,
            rng=Random(self._fault_schedule.seed + 2),
            journal=self.extender.events,
        )
        # EvictionExecutor GET-confirms through the unified policy
        self._evictions.retrier = self.confirm_retrier
        raw_bind = pod_binder(self._store_api)

        def binder(alloc) -> None:
            try:
                self.bind_retrier.call(lambda: raw_bind(alloc))
            except retry.CircuitOpenError as e:
                raise ApiServerError(str(e)) from e

        self.extender.binder = binder
        # degraded mode: while the apiserver circuit is open the
        # extender fails filter/bind safe instead of planning work it
        # cannot effect
        self.extender.degraded_gate = (
            lambda: ("apiserver circuit open"
                     if self.circuit.is_open() else None)
        )
        # export the channel's retry/circuit counters on /metrics,
        # exactly as the real daemon main wires them
        self.extender.api_retrier = self.bind_retrier
        self.extender.api_circuit = self.circuit

    # fresh-extender metrics/degraded wiring also applies after a
    # scenario-9-style restart: SimCluster.restart_extender calls
    # _wire_extender, so nothing extra is needed here.


def leaked_reservations(cluster: SimCluster) -> list[dict[str, Any]]:
    """Gang reservations that can never complete: uncommitted with zero
    assigned members (a committed gang or one mid-assembly with live
    members is fine — TTL or later binds own those)."""
    leaks = []
    for g in cluster.extender.gang_snapshot():
        if not g["committed"] and g["members_bound"] == 0:
            leaks.append(g)
    return leaks


def ledger_divergence(cluster: SimCluster) -> list[str]:
    """Cross-check the extender's ledger against the pod store (the
    sim's apiserver ground truth). Returns human-readable divergences;
    [] is the scenario acceptance criterion.

      * every live, bound, non-terminal pod with an alloc annotation
        must hold a matching ledger entry (node + device ids);
      * every ledger entry must point at such a pod.

    Terminal-phase pods and unbound pods with annotation residue are
    skipped — those are exactly the states the rebuild/lifecycle
    machinery is DOCUMENTED to skip or release."""
    problems: list[str] = []
    ledger = {a.pod_key: a for a in cluster.extender.state.allocations()}
    seen: set[str] = set()
    for key, pod in sorted(cluster.pods.items()):
        annos = (pod.get("metadata") or {}).get("annotations") or {}
        payload = annos.get(codec.ANNO_ALLOC)
        bound = (pod.get("spec") or {}).get("nodeName")
        phase = (pod.get("status") or {}).get("phase")
        if not payload or not bound or phase in TERMINAL_PHASES:
            continue
        try:
            planned = codec.decode_alloc(payload)
        except codec.CodecError as e:
            problems.append(f"{key}: undecodable alloc annotation: {e}")
            continue
        seen.add(key)
        entry = ledger.get(key)
        if entry is None:
            problems.append(
                f"{key}: bound to {bound} with an alloc annotation but "
                f"absent from the ledger"
            )
            continue
        if entry.node_name != bound:
            problems.append(
                f"{key}: ledger says node {entry.node_name}, pod is "
                f"bound to {bound}"
            )
        if sorted(entry.device_ids) != sorted(planned.device_ids):
            problems.append(
                f"{key}: ledger devices {sorted(entry.device_ids)} != "
                f"annotation devices {sorted(planned.device_ids)}"
            )
    for key in sorted(set(ledger) - seen):
        problems.append(
            f"{key}: in the ledger but no live bound pod carries its "
            f"alloc annotation"
        )
    return problems


def replica_crash_recover(cluster: SimCluster, idx: int,
                          rounds: int = 50) -> dict[str, Any]:
    """ISSUE 13 replica chaos: kill ONE planner replica of a sharded
    cluster mid-flight, let the router's rendezvous janitor abort any
    uncommitted rendezvous holding a part there, converge the
    effectors, cold-restart the replica via ``rebuild_from_pods``,
    and converge again. Returns a report with the aborted rendezvous
    keys, allocations restored, and the post-recovery leak/divergence
    counts — the zero-leak acceptance the caller asserts on."""
    cluster.crash_replica(idx)
    aborted = cluster.extender.sweep()
    converge(cluster, rounds=rounds)
    restored = cluster.restart_replica(idx)
    converge(cluster, rounds=rounds)
    cluster.extender.sweep()
    converge(cluster, rounds=rounds)
    return {
        "replica": idx,
        "rendezvous_aborted": [list(k) for k in aborted],
        "restored_allocs": restored,
        "leaked_reservations": len(leaked_reservations(cluster)),
        "ledger_divergence": len(ledger_divergence(cluster)),
        "audit": cluster.extender.audit_stats(),
    }


def converge(cluster: SimCluster, rounds: int = 50) -> int:
    """Drive the effector loops until quiet (or ``rounds``): evictions
    drained + confirmed, lifecycle resynced. Returns rounds used. Loop
    steps swallow transient (possibly chaos-injected) API errors — the
    real daemons' poll loops do exactly that and try again."""
    for i in range(rounds):
        busy = False
        try:
            busy |= bool(cluster.drain_evictions())
        except ApiServerError as e:
            log.info("converge: eviction drain hit %s; retrying", e)
            busy = True
        try:
            busy |= cluster._lifecycle.check_once()
        except ApiServerError as e:
            log.info("converge: lifecycle resync hit %s; retrying", e)
            busy = True
        if not busy and cluster._evictions.depth() == 0:
            return i + 1
    return rounds

"""ctypes wrapper over libtpuinfo.so (SURVEY.md §2 C2).

The reference consumes libnvidia-ml.so through cgo; here Python consumes the
C++ enumeration shim through ctypes (no pybind11 in this environment — task
brief). The wrapper owns build-on-demand (make), struct marshalling into the
core types, and turning C error returns into :class:`TpuInfoError`.

Thread-safety: libtpuinfo is single-instance; :class:`TpuInfo` serializes
all calls behind a lock, mirroring the reference's NVML init/shutdown
discipline.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import ChipInfo, Health, TopologyCoord

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtpuinfo.so")

ABI_VERSION = 4
_MAX_LINKS = 6


class TpuInfoError(RuntimeError):
    pass


class _Chip(ctypes.Structure):
    _fields_ = [
        ("index", ctypes.c_int32),
        ("chip_id", ctypes.c_char * 64),
        ("coord", ctypes.c_int32 * 3),
        ("hbm_bytes", ctypes.c_int64),
        ("num_cores", ctypes.c_int32),
        ("healthy", ctypes.c_int32),
    ]


class _Mesh(ctypes.Structure):
    _fields_ = [
        ("dims", ctypes.c_int32 * 3),
        ("host_block", ctypes.c_int32 * 3),
        ("torus", ctypes.c_int32 * 3),
    ]


def _ensure_built() -> str:
    """Build libtpuinfo.so if missing or older than its sources."""
    src = os.path.join(_NATIVE_DIR, "tpuinfo.cpp")
    hdr = os.path.join(_NATIVE_DIR, "tpuinfo.h")
    if os.path.exists(_LIB_PATH):
        lib_mtime = os.path.getmtime(_LIB_PATH)
        if all(os.path.getmtime(p) <= lib_mtime for p in (src, hdr)):
            return _LIB_PATH
    proc = subprocess.run(
        ["make", "-C", _NATIVE_DIR, "libtpuinfo.so"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise TpuInfoError(
            f"failed to build libtpuinfo.so:\n{proc.stdout}\n{proc.stderr}"
        )
    return _LIB_PATH


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_ensure_built())
        lib.tpuinfo_abi_version.restype = ctypes.c_int
        # check ABI FIRST: binding v2 symbols against a stale v1 .so would
        # die with an opaque AttributeError before the guard below ran
        abi = lib.tpuinfo_abi_version()
        if abi != ABI_VERSION:
            raise TpuInfoError(f"libtpuinfo ABI {abi} != expected {ABI_VERSION}")
        lib.tpuinfo_init.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.tpuinfo_init.restype = ctypes.c_int
        lib.tpuinfo_shutdown.restype = ctypes.c_int
        lib.tpuinfo_mesh_get.argtypes = [ctypes.POINTER(_Mesh)]
        lib.tpuinfo_mesh_get.restype = ctypes.c_int
        lib.tpuinfo_chip_count.restype = ctypes.c_int
        lib.tpuinfo_chip_get.argtypes = [ctypes.c_int32, ctypes.POINTER(_Chip)]
        lib.tpuinfo_chip_get.restype = ctypes.c_int
        lib.tpuinfo_chip_links.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.tpuinfo_chip_links.restype = ctypes.c_int
        lib.tpuinfo_inject_fault.argtypes = [ctypes.c_int32, ctypes.c_int32]
        lib.tpuinfo_inject_fault.restype = ctypes.c_int
        lib.tpuinfo_inject_link_fault.argtypes = [ctypes.c_int32] * 7
        lib.tpuinfo_inject_link_fault.restype = ctypes.c_int
        lib.tpuinfo_link_faults.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.tpuinfo_link_faults.restype = ctypes.c_int
        lib.tpuinfo_last_error.restype = ctypes.c_char_p
        lib.tpuinfo_source.restype = ctypes.c_char_p
        lib.tpuinfo_probe.restype = ctypes.c_int
        _lib = lib
        return lib


def sim_spec(
    mesh: MeshSpec,
    host: str,
    hbm_bytes: int,
    cores: int = 2,
    origin: Optional[tuple[int, int, int]] = None,
) -> str:
    """Render the key=value sim spec libtpuinfo parses.

    ``origin`` pins the host block's chip-coord origin explicitly; without
    it the C side derives the origin from the host-i-j-k name convention
    (so free-form node names — e.g. slice-prefixed — need origin)."""

    def triple(t) -> str:
        return ",".join(str(int(v)) for v in t)

    out = (
        f"dims={triple(mesh.dims)}\n"
        f"host_block={triple(mesh.host_block)}\n"
        f"torus={triple(mesh.torus)}\n"
        f"host={host}\n"
        f"hbm={hbm_bytes}\n"
        f"cores={cores}\n"
    )
    if origin is not None:
        out += f"origin={triple(origin)}\n"
    return out


def default_libtpu_path() -> Optional[str]:
    """Locate libtpu.so: loader path first (None lets the C side use the
    plain soname), else inside the ``libtpu`` Python package (how Cloud
    TPU images ship it — it is not on the default loader path there)."""
    import ctypes.util
    import importlib.util

    if ctypes.util.find_library("tpu"):
        return None
    try:
        spec = importlib.util.find_spec("libtpu")
    except (ImportError, ValueError):
        return None
    if spec is None or spec.origin is None:
        return None
    path = os.path.join(os.path.dirname(spec.origin), "libtpu.so")
    return path if os.path.exists(path) else None


class TpuInfo:
    """One initialized enumeration session (context manager).

    >>> with TpuInfo("sim", sim_spec(mesh, "host-0-0-0", 16 << 30)) as ti:
    ...     chips = ti.chips()
    """

    _instance_lock = threading.Lock()

    def __init__(self, backend: str, spec: Optional[str] = None):
        if backend == "real" and "libtpu=" not in (spec or ""):
            found = default_libtpu_path()
            if found is not None:
                spec = spec or ""
                if spec and not spec.endswith("\n"):
                    spec += "\n"
                spec += f"libtpu={found}\n"
        self._lib = _load()
        self._lock = threading.Lock()
        self._open = False
        with TpuInfo._instance_lock:
            rc = self._lib.tpuinfo_init(
                backend.encode(), spec.encode() if spec is not None else None
            )
            if rc != 0:
                raise TpuInfoError(self._last_error())
            self._open = True

    def _last_error(self) -> str:
        return (self._lib.tpuinfo_last_error() or b"").decode()

    def _check_open(self) -> None:
        if not self._open:
            raise TpuInfoError("TpuInfo session is closed")

    def close(self) -> None:
        # _instance_lock serializes shutdown against a concurrent __init__ of
        # a new session: the C globals are not thread-safe.
        with TpuInfo._instance_lock, self._lock:
            if self._open:
                self._open = False
                if self._lib.tpuinfo_shutdown() != 0:
                    raise TpuInfoError(self._last_error())

    def __enter__(self) -> "TpuInfo":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:
        # A leaked session would wedge the process-wide singleton; release
        # best-effort on GC (explicit close() remains the contract).
        try:
            self.close()
        # tpukube: allow(exception-hygiene) GC-time best effort: logging machinery may already be finalized at interpreter shutdown
        except Exception:
            pass

    def mesh(self) -> MeshSpec:
        with self._lock:
            self._check_open()
            m = _Mesh()
            if self._lib.tpuinfo_mesh_get(ctypes.byref(m)) != 0:
                raise TpuInfoError(self._last_error())
            return MeshSpec(
                dims=tuple(m.dims),
                host_block=tuple(m.host_block),
                torus=tuple(bool(v) for v in m.torus),
            )

    def chip_count(self) -> int:
        with self._lock:
            self._check_open()
            n = self._lib.tpuinfo_chip_count()
            if n < 0:
                raise TpuInfoError(self._last_error())
            return n

    def source(self) -> str:
        """Where the inventory came from: "sim", "pjrt" (runtime
        introspection through the PJRT C API), or "table (<reason>)"
        (liveness-only fallback)."""
        with self._lock:
            self._check_open()
            return (self._lib.tpuinfo_source() or b"").decode()

    def probe(self) -> bool:
        """Real-backend health canary (see tpuinfo.h tpuinfo_probe): True
        when the canary passed (chips healthy), False when it failed and
        every chip was marked unhealthy. Sim backend: always True (sim
        health is driven by inject_fault)."""
        with self._lock:
            self._check_open()
            rc = self._lib.tpuinfo_probe()
            if rc < 0:
                raise TpuInfoError(self._last_error())
            return bool(rc)

    def chips(self) -> list[ChipInfo]:
        with self._lock:
            self._check_open()
            n = self._lib.tpuinfo_chip_count()
            if n < 0:
                raise TpuInfoError(self._last_error())
            out: list[ChipInfo] = []
            for i in range(n):
                c = _Chip()
                if self._lib.tpuinfo_chip_get(i, ctypes.byref(c)) != 0:
                    raise TpuInfoError(self._last_error())
                out.append(
                    ChipInfo(
                        chip_id=c.chip_id.decode(),
                        index=int(c.index),
                        coord=TopologyCoord(*c.coord),
                        hbm_bytes=int(c.hbm_bytes),
                        num_cores=int(c.num_cores),
                        health=Health.HEALTHY if c.healthy else Health.UNHEALTHY,
                    )
                )
            return out

    def links(self, index: int) -> list[TopologyCoord]:
        """ICI neighbor coords of a chip — the NVLink-table analog."""
        with self._lock:
            self._check_open()
            buf = (ctypes.c_int32 * (3 * _MAX_LINKS))()
            n = self._lib.tpuinfo_chip_links(index, buf, _MAX_LINKS)
            if n < 0:
                raise TpuInfoError(self._last_error())
            return [
                TopologyCoord(buf[3 * i], buf[3 * i + 1], buf[3 * i + 2])
                for i in range(n)
            ]

    def inject_fault(self, index: int, healthy: bool = False) -> None:
        """Flip a chip's health (sim backend only) — the XID-event analog."""
        with self._lock:
            self._check_open()
            if self._lib.tpuinfo_inject_fault(index, 1 if healthy else 0) != 0:
                raise TpuInfoError(self._last_error())

    def inject_link_fault(
        self, a: TopologyCoord, b: TopologyCoord, up: bool = False
    ) -> None:
        """Mark the ICI link between adjacent chips ``a``/``b`` down (or back
        up) — sim backend only; the NVLink lane-error analog."""
        with self._lock:
            self._check_open()
            a, b = TopologyCoord.of(a), TopologyCoord.of(b)
            rc = self._lib.tpuinfo_inject_link_fault(
                a.x, a.y, a.z, b.x, b.y, b.z, 1 if up else 0
            )
            if rc != 0:
                raise TpuInfoError(self._last_error())

    def link_faults(self) -> list[tuple[TopologyCoord, TopologyCoord]]:
        """All downed ICI links, canonical (a <= b) coord pairs."""
        with self._lock:
            self._check_open()
            max_n = 16
            while True:
                buf = (ctypes.c_int32 * (6 * max_n))()
                n = self._lib.tpuinfo_link_faults(buf, max_n)
                if n < 0:
                    raise TpuInfoError(self._last_error())
                if n <= max_n:
                    return [
                        (
                            TopologyCoord(buf[6 * i], buf[6 * i + 1], buf[6 * i + 2]),
                            TopologyCoord(buf[6 * i + 3], buf[6 * i + 4], buf[6 * i + 5]),
                        )
                        for i in range(n)
                    ]
                max_n = n

"""Native bindings (L1): ctypes wrapper over libtpuinfo.so."""

from tpukube.native.tpuinfo import TpuInfo, TpuInfoError, sim_spec  # noqa: F401

/* libtpuinfo — native TPU chip enumeration shim (C ABI).
 *
 * TPU-native analog of the reference's NVML cgo binding (SURVEY.md §2 C2):
 * where KubeGPU wraps libnvidia-ml.so (device count/UUID/memory, NVLink
 * topology, XID health events), this shim exposes chip enumeration for a
 * node agent: chip id, mesh coordinate, HBM bytes, core count, health, and
 * the ICI link table (mesh adjacency).
 *
 * Two backends, selected at init:
 *   "sim"  — topology from a key=value spec (the load-bearing backend: no
 *            cluster or multi-chip hardware exists in CI; BASELINE config 1
 *            requires a fake-device path).
 *   "real" — runtime introspection through the PJRT C API (libtpu's
 *            GetPjrtApi): device count, kind, chip coords, and HBM limit
 *            read from a short-lived PJRT client, released immediately
 *            (TPU runtimes are single-owner). Falls back to libtpu.so
 *            liveness + per-generation HBM/core tables when a client
 *            cannot be created (chip owned by another process, version
 *            skew); tpuinfo_source() reports which path produced the
 *            inventory.
 *
 * Consumed from Python via ctypes (tpukube/native/tpuinfo.py). All calls
 * return 0 on success, -1 on error; tpuinfo_last_error() describes the
 * failure. Not thread-safe by design: the node agent owns one instance
 * behind a lock (mirrors NVML's init/shutdown discipline).
 */
#ifndef TPUKUBE_TPUINFO_H
#define TPUKUBE_TPUINFO_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define TPUINFO_ABI_VERSION 4
#define TPUINFO_MAX_ID 64

typedef struct {
  int32_t index;              /* node-local chip index */
  char chip_id[TPUINFO_MAX_ID];
  int32_t coord[3];           /* global mesh coordinate (x, y, z) */
  int64_t hbm_bytes;
  int32_t num_cores;          /* TensorCores per chip */
  int32_t healthy;            /* 1 healthy, 0 unhealthy */
} tpuinfo_chip;

typedef struct {
  int32_t dims[3];
  int32_t host_block[3];
  int32_t torus[3];
} tpuinfo_mesh;

int tpuinfo_abi_version(void);

/* backend: "sim" or "real". spec: key=value lines (sim), or NULL (real).
 * Sim spec keys: dims=X,Y,Z  host_block=X,Y,Z  torus=0|1,0|1,0|1
 *                host=host-i-j-k  hbm=<bytes>  cores=<n>
 * Real spec keys (all optional): libtpu=<path>  gen=v4|v5e|v5p|v6e  chips=<n>
 * Real-backend generation default: env PALLAS_AXON_TPU_GEN if set (the env
 * this machine's TPU tunnel exports), else "v5e"; an explicit gen= spec key
 * always wins.
 */
int tpuinfo_init(const char* backend, const char* spec);
int tpuinfo_shutdown(void);

int tpuinfo_mesh_get(tpuinfo_mesh* out);
int tpuinfo_chip_count(void);
int tpuinfo_chip_get(int32_t index, tpuinfo_chip* out);

/* ICI link table: write up to max neighbor coords (x,y,z triples) of chip
 * `index` into out (length 3*max). Returns neighbor count, or -1. */
int tpuinfo_chip_links(int32_t index, int32_t* out, int32_t max);

/* Health manipulation — the sim analog of an NVML XID event (sim only). */
int tpuinfo_inject_fault(int32_t index, int32_t healthy);

/* ICI link faults (ABI v2). A fault is an unordered pair of mesh-adjacent
 * chip coords whose link is down — the TPU analog of an NVLink lane error.
 * inject (sim only): up=0 marks the link down, up=1 restores it; the pair
 * must be mesh-adjacent (torus wrap honored) or -1 is returned.
 * faults: write up to `max` downed links into out (6 ints per entry: ax,
 * ay, az, bx, by, bz, pair canonicalized a<=b lexicographically). Returns
 * the total downed-link count (may exceed max; callers re-ask), or -1. */
int tpuinfo_inject_link_fault(int32_t ax, int32_t ay, int32_t az,
                              int32_t bx, int32_t by, int32_t bz,
                              int32_t up);
int tpuinfo_link_faults(int32_t* out, int32_t max);

const char* tpuinfo_last_error(void);

/* Where the current inventory came from (ABI v3): "sim", "pjrt" (runtime
 * introspection), or "table (<reason pjrt was unavailable>)". Empty string
 * before init. */
const char* tpuinfo_source(void);

/* Device liveness re-probe (ABI v4) — the real backend's health canary,
 * closing SURVEY §4.4's real-mode gap (sim health comes from
 * inject_fault; without this the real backend set healthy=1 at init and
 * could never change its mind). Modes, via the real spec key `probe=`:
 *   client   — re-run the PJRT canary enumeration (client create ->
 *              devices -> destroy); failure flips EVERY chip Unhealthy,
 *              recovery flips them back. OPT-IN: on single-owner TPU
 *              runtimes a workload holding the chip fails the canary
 *              while the chip is perfectly healthy — choose this only
 *              where the runtime tolerates a second short-lived client
 *              (multi-client runtimes, dedicated-agent nodes).
 *   liveness — libtpu.so still loaded and exposing GetPjrtApi. Weak but
 *              false-alarm-free; the default.
 *   off      — probe never changes health.
 * Returns 1 (canary passed: chips healthy), 0 (failed: chips marked
 * unhealthy), -1 on error. Sim backend: no-op, returns 1. */
int tpuinfo_probe(void);

#ifdef __cplusplus
}
#endif

#endif /* TPUKUBE_TPUINFO_H */

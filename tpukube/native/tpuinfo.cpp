/* libtpuinfo implementation. See tpuinfo.h for the contract.
 *
 * Replaces the reference's cgo->libnvidia-ml.so layer (SURVEY.md §2 C2)
 * with a TPU-native shim: mesh geometry instead of NVLink pair queries,
 * libtpu.so liveness instead of NVML init, spec-driven sim topology for
 * the CPU-only control plane the tests run on.
 */
#include "tpuinfo.h"

#include <dlfcn.h>
#include <stddef.h>

#include <algorithm>
#include <array>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#ifdef TPUINFO_HAVE_PJRT
/* Public OpenXLA PJRT C API header (shipped in this image by the
 * tensorflow wheel; see Makefile PJRT_INC autodiscovery). Pure ABI
 * declarations — versioned via struct_size, checked below. */
#include "xla/pjrt/c/pjrt_c_api.h"
#endif

namespace {

using LinkPair = std::array<int32_t, 6>;  /* ax,ay,az,bx,by,bz, a<=b lex */

struct State {
  bool initialized = false;
  bool is_sim = false;
  tpuinfo_mesh mesh{};
  std::vector<tpuinfo_chip> chips;
  std::vector<LinkPair> bad_links;
  std::string source = "";  /* "sim" | "pjrt" | "table (<why no pjrt>)" */
  /* real-backend probe context (ABI v4, see tpuinfo.h tpuinfo_probe) */
  std::string probe_mode = "";  /* "client" | "liveness" | "off"; "" = sim */
  std::string libtpu_path;
  void* get_api_sym = nullptr;
};

State g_state;
std::string g_last_error = "";

void set_error(const std::string& msg) { g_last_error = msg; }

bool parse_triple(const std::string& val, int32_t out[3]) {
  return std::sscanf(val.c_str(), "%d,%d,%d", &out[0], &out[1], &out[2]) == 3;
}

/* Per-generation chip facts (real backend). HBM per chip / TensorCores per
 * chip for recent Cloud TPU generations; the sim backend takes these from
 * its spec instead. */
struct GenInfo {
  const char* name;
  int64_t hbm_bytes;
  int32_t cores;
};
const GenInfo kGenTable[] = {
    {"v4", 32LL << 30, 2},
    {"v5e", 16LL << 30, 1},
    {"v5litepod", 16LL << 30, 1},
    {"v5p", 95LL << 30, 2},
    {"v6e", 32LL << 30, 1},
};

std::vector<std::pair<std::string, std::string>> parse_spec(const char* spec) {
  std::vector<std::pair<std::string, std::string>> kv;
  if (spec == nullptr) return kv;
  std::string s(spec);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t nl = s.find('\n', pos);
    if (nl == std::string::npos) nl = s.size();
    std::string line = s.substr(pos, nl - pos);
    pos = nl + 1;
    while (!line.empty() && (line.back() == '\r' || line.back() == ' '))
      line.pop_back();
    if (line.empty() || line[0] == '#') continue;
    size_t eq = line.find('=');
    if (eq == std::string::npos) continue;
    kv.emplace_back(line.substr(0, eq), line.substr(eq + 1));
  }
  return kv;
}

int init_sim(const char* spec) {
  int32_t dims[3] = {4, 4, 4};
  int32_t host_block[3] = {2, 2, 1};
  int32_t torus[3] = {0, 0, 0};
  std::string host = "host-0-0-0";
  int64_t hbm = 95LL << 30;
  int32_t cores = 2;
  int32_t origin[3] = {0, 0, 0};
  bool have_origin = false;

  for (const auto& [key, val] : parse_spec(spec)) {
    if (key == "dims") {
      if (!parse_triple(val, dims)) { set_error("sim: bad dims: " + val); return -1; }
    } else if (key == "host_block") {
      if (!parse_triple(val, host_block)) { set_error("sim: bad host_block: " + val); return -1; }
    } else if (key == "torus") {
      if (!parse_triple(val, torus)) { set_error("sim: bad torus: " + val); return -1; }
    } else if (key == "host") {
      host = val;
    } else if (key == "origin") {
      if (!parse_triple(val, origin)) { set_error("sim: bad origin: " + val); return -1; }
      have_origin = true;
    } else if (key == "hbm") {
      hbm = std::strtoll(val.c_str(), nullptr, 10);
      if (hbm <= 0) { set_error("sim: bad hbm: " + val); return -1; }
    } else if (key == "cores") {
      cores = std::atoi(val.c_str());
      if (cores <= 0) { set_error("sim: bad cores: " + val); return -1; }
    } else {
      set_error("sim: unknown spec key: " + key);
      return -1;
    }
  }
  for (int a = 0; a < 3; ++a) {
    if (dims[a] <= 0 || host_block[a] <= 0 || dims[a] % host_block[a] != 0) {
      set_error("sim: host_block must divide dims and both be positive");
      return -1;
    }
  }
  if (have_origin) {
    /* explicit chip-coord origin of the host block: the host name is then
     * a free-form label (multi-slice sims prefix slice ids) */
    for (int a = 0; a < 3; ++a) {
      if (origin[a] < 0 || origin[a] + host_block[a] > dims[a] ||
          origin[a] % host_block[a] != 0) {
        set_error("sim: origin not host_block-aligned inside dims");
        return -1;
      }
    }
  } else {
    int hg[3];  /* host grid position parsed from the host name */
    if (std::sscanf(host.c_str(), "host-%d-%d-%d", &hg[0], &hg[1], &hg[2]) != 3) {
      set_error("sim: malformed host name (want host-i-j-k, or pass origin=): " + host);
      return -1;
    }
    for (int a = 0; a < 3; ++a) {
      if (hg[a] < 0 || hg[a] >= dims[a] / host_block[a]) {
        set_error("sim: host outside host grid: " + host);
        return -1;
      }
      origin[a] = hg[a] * host_block[a];
    }
  }

  std::memcpy(g_state.mesh.dims, dims, sizeof dims);
  std::memcpy(g_state.mesh.host_block, host_block, sizeof host_block);
  std::memcpy(g_state.mesh.torus, torus, sizeof torus);
  g_state.chips.clear();

  /* Mint this host's chips: x fastest within the host block, matching
   * MeshSpec.coords_of_host on the Python side. */
  int32_t idx = 0;
  for (int dz = 0; dz < host_block[2]; ++dz)
    for (int dy = 0; dy < host_block[1]; ++dy)
      for (int dx = 0; dx < host_block[0]; ++dx) {
        tpuinfo_chip c{};
        c.index = idx;
        c.coord[0] = origin[0] + dx;
        c.coord[1] = origin[1] + dy;
        c.coord[2] = origin[2] + dz;
        std::snprintf(c.chip_id, TPUINFO_MAX_ID, "%s-chip-%d", host.c_str(), idx);
        c.hbm_bytes = hbm;
        c.num_cores = cores;
        c.healthy = 1;
        g_state.chips.push_back(c);
        ++idx;
      }
  g_state.is_sim = true;
  g_state.source = "sim";
  return 0;
}

#ifdef TPUINFO_HAVE_PJRT
/* Real enumeration through the PJRT C API (SURVEY.md §2 C2: the NVML
 * device-query analog). Creates a client, reads each addressable device's
 * id / kind / coords / HBM limit, and destroys the client immediately —
 * TPU runtimes are single-owner, so the agent must not squat on the chips
 * past enumeration. Any failure returns false with a reason; the caller
 * falls back to the static generation table. */
bool enumerate_pjrt(void* get_api_sym, std::string* why,
                    std::vector<tpuinfo_chip>* chips_out,
                    tpuinfo_mesh* mesh_out) {
  typedef const PJRT_Api* (*GetPjrtApiFn)();
  const PJRT_Api* api = reinterpret_cast<GetPjrtApiFn>(get_api_sym)();
  if (api == nullptr) { *why = "GetPjrtApi returned null"; return false; }
  if (api->pjrt_api_version.major_version != PJRT_API_MAJOR) {
    *why = "PJRT major version mismatch";
    return false;
  }
  /* The plugin may implement an older minor version with a smaller PJRT_Api
   * struct: every function pointer we touch must lie inside it. */
#define TPUINFO_HAVE_FN(f) \
  (api->struct_size >= offsetof(PJRT_Api, f) + sizeof(void*) && api->f)
  if (!TPUINFO_HAVE_FN(PJRT_Error_Destroy) ||
      !TPUINFO_HAVE_FN(PJRT_Error_Message) ||
      !TPUINFO_HAVE_FN(PJRT_Plugin_Initialize) ||
      !TPUINFO_HAVE_FN(PJRT_Client_Create) ||
      !TPUINFO_HAVE_FN(PJRT_Client_Destroy) ||
      !TPUINFO_HAVE_FN(PJRT_Client_Devices) ||
      !TPUINFO_HAVE_FN(PJRT_Device_GetDescription) ||
      !TPUINFO_HAVE_FN(PJRT_Device_IsAddressable) ||
      !TPUINFO_HAVE_FN(PJRT_DeviceDescription_Id) ||
      !TPUINFO_HAVE_FN(PJRT_DeviceDescription_Kind) ||
      !TPUINFO_HAVE_FN(PJRT_DeviceDescription_Attributes)) {
    *why = "plugin PJRT_Api too old (missing required entry points)";
    return false;
  }
  bool have_memstats = TPUINFO_HAVE_FN(PJRT_Device_MemoryStats);
#undef TPUINFO_HAVE_FN

  auto take_error = [api](PJRT_Error* e) -> std::string {
    if (e == nullptr) return "";
    PJRT_Error_Message_Args ma;
    std::memset(&ma, 0, sizeof ma);
    ma.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
    ma.error = e;
    api->PJRT_Error_Message(&ma);
    std::string msg(ma.message, ma.message_size);
    PJRT_Error_Destroy_Args da;
    std::memset(&da, 0, sizeof da);
    da.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
    da.error = e;
    api->PJRT_Error_Destroy(&da);
    return msg.empty() ? "unknown PJRT error" : msg;
  };

  PJRT_Plugin_Initialize_Args pia;
  std::memset(&pia, 0, sizeof pia);
  pia.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  std::string err = take_error(api->PJRT_Plugin_Initialize(&pia));
  if (!err.empty()) { *why = "Plugin_Initialize: " + err; return false; }

  PJRT_Client_Create_Args ca;
  std::memset(&ca, 0, sizeof ca);
  ca.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  err = take_error(api->PJRT_Client_Create(&ca));
  if (!err.empty()) { *why = "Client_Create: " + err; return false; }
  PJRT_Client* client = ca.client;

  auto destroy_client = [api, client]() {
    PJRT_Client_Destroy_Args cda;
    std::memset(&cda, 0, sizeof cda);
    cda.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
    cda.client = client;
    PJRT_Error* e = api->PJRT_Client_Destroy(&cda);
    if (e != nullptr) {
      PJRT_Error_Destroy_Args da;
      std::memset(&da, 0, sizeof da);
      da.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
      da.error = e;
      api->PJRT_Error_Destroy(&da);
    }
  };

  PJRT_Client_Devices_Args dva;
  std::memset(&dva, 0, sizeof dva);
  dva.struct_size = PJRT_Client_Devices_Args_STRUCT_SIZE;
  dva.client = client;
  err = take_error(api->PJRT_Client_Devices(&dva));
  if (!err.empty()) {
    destroy_client();
    *why = "Client_Devices: " + err;
    return false;
  }

  /* One PJRT device == one core (or one megacore); group by chip coords.
   * coords come from the TPU plugin's "coords" int64[3] attribute. */
  struct ChipAgg {
    int32_t coord[3] = {0, 0, 0};
    bool have_coord = false;
    int32_t cores = 0;
    int64_t hbm = 0;
    int min_id = INT32_MAX;
    std::string kind;
  };
  std::map<std::array<int64_t, 3>, ChipAgg> by_coord;
  int fallback_x = 0;
  int64_t wrap[3] = {0, 0, 0};
  bool have_wrap = false;

  for (size_t i = 0; i < dva.num_devices; ++i) {
    PJRT_Device* dev = dva.devices[i];
    PJRT_Device_IsAddressable_Args aa;
    std::memset(&aa, 0, sizeof aa);
    aa.struct_size = PJRT_Device_IsAddressable_Args_STRUCT_SIZE;
    aa.device = dev;
    if (!take_error(api->PJRT_Device_IsAddressable(&aa)).empty() ||
        !aa.is_addressable) {
      continue;  /* another host's device: not this node's inventory */
    }
    PJRT_Device_GetDescription_Args ga;
    std::memset(&ga, 0, sizeof ga);
    ga.struct_size = PJRT_Device_GetDescription_Args_STRUCT_SIZE;
    ga.device = dev;
    err = take_error(api->PJRT_Device_GetDescription(&ga));
    if (!err.empty()) { destroy_client(); *why = "GetDescription: " + err; return false; }

    PJRT_DeviceDescription_Id_Args ida;
    std::memset(&ida, 0, sizeof ida);
    ida.struct_size = PJRT_DeviceDescription_Id_Args_STRUCT_SIZE;
    ida.device_description = ga.device_description;
    take_error(api->PJRT_DeviceDescription_Id(&ida));

    PJRT_DeviceDescription_Kind_Args ka;
    std::memset(&ka, 0, sizeof ka);
    ka.struct_size = PJRT_DeviceDescription_Kind_Args_STRUCT_SIZE;
    ka.device_description = ga.device_description;
    std::string kind;
    if (take_error(api->PJRT_DeviceDescription_Kind(&ka)).empty())
      kind.assign(ka.device_kind, ka.device_kind_size);

    std::array<int64_t, 3> coords{fallback_x, 0, 0};
    bool have_coord = false;
    PJRT_DeviceDescription_Attributes_Args ata;
    std::memset(&ata, 0, sizeof ata);
    ata.struct_size = PJRT_DeviceDescription_Attributes_Args_STRUCT_SIZE;
    ata.device_description = ga.device_description;
    if (take_error(api->PJRT_DeviceDescription_Attributes(&ata)).empty()) {
      for (size_t a = 0; a < ata.num_attributes; ++a) {
        const PJRT_NamedValue& nv = ata.attributes[a];
        std::string name(nv.name, nv.name_size);
        if (name == "coords" &&
            nv.type == PJRT_NamedValue_kInt64List && nv.value_size == 3) {
          coords = {nv.int64_array_value[0], nv.int64_array_value[1],
                    nv.int64_array_value[2]};
          have_coord = true;
        } else if (name == "wrap" &&
                   nv.type == PJRT_NamedValue_kInt64List &&
                   nv.value_size == 3) {
          /* per-axis torus wrap flags, when the runtime exposes them */
          wrap[0] = nv.int64_array_value[0];
          wrap[1] = nv.int64_array_value[1];
          wrap[2] = nv.int64_array_value[2];
          have_wrap = true;
        }
      }
    }
    /* A device without the coords attribute gets a synthetic (i,0,0) —
     * but ONLY while no real coord occupies that slot: silently merging a
     * synthetic chip into a real one would corrupt the inventory (core
     * counts, HBM, ids). Mixed real/synthetic coords that collide mean
     * the plugin's metadata cannot be trusted — reject enumeration and
     * let the caller fall back to the honest table. */
    if (!have_coord) {
      auto it = by_coord.find(coords);
      if (it != by_coord.end() && it->second.have_coord) {
        destroy_client();
        *why = "synthetic fallback coord collides with a runtime-reported "
               "coord (plugin reports coords for only some devices)";
        return false;
      }
      ++fallback_x;
    } else {
      auto it = by_coord.find(coords);
      if (it != by_coord.end() && !it->second.have_coord) {
        destroy_client();
        *why = "runtime-reported coord collides with a synthetic fallback "
               "coord (plugin reports coords for only some devices)";
        return false;
      }
    }

    int64_t hbm = 0;
    if (have_memstats) {
      PJRT_Device_MemoryStats_Args msa;
      std::memset(&msa, 0, sizeof msa);
      msa.struct_size = PJRT_Device_MemoryStats_Args_STRUCT_SIZE;
      msa.device = dev;
      if (take_error(api->PJRT_Device_MemoryStats(&msa)).empty() &&
          msa.bytes_limit_is_set) {
        hbm = msa.bytes_limit;
      }
    }

    ChipAgg& agg = by_coord[coords];
    agg.coord[0] = (int32_t)coords[0];
    agg.coord[1] = (int32_t)coords[1];
    agg.coord[2] = (int32_t)coords[2];
    agg.have_coord = have_coord;
    agg.cores += 1;
    if (hbm > agg.hbm) agg.hbm = hbm;  /* cores share the chip's HBM */
    if (ida.id < agg.min_id) agg.min_id = ida.id;
    if (agg.kind.empty()) agg.kind = kind;
  }
  destroy_client();
  if (by_coord.empty()) { *why = "no addressable PJRT devices"; return false; }

  /* Local coords may sit anywhere in the global slice; the mesh this
   * enumeration can honestly report is the bounding box of what it saw
   * (single-host dev boxes get exact dims; multi-host layouts override
   * geometry via config/annotations). */
  int32_t mn[3] = {INT32_MAX, INT32_MAX, INT32_MAX}, mx[3] = {0, 0, 0};
  for (const auto& [c, agg] : by_coord) {
    for (int a = 0; a < 3; ++a) {
      mn[a] = std::min(mn[a], agg.coord[a]);
      mx[a] = std::max(mx[a], agg.coord[a]);
    }
  }
  for (int a = 0; a < 3; ++a) {
    mesh_out->dims[a] = mx[a] + 1;
    mesh_out->host_block[a] = mx[a] - mn[a] + 1;
    /* torus wraps only when the runtime said so (the "wrap" attribute);
     * otherwise 0 — the honest default for a bounding-box mesh. Config
     * can still override for real nodes (device manager, real_torus). */
    mesh_out->torus[a] = have_wrap && wrap[a] ? 1 : 0;
  }
  chips_out->clear();
  int32_t idx = 0;
  for (const auto& [c, agg] : by_coord) {
    tpuinfo_chip chip{};
    chip.index = idx++;
    chip.coord[0] = agg.coord[0];
    chip.coord[1] = agg.coord[1];
    chip.coord[2] = agg.coord[2];
    std::snprintf(chip.chip_id, TPUINFO_MAX_ID, "%s-%d",
                  agg.kind.empty() ? "tpu" : agg.kind.c_str(), agg.min_id);
    chip.hbm_bytes = agg.hbm;
    chip.num_cores = agg.cores;
    chip.healthy = 1;
    chips_out->push_back(chip);
  }
  return true;
}
#endif  /* TPUINFO_HAVE_PJRT */

int init_real(const char* spec) {
  std::string libtpu_path = "libtpu.so";
  std::string gen = "v5e";
  std::string probe_mode = "";  /* "" = default per enumeration outcome */
  int32_t nchips = 1;
  if (const char* env_gen = std::getenv("PALLAS_AXON_TPU_GEN")) gen = env_gen;
  for (const auto& [key, val] : parse_spec(spec)) {
    if (key == "libtpu") libtpu_path = val;
    else if (key == "gen") gen = val;
    else if (key == "probe") {
      if (val != "client" && val != "liveness" && val != "off") {
        set_error("real: probe must be client|liveness|off, got: " + val);
        return -1;
      }
#ifndef TPUINFO_HAVE_PJRT
      if (val == "client") {
        set_error("real: probe=client requires a PJRT-enabled build");
        return -1;
      }
#endif
      probe_mode = val;
    } else if (key == "chips") {
      nchips = std::atoi(val.c_str());
      if (nchips <= 0) { set_error("real: bad chips: " + val); return -1; }
    } else { set_error("real: unknown spec key: " + key); return -1; }
  }

  const GenInfo* gi = nullptr;
  for (const auto& g : kGenTable)
    if (gen == g.name) { gi = &g; break; }
  if (gi == nullptr) {
    set_error("real: unknown TPU generation: " + gen);
    return -1;
  }

  /* Liveness: libtpu.so must load and expose a PJRT entry point. This is
   * the TPU analog of nvmlInit succeeding. RTLD_NOLOAD-first so we never
   * double-initialize a runtime the host process already owns. */
  void* h = dlopen(libtpu_path.c_str(), RTLD_LAZY | RTLD_NOLOAD);
  if (h == nullptr) h = dlopen(libtpu_path.c_str(), RTLD_LAZY | RTLD_LOCAL);
  if (h == nullptr) {
    set_error(std::string("real: cannot load libtpu: ") + dlerror());
    return -1;
  }
  void* get_api = dlsym(h, "GetPjrtApi");
  if (get_api == nullptr) {
    set_error("real: libtpu loaded but GetPjrtApi missing — not a PJRT libtpu");
    dlclose(h);
    return -1;
  }
  /* handle intentionally retained for process lifetime (liveness probe) */
  g_state.libtpu_path = libtpu_path;
  g_state.get_api_sym = get_api;

  /* First choice: ask the runtime itself (PJRT client; device id, kind,
   * coords, HBM limit). The spec string / generation table is the
   * FALLBACK for environments where a client cannot be created (chip
   * already owned by another process, version-skewed tunnel, ...). */
  std::string why = "built without PJRT header";
#ifdef TPUINFO_HAVE_PJRT
  if (enumerate_pjrt(get_api, &why, &g_state.chips, &g_state.mesh)) {
    for (auto& c : g_state.chips) {
      if (c.hbm_bytes <= 0) c.hbm_bytes = gi->hbm_bytes;  /* stats absent */
    }
    g_state.is_sim = false;
    g_state.source = "pjrt";
    g_state.probe_mode = probe_mode.empty() ? "liveness" : probe_mode;
    return 0;
  }
#endif
  g_state.mesh = tpuinfo_mesh{{nchips, 1, 1}, {nchips, 1, 1}, {0, 0, 0}};
  g_state.chips.clear();
  for (int32_t i = 0; i < nchips; ++i) {
    tpuinfo_chip c{};
    c.index = i;
    c.coord[0] = i;
    std::snprintf(c.chip_id, TPUINFO_MAX_ID, "local-%s-chip-%d", gen.c_str(), i);
    c.hbm_bytes = gi->hbm_bytes;
    c.num_cores = gi->cores;
    c.healthy = 1;
    g_state.chips.push_back(c);
  }
  g_state.is_sim = false;
  g_state.source = "table (" + why + ")";
  g_state.probe_mode = probe_mode.empty() ? "liveness" : probe_mode;
  return 0;
}

}  // namespace

extern "C" {

int tpuinfo_abi_version(void) { return TPUINFO_ABI_VERSION; }

int tpuinfo_init(const char* backend, const char* spec) {
  if (g_state.initialized) {
    set_error("already initialized (call tpuinfo_shutdown first)");
    return -1;
  }
  if (backend == nullptr) {
    set_error("backend is null");
    return -1;
  }
  int rc;
  if (std::strcmp(backend, "sim") == 0) rc = init_sim(spec);
  else if (std::strcmp(backend, "real") == 0) rc = init_real(spec);
  else {
    set_error(std::string("unknown backend: ") + backend);
    return -1;
  }
  if (rc == 0) g_state.initialized = true;
  return rc;
}

int tpuinfo_shutdown(void) {
  if (!g_state.initialized) {
    set_error("not initialized");
    return -1;
  }
  g_state = State{};
  return 0;
}

int tpuinfo_mesh_get(tpuinfo_mesh* out) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (out == nullptr) { set_error("out is null"); return -1; }
  *out = g_state.mesh;
  return 0;
}

int tpuinfo_chip_count(void) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  return static_cast<int>(g_state.chips.size());
}

int tpuinfo_chip_get(int32_t index, tpuinfo_chip* out) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (out == nullptr) { set_error("out is null"); return -1; }
  if (index < 0 || index >= static_cast<int32_t>(g_state.chips.size())) {
    set_error("chip index out of range");
    return -1;
  }
  *out = g_state.chips[index];
  return 0;
}

int tpuinfo_chip_links(int32_t index, int32_t* out, int32_t max) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (out == nullptr && max > 0) { set_error("out is null"); return -1; }
  if (index < 0 || index >= static_cast<int32_t>(g_state.chips.size())) {
    set_error("chip index out of range");
    return -1;
  }
  const tpuinfo_chip& c = g_state.chips[index];
  int n = 0;
  for (int axis = 0; axis < 3; ++axis) {
    int d = g_state.mesh.dims[axis];
    if (d <= 1) continue;
    for (int step = -1; step <= 1; step += 2) {
      int32_t nb[3] = {c.coord[0], c.coord[1], c.coord[2]};
      nb[axis] += step;
      if (nb[axis] < 0 || nb[axis] >= d) {
        if (!g_state.mesh.torus[axis]) continue;
        nb[axis] = (nb[axis] + d) % d;
      }
      /* length-2 torus axis: both steps reach the same chip; dedup */
      bool dup = false;
      for (int j = 0; j < n; ++j)
        if (out[3 * j] == nb[0] && out[3 * j + 1] == nb[1] && out[3 * j + 2] == nb[2])
          dup = true;
      if (dup || (nb[0] == c.coord[0] && nb[1] == c.coord[1] && nb[2] == c.coord[2]))
        continue;
      if (n >= max) { set_error("links buffer too small"); return -1; }
      out[3 * n] = nb[0];
      out[3 * n + 1] = nb[1];
      out[3 * n + 2] = nb[2];
      ++n;
    }
  }
  return n;
}

static int mesh_adjacent(const int32_t a[3], const int32_t b[3]) {
  /* Exactly one axis differs, by 1 (or wraps on a torus axis). */
  int diff_axis = -1;
  for (int axis = 0; axis < 3; ++axis) {
    int32_t d = g_state.mesh.dims[axis];
    if (a[axis] < 0 || a[axis] >= d || b[axis] < 0 || b[axis] >= d) return 0;
    if (a[axis] == b[axis]) continue;
    if (diff_axis != -1) return 0;
    int32_t delta = a[axis] > b[axis] ? a[axis] - b[axis] : b[axis] - a[axis];
    if (delta != 1 && !(g_state.mesh.torus[axis] && delta == d - 1 && d > 1))
      return 0;
    diff_axis = axis;
  }
  return diff_axis != -1;
}

int tpuinfo_inject_link_fault(int32_t ax, int32_t ay, int32_t az,
                              int32_t bx, int32_t by, int32_t bz,
                              int32_t up) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (!g_state.is_sim) {
    set_error("link fault injection is sim-only");
    return -1;
  }
  int32_t a[3] = {ax, ay, az};
  int32_t b[3] = {bx, by, bz};
  if (!mesh_adjacent(a, b)) {
    set_error("link endpoints are not mesh-adjacent chips");
    return -1;
  }
  LinkPair p;
  bool a_first = std::lexicographical_compare(a, a + 3, b, b + 3);
  const int32_t* lo = a_first ? a : b;
  const int32_t* hi = a_first ? b : a;
  for (int i = 0; i < 3; ++i) { p[i] = lo[i]; p[3 + i] = hi[i]; }
  auto& v = g_state.bad_links;
  for (auto it = v.begin(); it != v.end(); ++it) {
    if (*it == p) {
      if (up) v.erase(it);
      return 0;  /* already down, or just restored */
    }
  }
  if (!up) v.push_back(p);
  return 0;
}

int tpuinfo_link_faults(int32_t* out, int32_t max) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (out == nullptr && max > 0) { set_error("out is null"); return -1; }
  int32_t n = static_cast<int32_t>(g_state.bad_links.size());
  int32_t write = n < max ? n : max;
  for (int32_t i = 0; i < write; ++i)
    std::memcpy(out + 6 * i, g_state.bad_links[i].data(), 6 * sizeof(int32_t));
  return n;
}

int tpuinfo_inject_fault(int32_t index, int32_t healthy) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (!g_state.is_sim) {
    set_error("fault injection is sim-only");
    return -1;
  }
  if (index < 0 || index >= static_cast<int32_t>(g_state.chips.size())) {
    set_error("chip index out of range");
    return -1;
  }
  g_state.chips[index].healthy = healthy ? 1 : 0;
  return 0;
}

const char* tpuinfo_last_error(void) { return g_last_error.c_str(); }

const char* tpuinfo_source(void) { return g_state.source.c_str(); }

int tpuinfo_probe(void) {
  if (!g_state.initialized) { set_error("not initialized"); return -1; }
  if (g_state.is_sim || g_state.probe_mode == "off") return 1;
  int ok = 0;
  std::string why;
  if (g_state.probe_mode == "client") {
#ifdef TPUINFO_HAVE_PJRT
    /* the canary IS a fresh enumeration (SURVEY §6 C5: "device liveness
     * probe via a canary enumeration") into scratch buffers — the live
     * inventory's identity (ids, coords, mesh) must not shift mid-session
     * under the device manager's minted device ids */
    std::vector<tpuinfo_chip> scratch_chips;
    tpuinfo_mesh scratch_mesh{};
    ok = enumerate_pjrt(g_state.get_api_sym, &why, &scratch_chips,
                        &scratch_mesh)
             ? 1 : 0;
#else
    /* an ERROR, not a failed canary: marking healthy chips Unhealthy
     * because the BINARY lacks a header would poison the whole node
     * (init_real also rejects this spec; belt and braces) */
    set_error("probe=client requires a PJRT-enabled build");
    return -1;
#endif
  } else {  /* liveness */
    /* the retained init handle keeps the image mapped forever, so the
     * RTLD_NOLOAD lookup alone is a tautology; the on-disk check is the
     * part that can actually fail (driver volume unmounted, node image
     * rot). Only possible when libtpu was given as a path — a bare
     * soname has no checkable location. */
    bool on_disk = true;
    if (g_state.libtpu_path.find('/') != std::string::npos) {
      FILE* fp = std::fopen(g_state.libtpu_path.c_str(), "r");
      on_disk = fp != nullptr;
      if (fp != nullptr) std::fclose(fp);
    }
    void* h = dlopen(g_state.libtpu_path.c_str(), RTLD_LAZY | RTLD_NOLOAD);
    ok = (on_disk && h != nullptr && dlsym(h, "GetPjrtApi") != nullptr)
             ? 1 : 0;
    /* NOLOAD still bumps the refcount on a hit: dlclose it, or a daemon's
     * per-poll probes grow libtpu's refcount without bound (the image
     * stays mapped via the retained init handle regardless) */
    if (h != nullptr) dlclose(h);
    if (!ok) why = "libtpu no longer loadable/present";
  }
  for (auto& c : g_state.chips) c.healthy = ok;
  if (!ok) set_error("probe failed: " + why);
  return ok;
}

}  // extern "C"

/* libhbmguard — HBM-quota audit preload shim (sim-mode enforcement).
 *
 * The reference's vGPU layer enforces SM/memory quotas with a CUDA-API
 * intercept .so preloaded into the container (SURVEY.md §2 C6). TPUs have
 * no CUDA to intercept and real-fleet enforcement is cooperative (the
 * Allocate env caps the XLA client's HBM pool); what the sim needs is HARD
 * enforcement so config-3 tests can prove quotas bite. This shim is that
 * enforcement: LD_PRELOADed into a simulated workload process, it
 * interposes the allocator and fails any large allocation that would push
 * the process past TPU_HBM_LIMIT_BYTES — large host buffers stand in for
 * device HBM in the simulation.
 *
 * Mechanics:
 *  - interposes malloc/calloc/realloc/free plus the aligned allocators
 *    (posix_memalign/aligned_alloc/memalign — numpy >= 1.26 takes these
 *    paths for large buffers) and anonymous mmap/munmap, via
 *    dlsym(RTLD_NEXT, ...)
 *  - only allocations with usable size >= HBMGUARD_THRESHOLD_BYTES
 *    (default 1 MiB) are metered — interpreter small-object churn is
 *    invisible; big tensor buffers are not
 *  - metered blocks are remembered in a lock-free (pointer, size) table,
 *    so a free() of memory the shim never metered (pre-init blocks,
 *    glibc-internal arenas) cannot corrupt the ledger
 *  - over-quota requests return NULL with errno=ENOMEM (numpy raises
 *    MemoryError, exactly how a real HBM OOM surfaces to the user);
 *    posix_memalign returns ENOMEM per its contract
 *  - introspection for tests: hbmguard_used()/hbmguard_limit()
 *
 * Limits of the model (documented trust model, SURVEY.md §9.3): glibc
 * malloc's INTERNAL mmaps do not re-enter this interposer (they call the
 * non-PLT alias), so big malloc'd buffers are metered exactly once, at the
 * malloc layer; mremap-grown maps are not re-metered; if the pointer table
 * fills, overflow blocks pass unmetered rather than corrupting accounting.
 * An audit shim, not a security boundary (neither is the reference's).
 */

#include <dlfcn.h>
#include <errno.h>
#include <malloc.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>

extern "C" {

typedef void* (*malloc_t)(size_t);
typedef void* (*calloc_t)(size_t, size_t);
typedef void* (*realloc_t)(void*, size_t);
typedef void (*free_t)(void*);
typedef int (*posix_memalign_t)(void**, size_t, size_t);
typedef void* (*aligned_alloc_t)(size_t, size_t);
typedef void* (*memalign_t)(size_t, size_t);
typedef void* (*mmap_t)(void*, size_t, int, int, int, off_t);
typedef int (*munmap_t)(void*, size_t);

static std::atomic<int64_t> g_used{0};
/* Re-entrancy depth: >0 while we are inside a real_* allocator call. An
 * mmap arriving then is the allocator's own backing map for a block the
 * outer call is already metering — metering it too would double-count. */
static __thread int t_in_alloc = 0;
static int64_t g_limit = -1;      /* -1 = unlimited (shim inert) */
static int64_t g_threshold = 1 << 20;
/* Direct-mmap metering threshold. Higher than the malloc threshold:
 * CPython's pymalloc arenas are 1 MiB anonymous mmaps, and metering the
 * interpreter's own object heap is exactly the churn the threshold model
 * excludes. Tensor-scale direct maps are far larger. */
static int64_t g_mmap_threshold = 16 << 20;
static std::atomic<int> g_init_state{0}; /* 0=uninit, 1=initializing, 2=ready */
static pthread_t g_init_thread;

static malloc_t real_malloc = nullptr;
static calloc_t real_calloc = nullptr;
static realloc_t real_realloc = nullptr;
static free_t real_free = nullptr;
static posix_memalign_t real_posix_memalign = nullptr;
static aligned_alloc_t real_aligned_alloc = nullptr;
static memalign_t real_memalign = nullptr;
static mmap_t real_mmap = nullptr;
static munmap_t real_munmap = nullptr;

/* -- boot arena ------------------------------------------------------------
 * dlsym may itself allocate during init: serve those from a static arena
 * (never freed; a few KiB at most). Each block carries a size header so a
 * later realloc can copy exactly the old contents. */
alignas(16) static char g_boot_arena[16384];
static size_t g_boot_off = 0;

static int in_boot_arena(const void* p) {
  const char* c = static_cast<const char*>(p);
  return c >= g_boot_arena && c < g_boot_arena + sizeof(g_boot_arena);
}

static void* boot_alloc(size_t n) {
  size_t need = ((n + 15) & ~size_t{15}) + 16; /* 16-byte header */
  if (g_boot_off + need > sizeof(g_boot_arena)) return nullptr;
  char* base = g_boot_arena + g_boot_off;
  g_boot_off += need;
  *reinterpret_cast<size_t*>(base) = n;
  return base + 16;
}

static size_t boot_size(const void* p) {
  return *reinterpret_cast<const size_t*>(static_cast<const char*>(p) - 16);
}

/* -- metered-pointer table -------------------------------------------------
 * Open-addressed, lock-free table of (block, metered size) the shim
 * actually metered. Metered allocations are big (>= 1 MiB), so live count
 * is small; 64Ki slots is generous. If the table ever fills, the block
 * passes unmetered — losing one block's metering is strictly better than
 * corrupting g_used. Sizes are stored so unmetering is exact for blocks
 * without malloc_usable_size (mmap regions). The size slot is written
 * BEFORE the pointer CAS publishes it, so a reader that matched the
 * pointer sees the matching size. */
#define TABLE_SLOTS 65536
static std::atomic<uintptr_t> g_table[TABLE_SLOTS];
static std::atomic<int64_t> g_table_size[TABLE_SLOTS];

static size_t slot_of(uintptr_t p) {
  /* fibonacci hash on the address */
  return (size_t)((p * 11400714819323198485ull) >> 48) & (TABLE_SLOTS - 1);
}

/* Returns the metered size (removing the entry), or -1 if never metered. */
static int64_t table_remove(void* p) {
  uintptr_t v = reinterpret_cast<uintptr_t>(p);
  size_t i = slot_of(v);
  for (int probe = 0; probe < TABLE_SLOTS; ++probe) {
    uintptr_t cur = g_table[i].load();
    if (cur == v) {
      int64_t sz = g_table_size[i].load();
      /* tombstone-free removal is unsafe in open addressing; use a
       * tombstone value so probe chains stay intact */
      if (g_table[i].compare_exchange_strong(cur, UINTPTR_MAX)) return sz;
    }
    if (cur == 0) return -1; /* end of probe chain: never metered */
    i = (i + 1) & (TABLE_SLOTS - 1);
  }
  return -1;
}

/* Metered size of a live entry without removing it, or -1. */
static int64_t table_lookup(void* p) {
  uintptr_t v = reinterpret_cast<uintptr_t>(p);
  size_t i = slot_of(v);
  for (int probe = 0; probe < TABLE_SLOTS; ++probe) {
    uintptr_t cur = g_table[i].load();
    if (cur == v) return g_table_size[i].load();
    if (cur == 0) return -1;
    i = (i + 1) & (TABLE_SLOTS - 1);
  }
  return -1;
}

/* tombstones are reusable on insert. The slot is first claimed with a
 * sentinel, the size written, THEN the pointer published — a lost CAS can
 * therefore never scribble a size into another entry's slot, and readers
 * that match the pointer always see its size. Readers skip claim-sentinel
 * slots naturally (the sentinel matches neither their pointer nor 0). */
static int table_insert_reuse(void* p, int64_t sz) {
  const uintptr_t kClaim = UINTPTR_MAX - 1;
  uintptr_t v = reinterpret_cast<uintptr_t>(p);
  size_t i = slot_of(v);
  for (int probe = 0; probe < TABLE_SLOTS; ++probe) {
    uintptr_t cur = g_table[i].load();
    if (cur == 0 || cur == UINTPTR_MAX) {
      if (g_table[i].compare_exchange_strong(cur, kClaim)) {
        g_table_size[i].store(sz);
        g_table[i].store(v, std::memory_order_release);
        return 1;
      }
      /* slot just taken by another thread: probe on */
    }
    i = (i + 1) & (TABLE_SLOTS - 1);
  }
  return 0;
}

/* -- init ------------------------------------------------------------------ */
static void hbmguard_init(void) {
  int expected = 0;
  if (!g_init_state.compare_exchange_strong(expected, 1)) {
    if (expected == 1 && pthread_equal(g_init_thread, pthread_self())) {
      return; /* re-entered by the initializing thread (dlsym alloc) */
    }
    while (g_init_state.load() != 2) {
    }
    return;
  }
  g_init_thread = pthread_self();
  real_malloc = (malloc_t)dlsym(RTLD_NEXT, "malloc");
  real_calloc = (calloc_t)dlsym(RTLD_NEXT, "calloc");
  real_realloc = (realloc_t)dlsym(RTLD_NEXT, "realloc");
  real_free = (free_t)dlsym(RTLD_NEXT, "free");
  real_posix_memalign =
      (posix_memalign_t)dlsym(RTLD_NEXT, "posix_memalign");
  real_aligned_alloc = (aligned_alloc_t)dlsym(RTLD_NEXT, "aligned_alloc");
  real_memalign = (memalign_t)dlsym(RTLD_NEXT, "memalign");
  real_mmap = (mmap_t)dlsym(RTLD_NEXT, "mmap");
  real_munmap = (munmap_t)dlsym(RTLD_NEXT, "munmap");
  const char* lim = getenv("TPU_HBM_LIMIT_BYTES");
  if (lim != nullptr && *lim != '\0') {
    char* end = nullptr;
    int64_t v = strtoll(lim, &end, 10);
    /* unparseable garbage must leave the shim inert, not lock it to 0 */
    if (end != lim && v >= 0) g_limit = v;
  }
  const char* thr = getenv("HBMGUARD_THRESHOLD_BYTES");
  if (thr != nullptr && *thr != '\0') {
    char* end = nullptr;
    int64_t t = strtoll(thr, &end, 10);
    if (end != thr && t > 0) g_threshold = t;
  }
  const char* mthr = getenv("HBMGUARD_MMAP_THRESHOLD_BYTES");
  if (mthr != nullptr && *mthr != '\0') {
    char* end = nullptr;
    int64_t t = strtoll(mthr, &end, 10);
    if (end != mthr && t > 0) g_mmap_threshold = t;
  }
  g_init_state.store(2);
}

/* Returns 1 when the caller must fall back to the boot arena (we are the
 * thread running hbmguard_init and re-entered the allocator). */
static inline int ensure_init(void) {
  int s = g_init_state.load(std::memory_order_acquire);
  if (s == 2) return 0;
  if (s == 1 && pthread_equal(g_init_thread, pthread_self())) return 1;
  hbmguard_init();
  return g_init_state.load(std::memory_order_acquire) != 2;
}

/* -- metering -------------------------------------------------------------- */

/* Meter a new block. Returns 0 if allowed (or not meterable). */
static int meter_block(void* p, int64_t sz) {
  if (g_limit < 0 || sz < g_threshold) return 0;
  int64_t now = g_used.fetch_add(sz) + sz;
  if (now > g_limit) {
    g_used.fetch_sub(sz);
    return -1;
  }
  if (!table_insert_reuse(p, sz)) {
    /* table full: pass unmetered rather than corrupt the ledger later */
    g_used.fetch_sub(sz);
  }
  return 0;
}

static void unmeter_block(void* p) {
  if (g_limit < 0) return;
  int64_t sz = table_remove(p);
  if (sz >= 0) g_used.fetch_sub(sz);
}

/* -- interposed allocator -------------------------------------------------- */

void* malloc(size_t size) {
  if (ensure_init()) return boot_alloc(size);
  t_in_alloc++;
  void* p = real_malloc(size);
  t_in_alloc--;
  if (p == nullptr) return nullptr;
  if (meter_block(p, (int64_t)malloc_usable_size(p)) != 0) {
    real_free(p);
    errno = ENOMEM;
    return nullptr;
  }
  return p;
}

void* calloc(size_t nmemb, size_t size) {
  if (ensure_init()) {
    size_t total = nmemb * size;
    void* p = boot_alloc(total);
    if (p != nullptr) memset(p, 0, total);
    return p;
  }
  t_in_alloc++;
  void* p = real_calloc(nmemb, size);
  t_in_alloc--;
  if (p == nullptr) return nullptr;
  if (meter_block(p, (int64_t)malloc_usable_size(p)) != 0) {
    real_free(p);
    errno = ENOMEM;
    return nullptr;
  }
  return p;
}

void* realloc(void* ptr, size_t size) {
  if (ensure_init()) {
    void* p = boot_alloc(size);
    if (p != nullptr && ptr != nullptr) {
      size_t old = in_boot_arena(ptr) ? boot_size(ptr) : 0;
      memcpy(p, ptr, old < size ? old : size);
    }
    return p;
  }
  if (ptr != nullptr && in_boot_arena(ptr)) {
    /* migrate a boot block through the metered path */
    void* p = malloc(size);
    if (p != nullptr) {
      size_t old = boot_size(ptr);
      memcpy(p, ptr, old < size ? old : size);
    }
    return p;
  }
  /* The quota check must happen BEFORE real_realloc: once realloc moves
   * the block, the old pointer is gone, and returning NULL then would
   * break realloc's "old block intact on failure" contract (the caller
   * would use-after-free). Pre-meter with the requested size; after a
   * successful realloc, true up to the actual usable sizes. */
  int64_t old_metered_sz = ptr ? table_lookup(ptr) : -1;
  if (g_limit >= 0 && (int64_t)size >= g_threshold) {
    int64_t projected =
        g_used.load() - (old_metered_sz > 0 ? old_metered_sz : 0) +
        (int64_t)size;
    if (projected > g_limit) {
      errno = ENOMEM;
      return nullptr; /* old block untouched */
    }
  }
  t_in_alloc++;
  void* p = real_realloc(ptr, size);
  t_in_alloc--;
  if (p == nullptr) return nullptr; /* old block intact, accounting holds */
  if (old_metered_sz >= 0) {
    int64_t removed = table_remove(ptr == p ? p : ptr);
    if (removed >= 0) g_used.fetch_sub(removed);
  }
  int64_t new_sz = (int64_t)malloc_usable_size(p);
  if (g_limit >= 0 && new_sz >= g_threshold) {
    /* account unconditionally — a post-hoc refusal would leak the move */
    g_used.fetch_add(new_sz);
    if (!table_insert_reuse(p, new_sz)) g_used.fetch_sub(new_sz);
  }
  return p;
}

void free(void* ptr) {
  if (ptr == nullptr || in_boot_arena(ptr)) return;
  if (ensure_init()) return; /* init-window real pointer: leak one block */
  unmeter_block(ptr);
  real_free(ptr);
}

/* -- aligned allocators (numpy >= 1.26's large-buffer path) ---------------- */

int posix_memalign(void** memptr, size_t alignment, size_t size) {
  if (ensure_init()) {
    if (alignment > 16) return ENOMEM; /* boot arena is 16-aligned */
    void* p = boot_alloc(size);
    if (p == nullptr) return ENOMEM;
    *memptr = p;
    return 0;
  }
  t_in_alloc++;
  int rc = real_posix_memalign(memptr, alignment, size);
  t_in_alloc--;
  if (rc != 0) return rc;
  if (meter_block(*memptr, (int64_t)malloc_usable_size(*memptr)) != 0) {
    real_free(*memptr);
    *memptr = nullptr;
    return ENOMEM;
  }
  return 0;
}

void* aligned_alloc(size_t alignment, size_t size) {
  if (ensure_init()) {
    return alignment <= 16 ? boot_alloc(size) : nullptr;
  }
  t_in_alloc++;
  void* p = real_aligned_alloc(alignment, size);
  t_in_alloc--;
  if (p == nullptr) return nullptr;
  if (meter_block(p, (int64_t)malloc_usable_size(p)) != 0) {
    real_free(p);
    errno = ENOMEM;
    return nullptr;
  }
  return p;
}

void* memalign(size_t alignment, size_t size) {
  if (ensure_init()) {
    return alignment <= 16 ? boot_alloc(size) : nullptr;
  }
  t_in_alloc++;
  void* p = real_memalign(alignment, size);
  t_in_alloc--;
  if (p == nullptr) return nullptr;
  if (meter_block(p, (int64_t)malloc_usable_size(p)) != 0) {
    real_free(p);
    errno = ENOMEM;
    return nullptr;
  }
  return p;
}

/* -- anonymous mmap (Python's mmap module, arena allocators) ---------------
 * glibc malloc's internal large-block mmaps call the non-PLT alias and do
 * NOT re-enter here, so malloc'd buffers stay metered exactly once. */

void* mmap(void* addr, size_t length, int prot, int flags, int fd,
           off_t offset) {
  if (ensure_init()) {
    /* init-window map (dlsym machinery): hand through to the kernel */
    return (void*)syscall(SYS_mmap, addr, length, prot, flags, fd, offset);
  }
  int meterable = t_in_alloc == 0 && (flags & MAP_ANONYMOUS) && fd == -1 &&
                  (prot & PROT_WRITE) && !(flags & MAP_STACK) &&
                  g_limit >= 0 && (int64_t)length >= g_mmap_threshold;
  if (meterable) {
    int64_t now = g_used.fetch_add((int64_t)length) + (int64_t)length;
    if (now > g_limit) {
      g_used.fetch_sub((int64_t)length);
      errno = ENOMEM;
      return MAP_FAILED;
    }
  }
  void* p = real_mmap(addr, length, prot, flags, fd, offset);
  if (p == MAP_FAILED) {
    if (meterable) g_used.fetch_sub((int64_t)length);
    return p;
  }
  if (meterable && !table_insert_reuse(p, (int64_t)length)) {
    g_used.fetch_sub((int64_t)length); /* table full: pass unmetered */
  }
  return p;
}

/* _FILE_OFFSET_BITS=64 builds (CPython among them) call mmap64 */
void* mmap64(void* addr, size_t length, int prot, int flags, int fd,
             off_t offset) {
  return mmap(addr, length, prot, flags, fd, offset);
}

int munmap(void* addr, size_t length) {
  if (ensure_init()) {
    return (int)syscall(SYS_munmap, addr, length);
  }
  /* partial unmaps of a metered region are rare (Python unmaps whole
   * regions); a base-pointer unmap releases the whole metered size */
  unmeter_block(addr);
  return real_munmap(addr, length);
}

/* -- test introspection --------------------------------------------------- */
int64_t hbmguard_used(void) {
  ensure_init();
  return g_used.load();
}

int64_t hbmguard_limit(void) {
  ensure_init();
  return g_limit;
}

int64_t hbmguard_threshold(void) {
  ensure_init();
  return g_threshold;
}

} /* extern "C" */

/* Native self-test for libtpuinfo, built with -fsanitize=address,undefined
 * in the `asan` target (SURVEY.md §6: the C++ shims get sanitizer builds,
 * standing in for the reference lineage's `go test -race`). Exercises the
 * sim backend end-to-end plus the error paths. Exit 0 == pass. */
#include "tpuinfo.h"

#include <cstdio>
#include <cstring>

static int failures = 0;
#define CHECK(cond)                                                         \
  do {                                                                      \
    if (!(cond)) {                                                          \
      std::fprintf(stderr, "FAIL %s:%d: %s (last_error=%s)\n", __FILE__,    \
                   __LINE__, #cond, tpuinfo_last_error());                  \
      ++failures;                                                           \
    }                                                                       \
  } while (0)

int main() {
  CHECK(tpuinfo_abi_version() == TPUINFO_ABI_VERSION);

  /* not initialized yet */
  CHECK(tpuinfo_chip_count() == -1);
  tpuinfo_chip chip;
  CHECK(tpuinfo_chip_get(0, &chip) == -1);
  CHECK(tpuinfo_shutdown() == -1);

  /* bad specs rejected */
  CHECK(tpuinfo_init("sim", "dims=zero,4,4") == -1);
  CHECK(tpuinfo_init("sim", "dims=4,4,4\nhost_block=3,3,3") == -1);
  CHECK(tpuinfo_init("sim", "host=rack-0") == -1);
  CHECK(tpuinfo_init("sim", "host=host-9-0-0") == -1);
  CHECK(tpuinfo_init("sim", "mystery=1") == -1);
  CHECK(tpuinfo_init("cuda", nullptr) == -1);
  CHECK(tpuinfo_init(nullptr, nullptr) == -1);

  /* good sim init: host-1-0-2 of a 4x4x4 mesh, 2x2x1 host blocks */
  const char* spec =
      "dims=4,4,4\nhost_block=2,2,1\ntorus=0,0,0\n"
      "host=host-1-0-2\nhbm=17179869184\ncores=1\n";
  CHECK(tpuinfo_init("sim", spec) == 0);
  CHECK(tpuinfo_init("sim", spec) == -1); /* double init rejected */

  tpuinfo_mesh mesh;
  CHECK(tpuinfo_mesh_get(&mesh) == 0);
  CHECK(mesh.dims[0] == 4 && mesh.dims[1] == 4 && mesh.dims[2] == 4);
  CHECK(tpuinfo_chip_count() == 4);

  /* chip 0 of host-1-0-2 sits at (2, 0, 2) */
  CHECK(tpuinfo_chip_get(0, &chip) == 0);
  CHECK(chip.coord[0] == 2 && chip.coord[1] == 0 && chip.coord[2] == 2);
  CHECK(chip.hbm_bytes == 17179869184LL);
  CHECK(chip.num_cores == 1);
  CHECK(chip.healthy == 1);
  CHECK(std::strcmp(chip.chip_id, "host-1-0-2-chip-0") == 0);
  /* chip 3 is (+1,+1,0) from chip 0 within the host block */
  CHECK(tpuinfo_chip_get(3, &chip) == 0);
  CHECK(chip.coord[0] == 3 && chip.coord[1] == 1 && chip.coord[2] == 2);
  CHECK(tpuinfo_chip_get(4, &chip) == -1);
  CHECK(tpuinfo_chip_get(-1, &chip) == -1);

  /* link table: interior-ish chip (2,0,2) has neighbors along x,z fully,
   * y only upward (y=0 edge, no torus): 2 + 1 + 2 = 5 */
  int32_t links[6 * 3];
  int n = tpuinfo_chip_links(0, links, 6);
  CHECK(n == 5);
  n = tpuinfo_chip_links(0, links, 2); /* buffer too small */
  CHECK(n == -1);

  /* ICI link faults (ABI v2): inject, list, restore, reject non-adjacent */
  int32_t lf[6 * 4];
  CHECK(tpuinfo_link_faults(lf, 4) == 0);
  CHECK(tpuinfo_inject_link_fault(2, 0, 2, 3, 0, 2, 0) == 0);
  CHECK(tpuinfo_inject_link_fault(3, 0, 2, 2, 0, 2, 0) == 0); /* dup, reversed */
  CHECK(tpuinfo_link_faults(lf, 4) == 1);
  CHECK(lf[0] == 2 && lf[1] == 0 && lf[2] == 2);  /* canonical a<=b */
  CHECK(lf[3] == 3 && lf[4] == 0 && lf[5] == 2);
  CHECK(tpuinfo_inject_link_fault(0, 0, 0, 2, 0, 0, 0) == -1); /* 2 hops */
  CHECK(tpuinfo_inject_link_fault(0, 0, 0, 1, 1, 0, 0) == -1); /* diagonal */
  CHECK(tpuinfo_inject_link_fault(0, 0, 0, 3, 0, 0, 0) == -1); /* no torus wrap */
  CHECK(tpuinfo_inject_link_fault(2, 0, 2, 3, 0, 2, 1) == 0);  /* restore */
  CHECK(tpuinfo_link_faults(lf, 4) == 0);

  /* fault injection (the sim XID event) */
  CHECK(tpuinfo_inject_fault(1, 0) == 0);
  CHECK(tpuinfo_chip_get(1, &chip) == 0);
  CHECK(chip.healthy == 0);
  CHECK(tpuinfo_inject_fault(1, 1) == 0);
  CHECK(tpuinfo_chip_get(1, &chip) == 0);
  CHECK(chip.healthy == 1);
  CHECK(tpuinfo_inject_fault(99, 0) == -1);

  CHECK(tpuinfo_shutdown() == 0);
  CHECK(tpuinfo_chip_count() == -1);

  /* re-init after shutdown with a length-2 torus: dedup'd single neighbor
   * per wrapped axis */
  CHECK(tpuinfo_init("sim", "dims=2,1,1\nhost_block=1,1,1\ntorus=1,1,1\nhost=host-0-0-0") == 0);
  n = tpuinfo_chip_links(0, links, 6);
  CHECK(n == 1);
  CHECK(links[0] == 1 && links[1] == 0 && links[2] == 0);
  CHECK(tpuinfo_shutdown() == 0);

  /* real backend with a bogus libtpu path must fail cleanly */
  CHECK(tpuinfo_init("real", "libtpu=/nonexistent/libtpu.so") == -1);

  if (failures == 0) std::printf("tpuinfo selftest: all checks passed\n");
  return failures == 0 ? 0 : 1;
}

"""tpukube — TPU-native cluster device-plugin + scheduler framework.

A ground-up rebuild of the capability set of qiniu-ava/KubeGPU (a Kubernetes
GPU device-plugin / scheduler-extender framework, Go + cgo/NVML) for Cloud
TPUs: libtpu-backed chip enumeration, a deviceplugin/v1beta1 gRPC node agent
advertising ``qiniu.com/tpu``, fractional vTPU sharing with HBM quotas, a
scheduler extender scoring ICI-mesh locality, gang scheduling onto contiguous
sub-slices, and multi-tenant bin-packing + preemption.

The reference tree at /root/reference was empty at survey time (SURVEY.md §0);
capability parity is defined by BASELINE.json's north_star + five configs and
SURVEY.md §8's acceptance checklist.

Layer map (SURVEY.md §2):
  L0 core/     — types, mesh geometry, annotation codec, config
  L1 native/   — C++ libtpuinfo enumeration shim (sim + real backends)
  L2 device/   — TpuDevice abstraction, vTPU minting, health
  L3 plugin/   — deviceplugin/v1beta1 gRPC server + fake kubelet for sim
  L4 core/codec.py — annotations are the cluster<->node channel
  L5 sched/    — slicefit, extender, gang, policy
"""

__version__ = "0.1.0"

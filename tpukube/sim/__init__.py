"""Simulation harness: a data-driven cluster driving the real daemons.

SURVEY.md §5: "multi-node behavior is exercised by feeding the extender
synthetic multi-node ExtenderArgs — a cluster is just data." No Kubernetes
exists in this environment; this harness IS the test cluster, and the
BASELINE configs run against it.
"""

from tpukube.sim.harness import SimCluster  # noqa: F401

"""Runnable BASELINE scenarios for the tpukube-sim CLI.

Each scenario replays one BASELINE.json config against the real stack
(extender over HTTP; configs 1-2 additionally walk the device-plugin gRPC
path) and returns a JSON-able result. The pytest configs
(tests/test_config*.py) are the asserting versions; these are the
operator-facing ones — same shapes, metrics out instead of asserts.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any

from tpukube.core.config import TpuKubeConfig, load_config
from tpukube.core.types import PodGroup
from tpukube.sim.harness import SimCluster


def run(scenario: int, config: TpuKubeConfig | None = None) -> dict[str, Any]:
    fn = {
        1: smoke_single_pod,
        2: dp_fanout,
        3: fractional_vtpu,
        4: gang_16,
        5: multi_tenant_northstar,
        6: churn,
        7: fault_telemetry,
    }[scenario]
    t0 = time.perf_counter()
    result = fn(config)
    result.setdefault("wall_s", round(time.perf_counter() - t0, 3))
    result["scenario"] = scenario
    return result


def _metrics(c: SimCluster) -> dict[str, float]:
    with urllib.request.urlopen(f"{c.base_url}/metrics", timeout=5) as r:
        text = r.read().decode()
    return {
        line.split(" ")[0]: float(line.split(" ")[1])
        for line in text.splitlines()
        if line and not line.startswith("#")
    }


def smoke_single_pod(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 1: one pod, one chip, full schedule + Allocate walk."""
    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        node, alloc = c.schedule(c.make_pod("smoke", tpu=1))
        env = c.execute_allocation(alloc)
        return {
            "metric": "allocate_smoke",
            "node": node,
            "devices": alloc.device_ids,
            "env_keys": sorted(env),
            "utilization_percent": round(100 * c.utilization(), 2),
        }


def dp_fanout(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 2: 4-pod data-parallel job, 1 chip per pod, no topology hint."""
    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        placements = {}
        for i in range(4):
            node, alloc = c.schedule(c.make_pod(f"resnet-{i}", tpu=1))
            c.execute_allocation(alloc)
            placements[f"resnet-{i}"] = node
        return {
            "metric": "dp_fanout",
            "placements": placements,
            "utilization_percent": round(100 * c.utilization(), 2),
        }


def fractional_vtpu(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 3: two inference pods share one chip via vTPU shares."""
    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "2,1,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,1,1",
        "TPUKUBE_SHARES_PER_CHIP": "2",
    })
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"},
                    vtpu_shares=cfg.shares_per_chip) as c:
        results = []
        for i in range(2):
            node, alloc = c.schedule(c.make_pod(f"infer-{i}", vtpu=1))
            env = c.execute_allocation(alloc)
            results.append({
                "pod": f"infer-{i}",
                "devices": alloc.device_ids,
                "hbm_limit": env.get("TPU_HBM_LIMIT_BYTES"),
            })
        chips = {r["devices"][0].split("-frac")[0] for r in results}
        return {
            "metric": "fractional_vtpu",
            "pods": results,
            "shared_one_chip": len(chips) == 1,
        }


def gang_16(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 4: 16-pod gang onto a contiguous box of a 64-chip mesh."""
    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,4",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        for i in range(2):
            c.schedule(c.make_pod(f"bg-{i}", tpu=4))
        group = PodGroup("llama-8b", min_member=16)
        coords = []
        for i in range(16):
            _, alloc = c.schedule(
                c.make_pod(f"llama-8b-{i}", tpu=1, priority=10, group=group)
            )
            coords.extend(alloc.coords)
        extents = [
            max(co[a] for co in coords) - min(co[a] for co in coords) + 1
            for a in range(3)
        ]
        m = _metrics(c)
        ex, ey, ez = extents
        return {
            "metric": "gang_16_contiguous",
            "gang_box": extents,
            # a true axis-aligned box: axis extents (not distinct-value
            # counts, which would miss gaps) multiply out to the chip count
            "contiguous": ex * ey * ez == len(set(coords)) == 16,
            "gang_p50_s": round(
                m['gang_schedule_latency_seconds{quantile="0.5"}'], 4),
            "utilization_percent": round(100 * c.utilization(), 2),
        }


def multi_tenant_northstar(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 5: the north-star scenario (also bench.py): 80 burst infer
    pods, a 64-pod priority training gang that preempts its way to a
    contiguous slice, then burst backfill to measure utilization."""
    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    with SimCluster(cfg) as c:
        for i in range(80):
            c.schedule(c.make_pod(f"infer-{i}", tpu=1, priority=0))
        group = PodGroup("llama-70b", min_member=64)
        for i in range(64):
            c.schedule(c.make_pod(f"train-{i}", tpu=1, priority=100,
                                  group=group))
        fill = 0
        while True:
            try:
                c.schedule(c.make_pod(f"fill-{fill}", tpu=1, priority=0))
                fill += 1
            except RuntimeError:
                break
        m = _metrics(c)
        util = m["tpu_chip_utilization_percent"]
        result = {
            "metric": "cluster_tpu_utilization_percent",
            "value": round(util, 2),
            "unit": "%",
            "vs_baseline": round(util / 95.0, 4),
            "gang_p50_s": round(
                m['gang_schedule_latency_seconds{quantile="0.5"}'], 4),
            "preemptions": int(m["tpukube_preemptions_total"]),
            "pods_placed": int(m["tpukube_binds_total"]),
        }
        # per-phase timeline stats (new key; every pre-existing key
        # above is unchanged): where scheduling time went, phase by
        # phase, from the run's own decision trace — the data BASELINE's
        # N-run honesty policy needs to explain run-to-run spread
        if c.extender.trace is not None:
            from tpukube.obs import timeline

            result["phases"] = timeline.phase_stats(
                c.extender.trace.events()
            )
        return result


def churn(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 6 — steady-state churn: the workload shape the pod-
    lifecycle release loop exists for. A training gang holds half the
    mesh while burst pods continuously FINISH (terminal phase → release
    loop frees the chips, no manual release anywhere) and replacements
    schedule into the freed capacity. Measures utilization stability
    (min across waves — a release leak shows up as the floor dropping)
    and the re-schedule latency p50 (finish → replacement bound)."""
    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    waves, wave_size = 6, 16
    with SimCluster(cfg) as c:
        n_chips = sum(m.num_chips for m in c.slices.values())
        group = PodGroup("train", min_member=n_chips // 2)
        for i in range(n_chips // 2):
            c.schedule(c.make_pod(f"train-{i}", tpu=1, priority=100,
                                  group=group))
        burst = 0
        alive: list[str] = []
        while True:
            try:
                c.schedule(c.make_pod(f"burst-{burst}", tpu=1))
                alive.append(f"burst-{burst}")
                burst += 1
            except RuntimeError:
                break
        full = c.utilization()

        util_samples: list[float] = []
        resched: list[float] = []
        released0 = c._lifecycle.released
        for _ in range(waves):
            done, alive = alive[:wave_size], alive[wave_size:]
            for name in done:
                c.complete_pod(name)  # phase Succeeded; object lingers
            util_samples.append(c.utilization())  # the dip
            for _ in range(len(done)):
                t0 = time.perf_counter()
                c.schedule(c.make_pod(f"burst-{burst}", tpu=1))
                resched.append(time.perf_counter() - t0)
                alive.append(f"burst-{burst}")
                burst += 1
            util_samples.append(c.utilization())  # must recover

        recovered = util_samples[1::2]  # post-refill samples
        resched.sort()
        result = {
            "metric": "churn",
            "value": round(100 * min(recovered), 2),
            "unit": "% min utilization after refill",
            "vs_baseline": round(min(recovered) / 0.95, 4),
            "waves": waves,
            "wave_size": wave_size,
            "full_utilization_percent": round(100 * full, 2),
            "util_min_after_refill_percent": round(100 * min(recovered), 2),
            "resched_p50_s": round(resched[len(resched) // 2], 5),
            "resched_p99_s": round(resched[int(len(resched) * 0.99)], 5),
            "lifecycle_releases": c._lifecycle.released - released0,
        }
        # per-phase timeline stats, same key scenario 5 carries: under
        # churn the interesting spread is release -> replacement-bind,
        # and attributing it needs the per-phase view (BENCH tracking)
        if c.extender.trace is not None:
            from tpukube.obs import timeline

            result["phases"] = timeline.phase_stats(
                c.extender.trace.events()
            )
        return result


def fault_telemetry(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 7: chip + ICI-link faults driven through the WHOLE
    telemetry pipeline — the first scenario to exercise
    ``inject_fault``/``inject_link_fault`` on a real node-agent stack:

      device layer fault -> HealthSampler transition -> ChipUnhealthy /
      LinkFault journal events + per-chip /metrics series -> node
      re-annotation (health summary) -> extender fleet rollup on
      /statusz -> SLO burn rates from a live /metrics scrape.
    """
    import os
    import tempfile

    from tpukube.core.config import load_config as _load
    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import MetricsServer, render_plugin_metrics
    from tpukube.obs import events as events_mod
    from tpukube.obs import slo as slo_mod
    from tpukube.obs.events import EventJournal
    from tpukube.obs.health import HealthSampler
    from tpukube.obs.statusz import plugin_statusz
    from tpukube.plugin import DevicePluginServer

    cfg = config or load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })

    def fetch(url: str) -> str:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.read().decode()

    link = ((0, 0, 0), (0, 1, 0))  # intra-host link on host-0-0-0
    with SimCluster(cfg) as c:
        # load the control plane so the SLO histograms hold samples
        group = PodGroup("telemetry-gang", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"tg-{i}", tpu=1, priority=10,
                                  group=group))
        for i in range(4):
            c.schedule(c.make_pod(f"bg-{i}", tpu=1))

        with tempfile.TemporaryDirectory() as td:
            node_cfg = _load(env={
                "TPUKUBE_DEVICE_PLUGIN_DIR": td,
                "TPUKUBE_SIM_MESH_DIMS": ",".join(
                    str(d) for d in cfg.sim_mesh_dims),
                "TPUKUBE_SIM_HOST_BLOCK": ",".join(
                    str(d) for d in cfg.sim_host_block),
            })
            journal_path = os.path.join(td, "events.jsonl")
            journal = EventJournal(path=journal_path)
            with TpuDeviceManager(node_cfg, host="host-0-0-0") as device, \
                    DevicePluginServer(node_cfg, device) as server:
                server.events = journal
                sampler = HealthSampler(device, journal=journal,
                                        poll_seconds=999)
                ms = MetricsServer(
                    lambda: render_plugin_metrics(
                        server, sampler=sampler, events=journal),
                    statusz=lambda: plugin_statusz(
                        server, device=device, sampler=sampler,
                        events=journal),
                )
                ms.start()
                try:
                    sampler.check_once()  # baseline sighting

                    def push_upstream() -> None:
                        # the syncer's job, stepped deterministically:
                        # apply the node's refreshed annotations (incl.
                        # the health summary) through the recorded
                        # upsert_node decision
                        for obj in c.node_objects():
                            if obj["metadata"]["name"] == "host-0-0-0":
                                c.extender.handle("upsert_node", {
                                    "name": "host-0-0-0",
                                    "annotations":
                                        obj["metadata"]["annotations"],
                                })

                    # chip fault + link fault, node-agent side and
                    # scheduler side (as the health watch + syncer would)
                    device.inject_fault(1)
                    chip_flip = sampler.check_once()
                    device.inject_link_fault(*link)
                    link_flip = sampler.check_once()
                    c.inject_fault("host-0-0-0", 1)
                    c.inject_link_fault(*link)
                    push_upstream()

                    degraded_metrics = fetch(
                        f"http://127.0.0.1:{ms.port}/metrics")
                    degraded_statusz = json.loads(
                        fetch(f"{c.base_url}/statusz"))

                    # recovery
                    device.inject_fault(1, healthy=True)
                    device.inject_link_fault(*link, up=True)
                    recovered = sampler.check_once()
                    c.inject_fault("host-0-0-0", 1, healthy=True)
                    c.inject_link_fault(*link, up=True)
                    push_upstream()
                    recovered_statusz = json.loads(
                        fetch(f"{c.base_url}/statusz"))

                    slo_eval = slo_mod.evaluate(
                        fetch(f"{c.base_url}/metrics"))
                finally:
                    ms.stop()
            journal.close()
            event_reasons = [
                e["reason"] for e in events_mod.load(journal_path)
            ]

        chip_series = sum(
            1 for line in degraded_metrics.splitlines()
            if line.startswith("tpukube_chip_")
        )
        fleet_degraded = degraded_statusz["fleet"]["total"]
        fleet_recovered = recovered_statusz["fleet"]["total"]
        return {
            "metric": "fault_telemetry",
            "transitions": {
                "chip_fault": chip_flip,
                "link_fault": link_flip,
                "recovery": recovered,
            },
            "event_reasons": sorted(set(event_reasons)),
            "chip_series_on_node_metrics": chip_series,
            "fleet_degraded": {
                k: fleet_degraded[k]
                for k in ("healthy", "degraded", "unhealthy", "links_down")
            },
            "fleet_recovered": {
                k: fleet_recovered[k]
                for k in ("healthy", "degraded", "unhealthy", "links_down")
            },
            "slo": {
                name: {
                    "burn_rate": entry["burn_rate"],
                    "total": entry["total"],
                }
                for name, entry in slo_eval.items()
            },
        }

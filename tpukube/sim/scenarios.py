"""Runnable BASELINE scenarios for the tpukube-sim CLI.

Each scenario replays one BASELINE.json config against the real stack
(extender over HTTP; configs 1-2 additionally walk the device-plugin gRPC
path) and returns a JSON-able result. The pytest configs
(tests/test_config*.py) are the asserting versions; these are the
operator-facing ones — same shapes, metrics out instead of asserts.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any, Optional

from tpukube.core.config import TpuKubeConfig, load_config
from tpukube.core.types import PodGroup
from tpukube.sim.harness import SimCluster

#: knobs that pass through from the process environment into every
#: scenario's canonical config (which would otherwise shadow them):
#: the chaos seed (tools/check.sh pins it for reproducible smoke), the
#: snapshot audit sentinel (the acceptance drive runs scenarios
#: 1-9 at TPUKUBE_SNAPSHOT_AUDIT_RATE=1.0 asserting zero divergences),
#: and the batching knobs (the ISSUE 8 parity suite re-runs scenarios
#: with TPUKUBE_BATCH_ENABLED=1 asserting bit-identical placements)
_PASSTHROUGH_KEYS = (
    "TPUKUBE_CHAOS_SEED",
    "TPUKUBE_SNAPSHOT_AUDIT_RATE",
    # incremental snapshot deltas (ISSUE 10): the parity suite re-runs
    # scenarios with TPUKUBE_SNAPSHOT_DELTA_ENABLED=0 (the
    # rebuild-every-epoch oracle) asserting bit-identical placements
    "TPUKUBE_SNAPSHOT_DELTA_ENABLED",
    "TPUKUBE_BATCH_ENABLED",
    "TPUKUBE_BATCH_MAX_PODS",
    "TPUKUBE_CYCLE_INTERVAL_SECONDS",
    # tenancy (ISSUE 9): the parity suite re-runs scenarios with a
    # NEUTRAL plane (TPUKUBE_TENANCY_ENABLED=1, no quotas) asserting
    # bit-identical placements
    "TPUKUBE_TENANCY_ENABLED",
    "TPUKUBE_TENANCY_QUOTAS",
    # durable-state journal (ISSUE 11): the parity suite re-runs
    # scenarios with the journal ON (a tempdir WAL) asserting
    # bit-identical placements — persistence must never move a pod
    "TPUKUBE_JOURNAL_ENABLED",
    "TPUKUBE_JOURNAL_PATH",
    "TPUKUBE_CHECKPOINT_INTERVAL_SECONDS",
    "TPUKUBE_JOURNAL_FSYNC",
    # decision provenance (ISSUE 12): the check.sh decisions smoke
    # re-runs the scenario-12 slice with sampling at 1.0 and asserts
    # the measured record overhead stays under the perf floor
    "TPUKUBE_DECISIONS_ENABLED",
    "TPUKUBE_DECISIONS_SAMPLE_RATE",
    "TPUKUBE_DECISIONS_PATH",
    # sharded control plane (ISSUE 13): check.sh's shard smoke and the
    # bench replica sweep pin replica count + plan-served answers
    "TPUKUBE_PLANNER_REPLICAS",
    "TPUKUBE_FILTER_FROM_PLAN",
    # process-parallel sharding (ISSUE 14): subprocess replica daemons
    # for the true multi-core sweep (check.sh shard-mp smoke, bench)
    "TPUKUBE_SHARD_TRANSPORT",
    # bulk cold-start ingestion + generation-based incremental resync
    # (ISSUE 15): the parity suite re-runs scenarios with the bulk
    # path off (the per-node oracle) / the generation log disabled
    # (legacy full-read resyncs) asserting bit-identical placements
    "TPUKUBE_BULK_INGEST_ENABLED",
    "TPUKUBE_GENERATION_LOG_CAPACITY",
    # capacity analytics (ISSUE 17): the check.sh capacity smoke and
    # the bench capacity key re-run the scenario-12 slice with the
    # flight recorder on and floor the measured sampling overhead
    "TPUKUBE_CAPACITY_ENABLED",
    "TPUKUBE_CAPACITY_SAMPLE_INTERVAL_SECONDS",
    "TPUKUBE_CAPACITY_SAMPLES",
    "TPUKUBE_CAPACITY_PATH",
    # federated lockgraph (ISSUE 18): re-run any scenario with the
    # dynamic lock-order detector live — sharded runs merge worker
    # edges into a fleet-wide cycle report on the result
    "TPUKUBE_LOCK_MONITOR",
    # fleet elasticity (ISSUE 19): check.sh's maintenance-storm smoke
    # and the bench elasticity key pin the drain/autoscaler knobs on
    # the scenarios that exercise the drain choreography
    "TPUKUBE_DRAIN_ENABLED",
    "TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES",
    "TPUKUBE_DRAIN_TENANT_BUDGET",
    "TPUKUBE_AUTOSCALE_ENABLED",
    "TPUKUBE_AUTOSCALE_MIN_SLICES",
    "TPUKUBE_AUTOSCALE_MAX_SLICES",
    # compact binary wire codec (ISSUE 20): check.sh's codec smoke and
    # the bench wire comparison re-run sharded drives with the TKW1
    # codec on asserting bit-identical placements and flooring the
    # bytes/wave ratio against the JSON oracle
    "TPUKUBE_WIRE_CODEC",
    "TPUKUBE_WIRE_COMPRESS_MIN_BYTES",
)


def _env(defaults: dict[str, str]) -> dict[str, str]:
    import os

    env = dict(defaults)
    for key in _PASSTHROUGH_KEYS:
        if os.environ.get(key):
            env[key] = os.environ[key]
    return env


def _audit_stats(c: SimCluster) -> dict[str, Any]:
    """The snapshot audit sentinel's counters for a scenario result
    (all zero when snapshot_audit_rate is 0 — the default)."""
    snaps = c.extender.snapshots
    return {
        "rate": snaps.audit_rate,
        "checks": snaps.audit_checks,
        "divergences": snaps.audit_divergences,
    }


def run(scenario: int, config: TpuKubeConfig | None = None) -> dict[str, Any]:
    fn = {
        1: smoke_single_pod,
        2: dp_fanout,
        3: fractional_vtpu,
        4: gang_16,
        5: multi_tenant_northstar,
        6: churn,
        7: fault_telemetry,
        8: apiserver_chaos,
        9: crash_recovery,
        10: kilonode_churn,
        11: tenant_serving,
        12: kilonode10k_churn,
        13: crash_storm,
        14: kilonode_sharded,
        15: maintenance_storm,
    }[scenario]
    t0 = time.perf_counter()
    result = fn(config)
    result.setdefault("wall_s", round(time.perf_counter() - t0, 3))
    result["scenario"] = scenario
    return result


def _metrics(c: SimCluster) -> dict[str, float]:
    with urllib.request.urlopen(f"{c.base_url}/metrics", timeout=5) as r:
        text = r.read().decode()
    return {
        line.split(" ")[0]: float(line.split(" ")[1])
        for line in text.splitlines()
        if line and not line.startswith("#")
    }


def smoke_single_pod(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 1: one pod, one chip, full schedule + Allocate walk."""
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "2,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    with SimCluster(cfg) as c:
        node, alloc = c.schedule(c.make_pod("smoke", tpu=1))
        env = c.execute_allocation(alloc)
        return {
            "metric": "allocate_smoke",
            "node": node,
            "devices": alloc.device_ids,
            "env_keys": sorted(env),
            "utilization_percent": round(100 * c.utilization(), 2),
        }


def dp_fanout(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 2: 4-pod data-parallel job, 1 chip per pod, no topology hint."""
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "4,2,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    with SimCluster(cfg) as c:
        placements = {}
        for i in range(4):
            node, alloc = c.schedule(c.make_pod(f"resnet-{i}", tpu=1))
            c.execute_allocation(alloc)
            placements[f"resnet-{i}"] = node
        return {
            "metric": "dp_fanout",
            "placements": placements,
            "utilization_percent": round(100 * c.utilization(), 2),
        }


def fractional_vtpu(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 3: two inference pods share one chip via vTPU shares."""
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "2,1,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,1,1",
        "TPUKUBE_SHARES_PER_CHIP": "2",
    }))
    with SimCluster(cfg, vtpu_nodes={"host-0-0-0"},
                    vtpu_shares=cfg.shares_per_chip) as c:
        results = []
        for i in range(2):
            node, alloc = c.schedule(c.make_pod(f"infer-{i}", vtpu=1))
            env = c.execute_allocation(alloc)
            results.append({
                "pod": f"infer-{i}",
                "devices": alloc.device_ids,
                "hbm_limit": env.get("TPU_HBM_LIMIT_BYTES"),
            })
        chips = {r["devices"][0].split("-frac")[0] for r in results}
        return {
            "metric": "fractional_vtpu",
            "pods": results,
            "shared_one_chip": len(chips) == 1,
        }


def gang_16(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 4: 16-pod gang onto a contiguous box of a 64-chip mesh."""
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "4,4,4",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    with SimCluster(cfg) as c:
        for i in range(2):
            c.schedule(c.make_pod(f"bg-{i}", tpu=4))
        group = PodGroup("llama-8b", min_member=16)
        coords = []
        for i in range(16):
            _, alloc = c.schedule(
                c.make_pod(f"llama-8b-{i}", tpu=1, priority=10, group=group)
            )
            coords.extend(alloc.coords)
        extents = [
            max(co[a] for co in coords) - min(co[a] for co in coords) + 1
            for a in range(3)
        ]
        m = _metrics(c)
        ex, ey, ez = extents
        return {
            "metric": "gang_16_contiguous",
            "gang_box": extents,
            # a true axis-aligned box: axis extents (not distinct-value
            # counts, which would miss gaps) multiply out to the chip count
            "contiguous": ex * ey * ez == len(set(coords)) == 16,
            "gang_p50_s": round(
                m['gang_schedule_latency_seconds{quantile="0.5"}'], 4),
            "utilization_percent": round(100 * c.utilization(), 2),
        }


def multi_tenant_northstar(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 5: the north-star scenario (also bench.py): 80 burst infer
    pods, a 64-pod priority training gang that preempts its way to a
    contiguous slice, then burst backfill to measure utilization."""
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    with SimCluster(cfg) as c:
        for i in range(80):
            c.schedule(c.make_pod(f"infer-{i}", tpu=1, priority=0))
        group = PodGroup("llama-70b", min_member=64)
        for i in range(64):
            c.schedule(c.make_pod(f"train-{i}", tpu=1, priority=100,
                                  group=group))
        fill = 0
        while True:
            try:
                c.schedule(c.make_pod(f"fill-{fill}", tpu=1, priority=0))
                fill += 1
            except RuntimeError:
                break
        m = _metrics(c)
        util = m["tpu_chip_utilization_percent"]
        result = {
            "metric": "cluster_tpu_utilization_percent",
            "value": round(util, 2),
            "unit": "%",
            "vs_baseline": round(util / 95.0, 4),
            "gang_p50_s": round(
                m['gang_schedule_latency_seconds{quantile="0.5"}'], 4),
            "preemptions": int(m["tpukube_preemptions_total"]),
            "pods_placed": int(m["tpukube_binds_total"]),
        }
        # per-phase timeline stats (new key; every pre-existing key
        # above is unchanged): where scheduling time went, phase by
        # phase, from the run's own decision trace — the data BASELINE's
        # N-run honesty policy needs to explain run-to-run spread
        if c.extender.trace is not None:
            from tpukube.obs import timeline

            result["phases"] = timeline.phase_stats(
                c.extender.trace.events()
            )
        return result


def churn(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Config 6 — steady-state churn: the workload shape the pod-
    lifecycle release loop exists for. A training gang holds half the
    mesh while burst pods continuously FINISH (terminal phase → release
    loop frees the chips, no manual release anywhere) and replacements
    schedule into the freed capacity. Measures utilization stability
    (min across waves — a release leak shows up as the floor dropping)
    and the re-schedule latency p50 (finish → replacement bound)."""
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    waves, wave_size = 6, 16
    with SimCluster(cfg) as c:
        n_chips = sum(m.num_chips for m in c.slices.values())
        group = PodGroup("train", min_member=n_chips // 2)
        for i in range(n_chips // 2):
            c.schedule(c.make_pod(f"train-{i}", tpu=1, priority=100,
                                  group=group))
        burst = 0
        alive: list[str] = []
        while True:
            try:
                c.schedule(c.make_pod(f"burst-{burst}", tpu=1))
                alive.append(f"burst-{burst}")
                burst += 1
            except RuntimeError:
                break
        full = c.utilization()

        util_samples: list[float] = []
        resched: list[float] = []
        released0 = c._lifecycle.released
        for _ in range(waves):
            done, alive = alive[:wave_size], alive[wave_size:]
            for name in done:
                c.complete_pod(name)  # phase Succeeded; object lingers
            util_samples.append(c.utilization())  # the dip
            for _ in range(len(done)):
                t0 = time.perf_counter()
                c.schedule(c.make_pod(f"burst-{burst}", tpu=1))
                resched.append(time.perf_counter() - t0)
                alive.append(f"burst-{burst}")
                burst += 1
            util_samples.append(c.utilization())  # must recover

        recovered = util_samples[1::2]  # post-refill samples
        resched.sort()
        result = {
            "metric": "churn",
            "value": round(100 * min(recovered), 2),
            "unit": "% min utilization after refill",
            "vs_baseline": round(min(recovered) / 0.95, 4),
            "waves": waves,
            "wave_size": wave_size,
            "full_utilization_percent": round(100 * full, 2),
            "util_min_after_refill_percent": round(100 * min(recovered), 2),
            "resched_p50_s": round(resched[len(resched) // 2], 5),
            "resched_p99_s": round(resched[int(len(resched) * 0.99)], 5),
            "lifecycle_releases": c._lifecycle.released - released0,
        }
        # per-phase timeline stats, same key scenario 5 carries: under
        # churn the interesting spread is release -> replacement-bind,
        # and attributing it needs the per-phase view (BENCH tracking)
        if c.extender.trace is not None:
            from tpukube.obs import timeline

            result["phases"] = timeline.phase_stats(
                c.extender.trace.events()
            )
        return result


def fault_telemetry(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 7: chip + ICI-link faults driven through the WHOLE
    telemetry pipeline — the first scenario to exercise
    ``inject_fault``/``inject_link_fault`` on a real node-agent stack:

      device layer fault -> HealthSampler transition -> ChipUnhealthy /
      LinkFault journal events + per-chip /metrics series -> node
      re-annotation (health summary) -> extender fleet rollup on
      /statusz -> SLO burn rates from a live /metrics scrape.
    """
    import os
    import tempfile

    from tpukube.core.config import load_config as _load
    from tpukube.device import TpuDeviceManager
    from tpukube.metrics import MetricsServer, render_plugin_metrics
    from tpukube.obs import events as events_mod
    from tpukube.obs import slo as slo_mod
    from tpukube.obs.events import EventJournal
    from tpukube.obs.health import HealthSampler
    from tpukube.obs.statusz import plugin_statusz
    from tpukube.plugin import DevicePluginServer

    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))

    def fetch(url: str) -> str:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.read().decode()

    link = ((0, 0, 0), (0, 1, 0))  # intra-host link on host-0-0-0
    with SimCluster(cfg) as c:
        # load the control plane so the SLO histograms hold samples
        group = PodGroup("telemetry-gang", min_member=4)
        for i in range(4):
            c.schedule(c.make_pod(f"tg-{i}", tpu=1, priority=10,
                                  group=group))
        for i in range(4):
            c.schedule(c.make_pod(f"bg-{i}", tpu=1))

        with tempfile.TemporaryDirectory() as td:
            node_cfg = _load(env={
                "TPUKUBE_DEVICE_PLUGIN_DIR": td,
                "TPUKUBE_SIM_MESH_DIMS": ",".join(
                    str(d) for d in cfg.sim_mesh_dims),
                "TPUKUBE_SIM_HOST_BLOCK": ",".join(
                    str(d) for d in cfg.sim_host_block),
            })
            journal_path = os.path.join(td, "events.jsonl")
            journal = EventJournal(path=journal_path)
            with TpuDeviceManager(node_cfg, host="host-0-0-0") as device, \
                    DevicePluginServer(node_cfg, device) as server:
                server.events = journal
                sampler = HealthSampler(device, journal=journal,
                                        poll_seconds=999)
                ms = MetricsServer(
                    lambda: render_plugin_metrics(
                        server, sampler=sampler, events=journal),
                    statusz=lambda: plugin_statusz(
                        server, device=device, sampler=sampler,
                        events=journal),
                )
                ms.start()
                try:
                    sampler.check_once()  # baseline sighting

                    def push_upstream() -> None:
                        # the syncer's job, stepped deterministically:
                        # apply the node's refreshed annotations (incl.
                        # the health summary) through the recorded
                        # upsert_node decision
                        for obj in c.node_objects():
                            if obj["metadata"]["name"] == "host-0-0-0":
                                c.extender.handle("upsert_node", {
                                    "name": "host-0-0-0",
                                    "annotations":
                                        obj["metadata"]["annotations"],
                                })

                    # chip fault + link fault, node-agent side and
                    # scheduler side (as the health watch + syncer would)
                    device.inject_fault(1)
                    chip_flip = sampler.check_once()
                    device.inject_link_fault(*link)
                    link_flip = sampler.check_once()
                    c.inject_fault("host-0-0-0", 1)
                    c.inject_link_fault(*link)
                    push_upstream()

                    degraded_metrics = fetch(
                        f"http://127.0.0.1:{ms.port}/metrics")
                    degraded_statusz = json.loads(
                        fetch(f"{c.base_url}/statusz"))

                    # recovery
                    device.inject_fault(1, healthy=True)
                    device.inject_link_fault(*link, up=True)
                    recovered = sampler.check_once()
                    c.inject_fault("host-0-0-0", 1, healthy=True)
                    c.inject_link_fault(*link, up=True)
                    push_upstream()
                    recovered_statusz = json.loads(
                        fetch(f"{c.base_url}/statusz"))

                    slo_eval = slo_mod.evaluate(
                        fetch(f"{c.base_url}/metrics"))
                finally:
                    ms.stop()
            journal.close()
            event_reasons = [
                e["reason"] for e in events_mod.load(journal_path)
            ]

        chip_series = sum(
            1 for line in degraded_metrics.splitlines()
            if line.startswith("tpukube_chip_")
        )
        fleet_degraded = degraded_statusz["fleet"]["total"]
        fleet_recovered = recovered_statusz["fleet"]["total"]
        return {
            "metric": "fault_telemetry",
            "transitions": {
                "chip_fault": chip_flip,
                "link_fault": link_flip,
                "recovery": recovered,
            },
            "event_reasons": sorted(set(event_reasons)),
            "chip_series_on_node_metrics": chip_series,
            "fleet_degraded": {
                k: fleet_degraded[k]
                for k in ("healthy", "degraded", "unhealthy", "links_down")
            },
            "fleet_recovered": {
                k: fleet_recovered[k]
                for k in ("healthy", "degraded", "unhealthy", "links_down")
            },
            "slo": {
                name: {
                    "burn_rate": entry["burn_rate"],
                    "total": entry["total"],
                }
                for name, entry in slo_eval.items()
            },
        }


def scenario8_storm():
    """Scenario 8's storm spec — ONE definition, reused verbatim by the
    multi-tenant scenario 11 so both run the same fault mix."""
    from tpukube.chaos import ChaosSpec

    return ChaosSpec(
        error_rate=0.12, timeout_rate=0.08, torn_rate=0.10,
        slow_rate=0.05, slow_seconds=0.001,
        gone_rate=0.10, drop_event_rate=0.05, dup_event_rate=0.05,
    )


def apiserver_chaos(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 8: seeded apiserver chaos under gang + burst churn.

    A ChaosSimCluster runs the full control plane — preempting gang,
    burst fill, completion churn — while the fault schedule injects
    503s, transport timeouts, torn writes, and slow responses into the
    eviction / lifecycle / bind-effector seams. A blackout phase
    (every request failing) then trips the apiserver circuit and
    proves degraded mode: filter requests fail SAFE while the circuit
    is open, and scheduling resumes through the half-open probe once
    the chaos stops. Acceptance: zero leaked gang reservations and
    zero ledger/apiserver divergence after the dust settles.
    """
    from tpukube.chaos import (
        ChaosSimCluster,
        ChaosSpec,
        FaultSchedule,
        converge,
        leaked_reservations,
        ledger_divergence,
    )

    # canonical topology; the seed + audit knobs must work WITHOUT
    # --config — _env passes them through from the process environment
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    seed = cfg.chaos_seed or 1337
    schedule_ = FaultSchedule(seed, scenario8_storm())

    with ChaosSimCluster(cfg, schedule_) as c:

        def robust(pod, deadline_s: float = 60.0,
                   retry_unschedulable: bool = True):
            """schedule() with the outer retry a real kube-scheduler
            queue provides: degraded-mode refusals wait out the
            circuit's reset window; chaos-exhausted binds,
            victims-terminating gates, and release lag just requeue.
            Each retry also steps the lifecycle loop — the real
            daemon's release watch runs concurrently; the sim steps
            it deterministically."""
            t0 = time.monotonic()
            while True:
                try:
                    return c.schedule(pod)
                except RuntimeError as e:
                    msg = str(e)
                    if not retry_unschedulable and "unschedulable" in msg:
                        raise
                    if time.monotonic() - t0 > deadline_s:
                        raise
                    if "degraded mode" in msg:
                        time.sleep(c.CIRCUIT_RESET_S)
                    try:
                        c._lifecycle.check_once()
                    except RuntimeError:
                        pass  # chaos-injected resync failure; next lap
                    continue

        # fill the mesh with bursts, then a priority gang preempts its
        # way in — evictions, confirms, and binds all under fault fire
        fill = 0
        while True:
            try:
                robust(c.make_pod(f"burst-{fill}", tpu=1),
                       deadline_s=20.0, retry_unschedulable=False)
                fill += 1
            except RuntimeError:
                break
        n_chips = sum(m.num_chips for m in c.slices.values())
        group = PodGroup("storm", min_member=n_chips // 2)
        for i in range(n_chips // 2):
            robust(c.make_pod(f"storm-{i}", tpu=1, priority=100,
                              group=group))

        # churn: survivors finish, replacements land in the freed chips
        survivors = sorted(
            a.pod_key.split("/", 1)[1]
            for a in c.extender.state.allocations()
            if a.pod_key.startswith("default/burst-")
        )
        finished = survivors[:4]
        for name in finished:
            try:
                c.complete_pod(name)
            except RuntimeError:
                pass  # release deferred by an injected fault; converge
        converge(c)
        for i in range(len(finished)):
            robust(c.make_pod(f"refill-{i}", tpu=1), deadline_s=20.0)

        # free one chip BEFORE the blackout so the probe pod passes
        # filter and reaches the (failing) bind effector — a full mesh
        # would answer "unschedulable" without ever touching the
        # circuit
        try:
            c.complete_pod("refill-0")
        except RuntimeError:
            pass
        converge(c)

        # blackout: every apiserver call fails until the circuit opens
        # and the extender fails filter requests safe (degraded mode)
        schedule_.resume(ChaosSpec(error_rate=1.0))
        degraded_before = c.extender.events.counts_by_reason().get(
            "DegradedMode", 0)
        blackout_refused = False
        try:
            c.schedule(c.make_pod("blackout-probe", tpu=1), retries=12)
        except RuntimeError:
            blackout_refused = True
        degraded_refusals = c.extender.events.counts_by_reason().get(
            "DegradedMode", 0) - degraded_before

        # quiet: chaos off, circuit half-opens, scheduling resumes
        schedule_.stop()
        time.sleep(c.CIRCUIT_RESET_S * 2)
        robust(c.make_pod("recovery-probe", tpu=1))
        converge_rounds = converge(c)

        leaks = leaked_reservations(c)
        div = ledger_divergence(c)
        reasons = c.extender.events.counts_by_reason()
        gangs = c.extender.gang_snapshot()
        committed = [g for g in gangs if g["committed"]]
        result = {
            "metric": "apiserver_chaos",
            "value": schedule_.injected(),
            "unit": "faults injected",
            "faults": schedule_.report(),
            "blackout_refused": blackout_refused,
            "degraded_refusals": degraded_refusals,
            "circuit": {
                "opens": c.circuit.opens,
                "state": c.circuit.state(),
            },
            "retry": {
                "bind_attempts": c.bind_retrier.stats.attempts,
                "bind_retries": c.bind_retrier.stats.retries,
                "bind_exhausted": c.bind_retrier.stats.exhausted,
                "retry_exhausted_events": reasons.get("RetryExhausted", 0),
            },
            "gang_committed": bool(committed),
            "preemptions": c.extender.preemptions,
            "converge_rounds": converge_rounds,
            "evictions_pending": c._evictions.depth(),
            "leaked_reservations": len(leaks),
            "ledger_divergence": len(div),
            "snapshot_audit": _audit_stats(c),
            "utilization_percent": round(100 * c.utilization(), 2),
        }
        # the acceptance invariants FAIL the scenario, not just dent a
        # number — a chaos run that leaks is a bug, full stop
        problems = [str(p) for p in leaks] + div
        if c._evictions.depth():
            problems.append(
                f"{c._evictions.depth()} eviction(s) still pending")
        if not committed:
            problems.append("the storm gang never committed")
        if not (blackout_refused and degraded_refusals > 0
                and c.circuit.opens > 0):
            problems.append(
                "blackout did not trip the circuit into degraded mode")
        if problems:
            raise RuntimeError("scenario 8 invariants violated: "
                               + "; ".join(problems))
        return result


def kilonode_churn(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 10: the kilonode scale trace (ISSUE 8 acceptance) —
    1024 nodes / 4096 chips, a committed 256-member training gang, and
    a ~100k-pod burst-churn trace driven through the batched
    scheduling cycles on a discrete-event fake clock: hours of
    simulated churn (waves arrive, run, complete on a simulated
    cadence; TTL sweeps and eviction ages all read the fake clock) in
    seconds of wall time. Every ~100th pod additionally runs the FULL
    per-pod webhook protocol (filter -> prioritize -> bind, in-process)
    so webhook latency quantiles are measured, not inferred.

    ``TPUKUBE_KILONODE_PODS`` scales the trace (default 100000; the
    check.sh smoke stage runs a shorter fixed-seed trace). Raises on
    invariant violations: gang uncommitted, ledger/store divergence,
    or a pod count short of the target.
    """
    import os

    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "16,16,16",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_BATCH_MAX_PODS": "1024",
    }))
    total_target = int(os.environ.get("TPUKUBE_KILONODE_PODS", "100000"))
    return _kilonode_drive(cfg, metric="kilonode_churn",
                           total_target=total_target, gang_size=256)


def kilonode10k_churn(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 12 (ISSUE 10 acceptance): the 10k-node / 40k-chip
    churn drive — 10240 nodes over a 32x32x40 mesh (40960 chips), a
    committed 512-member training gang placed through the batched gang
    planner, and burst-churn waves through the batched cycles on the
    fake clock, with the incremental snapshot path (delta advance +
    persistent fast-state patching) carrying the per-cycle constant
    that a full O(chips) rebuild would otherwise pay 10x over.

    Reports the ISSUE 10 bench keys: ``pods_per_sec``, the plan-hit
    ratio, and ``delta_apply_p50_ms`` vs ``rebuild_p50_ms`` — the
    latter measured by forcing full rebuilds on the SAME loaded
    cluster at drive end, so the speedup is apples-to-apples.

    ``TPUKUBE_KILONODE10K_PODS`` scales the trace (default 40000; the
    check.sh smoke stage runs a shorter fixed trace). Raises on: gang
    uncommitted, ledger/store divergence, LEAKED RESERVATIONS, or a
    pod shortfall."""
    import os

    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "32,32,40",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_BATCH_MAX_PODS": "2048",
    }))
    total_target = int(os.environ.get("TPUKUBE_KILONODE10K_PODS",
                                      "40000"))
    return _kilonode_drive(cfg, metric="kilonode10k_churn",
                           total_target=total_target, gang_size=512,
                           max_alive=8192, check_leaks=True,
                           delta_stats=True)


def kilonode_sharded(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 14 (ISSUE 13 acceptance): the 100k-node sharded drive —
    ``TPUKUBE_SHARD_SLICES`` (default 10) ICI slices of
    ``TPUKUBE_SIM_MESH_DIMS`` (default 32x32x40, i.e. 10,240 nodes /
    40,960 chips each: ~102k nodes / ~410k chips total), partitioned
    across ``TPUKUBE_PLANNER_REPLICAS`` (default 4) planner replicas
    behind the ShardRouter, burst-churned through the batched cycles
    on the fake clock with plan-served filter answers
    (filter_from_plan). The committed training gang routes whole to
    one replica (ICI-contiguous placement stays first choice); the
    webhook-sampled pods measure real p99s through the router.

    The measured wall EXCLUDES fleet minting + the one-time node
    ingest (reported separately as ``setup_s``): at 100k nodes the
    annotation encode/decode is a fixed startup cost, not the
    steady-state throughput the scenario records. Raises on: gang
    uncommitted, ledger/store divergence, leaked reservations, a dead
    replica, or a pod shortfall. ``TPUKUBE_KILONODE100K_PODS`` scales
    the trace (default 40000; check.sh's shard smoke runs a much
    smaller fleet via the env knobs)."""
    import os

    from tpukube.core.mesh import MeshSpec

    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": os.environ.get(
            "TPUKUBE_SIM_MESH_DIMS", "32,32,40"),
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_BATCH_MAX_PODS": "2048",
        "TPUKUBE_FILTER_FROM_PLAN": "1",
        "TPUKUBE_PLANNER_REPLICAS": os.environ.get(
            "TPUKUBE_PLANNER_REPLICAS", "4"),
    }))
    n_slices = int(os.environ.get("TPUKUBE_SHARD_SLICES", "10"))
    mesh = cfg.sim_mesh()
    slices = {
        f"s{i:02d}": MeshSpec(dims=mesh.dims,
                              host_block=mesh.host_block,
                              torus=mesh.torus)
        for i in range(n_slices)
    }
    total_target = int(os.environ.get("TPUKUBE_KILONODE100K_PODS",
                                      "40000"))
    total_chips = n_slices * mesh.num_chips
    result = _kilonode_drive(
        cfg, metric="kilonode_sharded", total_target=total_target,
        gang_size=min(512, total_chips // 8),
        max_alive=8192, check_leaks=True,
        slices=slices, include_setup=False,
    )
    problems = []
    if any(not r["alive"] for r in result["shard"]["replicas"]):
        problems.append("a planner replica died during the drive")
    if problems:
        raise RuntimeError("kilonode_sharded invariants violated: "
                           + "; ".join(problems))
    return result


def _kilonode_drive(cfg: TpuKubeConfig, metric: str, total_target: int,
                    gang_size: int,
                    max_alive: Optional[int] = None,
                    check_leaks: bool = False,
                    delta_stats: bool = False,
                    slices: Optional[dict] = None,
                    include_setup: bool = True) -> dict[str, Any]:
    """The shared kilonode churn driver (scenarios 10, 12, and 14): a
    committed training gang pins a contiguous block while burst waves
    arrive, run five simulated minutes, and complete, on the fake
    clock through the batched cycles. ``check_leaks`` adds the
    leaked-reservation invariant and ``delta_stats`` the ISSUE 10
    snapshot-maintenance numbers (delta-apply p50 vs a forced full
    rebuild p50 measured on the SAME loaded cluster at drive end).
    ``slices`` drives a multi-slice fleet (the sharded scenario's
    shape; the extender is then the ShardRouter when
    planner_replicas > 1), and ``include_setup=False`` excludes fleet
    minting + the initial node sync from the measured wall — at 100k
    nodes the one-time annotation encode/decode would otherwise
    swamp the steady-state number the scenario exists to record."""
    from collections import deque as _deque

    from tpukube.chaos import leaked_reservations, ledger_divergence
    from tpukube.core.clock import FakeClock
    from tpukube.obs.registry import quantile

    sample_every = 101  # full-webhook-protocol sampling cadence
    clock = FakeClock()
    t0 = time.perf_counter()
    # NodesCached sampled-webhook bodies (ISSUE 14 satellite): after
    # the one-time ingest the kilonode drives stop re-listing O(nodes)
    # names per sampled webhook (parity-tested in tests/test_shard_proc)
    with SimCluster(cfg, clock=clock, in_process=True,
                    slices=slices, cached_node_body=True) as c:
        setup_s = None
        if not include_setup:
            c._sync_nodes()  # the one-time node ingest, off the clock
            setup_s = round(time.perf_counter() - t0, 3)
            t0 = time.perf_counter()
        n_nodes = len(c.nodes)
        n_chips = sum(m.num_chips for m in c.slices.values())

        # a long-lived training gang pins a contiguous block while the
        # burst plane churns around it — the config-5 shape at 16x scale
        group = PodGroup("kilotrain", min_member=gang_size)
        gang_pods = [
            c.make_pod(f"kt-{i}", tpu=1, priority=100, group=group)
            for i in range(gang_size)
        ]
        c.schedule_pending(gang_pods)
        scheduled = gang_size
        sampled = 0

        capacity = n_chips - gang_size
        if max_alive is not None:
            # cap the live burst plane below mesh capacity so the
            # completion churn — the release-delta traffic the
            # incremental snapshot path must keep up with — starts
            # early even on a short smoke trace, instead of only after
            # the whole 40k-chip mesh fills
            capacity = min(capacity, max_alive)
        wave = min(cfg.batch_max_pods, capacity // 2)
        alive: _deque[str] = _deque()
        seq = 0
        waves = 0
        while scheduled < total_target:
            waves += 1
            room = capacity - len(alive)
            n = min(wave, room, total_target - scheduled)
            if n > 0:
                batch = []
                for _ in range(n):
                    name = f"burst-{seq}"
                    seq += 1
                    if seq % sample_every == 0:
                        # full per-pod webhook protocol for this one:
                        # filter/prioritize/bind latencies get sampled
                        c.schedule(c.make_pod(name, tpu=1))
                        sampled += 1
                    else:
                        batch.append(c.make_pod(name, tpu=1))
                    alive.append(name)
                if batch:
                    c.schedule_pending(batch)
                scheduled += n
            # the wave runs for five simulated minutes, then enough of
            # the oldest pods complete to make room for the next wave —
            # the mesh stays near-full, the steady-churn shape
            c.advance(300.0)
            done = min(len(alive), max(0, len(alive) + wave - capacity))
            for _ in range(done):
                c.pods.pop(f"default/{alive.popleft()}", None)
            c._lifecycle.check_once()
        wall = time.perf_counter() - t0

        ext = c.extender
        gangs = [g for g in ext.gang_snapshot() if g["group"] == "kilotrain"]
        committed = bool(gangs and gangs[0]["committed"])
        div = ledger_divergence(c)
        webhook_p99_ms = {
            handler: round(1000 * quantile(window, 0.99), 3)
            for handler, window in ext.latencies.items()
        }
        result = {
            "metric": metric,
            "value": round(scheduled / wall, 1),
            "unit": "pods scheduled per second",
            "nodes": n_nodes,
            "chips": n_chips,
            "pods_total": scheduled,
            "pods_sampled_full_protocol": sampled,
            "wall_s": round(wall, 3),
            "pods_per_sec": round(scheduled / wall, 1),
            # the fake clock's whole point: simulated hours per wall
            # second — the compression factor that makes kilonode
            # fleets measurable at all
            "sim_seconds": round(clock.monotonic(), 1),
            "time_compression": round(clock.monotonic() / wall, 1),
            "webhook_p99_ms": webhook_p99_ms,
            "gang_committed": committed,
            "ledger_divergence": len(div),
            "cycle": ext.cycle.stats() if ext.cycle is not None else None,
            "utilization_percent": round(100 * c.utilization(), 2),
        }
        if setup_s is not None:
            result["setup_s"] = setup_s
        # generation-based incremental resync (ISSUE 15): the per-wave
        # lifecycle reconcile's full-vs-incremental read counts and the
        # wire-shape bytes they moved — check.sh's coldstart smoke
        # floors the incremental-hit ratio on this key
        result["resync"] = c._lifecycle.resync_stats()
        statusz = getattr(ext, "statusz", None)
        if statusz is not None:
            # sharded plane: the router topology + rendezvous ledger +
            # per-replica summary rows ride the result
            doc = statusz()
            result["shard"] = {
                "replicas": [
                    {k: r[k] for k in ("replica", "alive", "nodes",
                                       "allocs", "pods_routed",
                                       "binds_total", "utilization")}
                    for r in doc["replicas"]
                ],
                "slice_assignment": doc["slice_assignment"],
                "rendezvous": doc["rendezvous"],
                # process mode: transport RTTs + health-check counters
                # ride the result (ISSUE 14)
                "transport": doc["transport"],
            }
            # federated lockgraph (ISSUE 18): with lock_monitor on, the
            # router merges its own observed lock-order edges with each
            # subprocess replica's (reported over the worker status
            # surface) and the fleet-wide cycle check rides the result
            lg_fn = getattr(ext, "lockgraph_report", None)
            if lg_fn is not None:
                lg = lg_fn()
                if lg is not None:
                    result["shard"]["lock_graph"] = {
                        "cycles": lg["cycles"],
                        "acquisitions": lg["acquisitions"],
                        "edge_count": len(lg["edges"]),
                        "replicas_reporting": lg["replicas_reporting"],
                    }
        wire_fn = getattr(ext, "wire_totals", None)
        if wire_fn is not None:
            # federated wire-cost accounting (ISSUE 16): the transport
            # byte bill normalized per churn wave — the measured
            # baseline the ROADMAP codec item is judged against (all
            # zeros over the in-process transport, which moves no
            # bytes)
            wt = wire_fn()
            top = sorted(wt["by_op"].items(),
                         key=lambda kv: -(kv[1]["tx"] + kv[1]["rx"]))
            result["wire"] = {
                "tx_bytes": wt["tx"],
                "rx_bytes": wt["rx"],
                "total_bytes": wt["total"],
                "waves": waves,
                "bytes_per_wave": (round(wt["total"] / waves, 1)
                                   if waves else 0.0),
                "per_replica": wt["per_replica"],
                "top_ops": dict(top[:8]),
            }
            if "codec" in wt:
                # binary wire codec (ISSUE 20): pre-compression frame
                # bytes and the resulting on-wire compression ratio —
                # keys appear only with the codec on, so the default
                # (json) drive result stays byte-identical
                result["wire"]["codec"] = wt["codec"]
                result["wire"]["raw_bytes"] = \
                    wt["raw_tx"] + wt["raw_rx"]
                result["wire"]["saved_bytes"] = wt["saved"]
                result["wire"]["compress_ratio"] = wt["ratio"]
        if ext.decisions is not None:
            # the measured-overhead guard (ISSUE 12): provenance's
            # cumulative record wall as a fraction of the drive wall —
            # check.sh's decisions smoke fails past the committed floor
            ds = ext.decisions.stats()
            result["decisions"] = {
                **ds,
                "overhead_pct": (round(100.0 * ds["record_seconds"]
                                       / wall, 3) if wall else None),
            }
        # capacity flight recorder (ISSUE 17): utilization-over-time +
        # the stranded forensics rollup ride the result, plus the
        # measured recorder overhead — check.sh's capacity smoke
        # floors it like the decisions overhead above
        cap = getattr(ext, "capacity", None)  # raw extender
        if cap is not None:
            cap_doc = cap.capacity_doc()
        else:  # router surface (federated; None when capacity is off)
            cap_fn = getattr(ext, "capacity_doc", None)
            cap_doc = cap_fn() if cap_fn is not None else None
        if cap_doc is not None:
            result["utilization_over_time"] = [
                (s.get("fleet") or {}).get("utilization")
                for s in cap_doc["samples"]
            ]
            result["stranded"] = cap_doc["stranded"]
            cstats = cap_doc.get("stats") or {}
            secs = cstats.get("sample_seconds")
            result["capacity"] = {
                **cstats,
                "overhead_pct": (
                    round(100.0 * secs / wall, 3)
                    if wall and isinstance(secs, (int, float))
                    else None),
            }
        if delta_stats:
            # the ISSUE 10 acceptance numbers: the O(Δ) delta-advance
            # p50 against a FORCED full-rebuild p50 on the same loaded
            # cluster (invalidate drops the cached snapshot, so the
            # next lookup re-derives every coord set from the ledger —
            # the pre-delta per-epoch cost)
            snaps = ext.snapshots
            applies = snaps.delta_apply_seconds_snapshot()
            # total maintenance cost normalized per cycle, captured
            # BEFORE the forced-rebuild measurement below inflates the
            # rebuild totals (the BENCH scaling sweep's per-point key)
            cycles = ext.cycle.cycles if ext.cycle is not None else 0
            maintain_s = (snaps.delta_apply_seconds_total
                          + snaps.rebuild_seconds_total)
            rebuild_walls = []
            for _ in range(5):
                snaps.invalidate()
                r0 = time.perf_counter()
                snaps.current()
                rebuild_walls.append(time.perf_counter() - r0)
            delta_p50 = quantile(applies, 0.5)
            rebuild_p50 = quantile(rebuild_walls, 0.5)
            result["snapshot"] = {
                "delta_applies": snaps.delta_applies,
                "delta_overflows": snaps.delta_overflows,
                "rebuilds": snaps.rebuilds,
                "snapshot_ms_per_cycle": (
                    round(1000 * maintain_s / cycles, 4) if cycles
                    else None
                ),
                "delta_apply_p50_ms": round(1000 * delta_p50, 4),
                "rebuild_p50_ms": round(1000 * rebuild_p50, 4),
                "delta_speedup": (
                    round(rebuild_p50 / delta_p50, 1)
                    if delta_p50 > 0 else None
                ),
            }
        problems = list(div)
        if check_leaks:
            problems += [str(p) for p in leaked_reservations(c)]
        if not committed:
            problems.append("the kilotrain gang never committed")
        if scheduled < total_target:
            problems.append(
                f"only {scheduled}/{total_target} pods scheduled"
            )
        if problems:
            raise RuntimeError(f"{metric} invariants violated: "
                               + "; ".join(problems[:5]))
        return result


def _complete_quiet(c: SimCluster, name: str) -> None:
    """complete_pod whose lifecycle step may hit an injected apiserver
    fault — the release is deferred, and converge() (the real daemons'
    retrying poll loops) picks it up next lap."""
    try:
        c.complete_pod(name)
    except RuntimeError:
        pass


def tenant_serving(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 11 (ISSUE 9): the multi-tenant serving plane under
    chaos — diurnal burst-infer waves from four synthetic tenants over
    a shared mesh while a committed training gang holds half of it, on
    the fake clock, with scenario 8's fault schedule reused verbatim.

    Shape: an 8x8x2 mesh (128 chips); tenant ``trainer`` commits a
    64-member gang; four burst tenants (``team-0..3``, 18-chip quotas)
    offer phase-shifted sinusoidal demand every simulated hour, far
    above the 64-chip burst plane — the DRF queue order must equalize
    their dominant shares. Mid-run a small priority-50 gang preempts
    its way in (tenant-aware victim choice) and deliberately commits
    slowly, burning the gang-schedule SLO past the page threshold —
    the admission controller then sheds over-share tenants' bursts
    with TenantAdmissionShed journal events.

    Raises on any violation: a tenant over quota at any wave, a
    steady-state max/min dominant-share ratio above 2.0, the training
    gang losing its commit, a shed or denial that is not journaled,
    leaked reservations, or ledger divergence.
    ``TPUKUBE_TENANCY_WAVES`` scales the trace (default 8)."""
    import math
    import os

    from tpukube.chaos import (
        ChaosSimCluster,
        FaultSchedule,
        converge,
        leaked_reservations,
        ledger_divergence,
    )
    from tpukube.core.clock import FakeClock
    from tpukube.sched import kube

    teams = [f"team-{i}" for i in range(4)]
    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_TENANCY_ENABLED": "1",
        "TPUKUBE_TENANCY_QUOTAS": "trainer=chips:72;" + ";".join(
            f"{t}=chips:18,hbm:0.2" for t in teams
        ),
        # burn windows ride the fake clock: hourly waves need a
        # window wide enough that a wave gap is not an "idle reset"
        # (BurnMonitor resets past two windows of silence)
        "TPUKUBE_TENANCY_BURN_WINDOW_SECONDS": "3600",
        # decision provenance on (ISSUE 12 acceptance): a shed pod's
        # explain output must name the burning SLO and the tenant's
        # share — asserted below against the actual sheds
        "TPUKUBE_DECISIONS_ENABLED": "1",
    }))
    waves = int(os.environ.get("TPUKUBE_TENANCY_WAVES", "8"))
    steady = [w for w in (2, 3, 4) if w < waves]
    burn_wave = 5  # the slow-commit SLO event
    seed = cfg.chaos_seed or 1337
    schedule_ = FaultSchedule(seed, scenario8_storm())
    clock = FakeClock()
    label = cfg.tenancy_label

    def demand(team_idx: int, hour: int) -> int:
        """Diurnal offered load: phase-shifted sine, 12..28 pods/hour —
        always above any achievable share, so every tenant stays
        backlogged and DRF fairness is actually load-bearing."""
        return round(20 + 8 * math.sin(
            2 * math.pi * (hour + 6 * team_idx) / 24.0
        ))

    with ChaosSimCluster(cfg, schedule_, clock=clock,
                         in_process=True) as c:
        ext = c.extender
        plane = ext.tenants
        assert plane is not None

        def robust(pod, deadline_rounds: int = 40):
            """schedule() with the requeue loop a real scheduler
            provides; each lap steps the effectors (eviction drain,
            lifecycle) so preemption/termination gates make progress
            under chaos, and degraded-mode refusals wait out the
            circuit's (wall-clock) reset window exactly as scenario 8
            does."""
            last = None
            for _ in range(deadline_rounds):
                try:
                    return c.schedule(pod)
                except RuntimeError as e:
                    last = e
                    if "degraded mode" in str(e):
                        time.sleep(c.CIRCUIT_RESET_S)
                    converge(c, rounds=3)
            raise RuntimeError(f"pod never scheduled: {last}")

        # the trained gang: half the mesh, committed before traffic
        train_group = PodGroup("diurnal-train", min_member=64)
        for i in range(64):
            robust(c.make_pod(
                f"dt-{i}", tpu=1, priority=100, group=train_group,
                labels={label: "trainer"},
            ))

        def committed(name: str) -> bool:
            return any(g["committed"] for g in ext.gang_snapshot()
                       if g["group"] == name)

        def drive(pods) -> list[str]:
            """Batch-drive one wave with the requeue semantics a real
            scheduler provides: admit (the enqueue-time gate may shed),
            plan (DRF order + plan-time gates), bind planned pods;
            chaos bind casualties and degraded-mode refusals requeue
            for another round (waiting out the circuit's wall-clock
            reset). Pods still unplaced after the rounds are abandoned
            — their objects leave the store and the lifecycle resync
            (converge) releases any assumed allocation they held.
            Returns placed pod names."""
            remaining = list(pods)
            placed: list[str] = []
            for _ in range(8):
                if not remaining:
                    break
                c._sync_nodes()
                try:
                    c.drain_evictions()
                except RuntimeError:
                    pass  # injected fault; converge retries below
                for obj in remaining:
                    ext.admit(kube.pod_from_k8s(obj))
                ext.plan_pending()
                still = []
                for obj in remaining:
                    meta = obj["metadata"]
                    key = f"{meta['namespace']}/{meta['name']}"
                    node = ext.planned_node(key)
                    if node is None:
                        still.append(obj)  # shed/denied/capacity
                        continue
                    bres = c._post("/bind", {
                        "PodName": meta["name"],
                        "PodNamespace": meta["namespace"],
                        "PodUID": meta["uid"],
                        "Node": node,
                    })
                    if bres.get("Error"):
                        if "degraded mode" in bres["Error"]:
                            time.sleep(c.CIRCUIT_RESET_S)
                        still.append(obj)  # requeue next round
                        continue
                    meta.setdefault("annotations", {}).update(
                        bres.get("Annotations", {})
                    )
                    obj["spec"]["nodeName"] = node
                    placed.append(meta["name"])
                remaining = still
                converge(c, rounds=3)
            for obj in remaining:
                meta = obj["metadata"]
                c.pods.pop(f"{meta['namespace']}/{meta['name']}", None)
            converge(c, rounds=3)
            return placed

        def team_chips() -> dict[str, float]:
            snap = plane.ledger.usage()
            return {t: (snap.usage[t].chips if t in snap.usage else 0.0)
                    for t in teams}

        alive: list[tuple[str, str]] = []  # (team, pod name), placement order
        seq = 0
        violations: list[str] = []
        ratio_samples: list[float] = []
        util_samples: list[float] = []
        pods_placed = 0
        for wave in range(waves):
            if wave == burn_wave:
                # the SLO event: a small priority-50 gang preempts its
                # way into the full mesh (tenant-aware victim choice)
                # and commits SLOWLY — 3 simulated seconds from
                # reservation to quorum blows the 2.5s gang SLO and
                # burns the budget at page rate
                probe_group = PodGroup("slo-probe", min_member=8)
                for i in range(7):
                    robust(c.make_pod(
                        f"sp-{i}", tpu=1, priority=50, group=probe_group,
                        labels={label: "trainer"},
                    ))
                c.advance(3.0)
                robust(c.make_pod(
                    "sp-7", tpu=1, priority=50, group=probe_group,
                    labels={label: "trainer"},
                ))
                converge(c)
                alive = [(t, n) for t, n in alive
                         if ext.state.allocation(f"default/{n}")
                         is not None]
                # skewed day's-end completions: team-1 finishes its
                # batch entirely and team-0 almost — the remaining
                # teams are now over the burst population's mean
                # share, exactly who shedding must select
                done = [(t, n) for t, n in alive if t == "team-1"]
                t0_alive = [(t, n) for t, n in alive if t == "team-0"]
                done += t0_alive[: max(0, len(t0_alive) - 4)]
                for t, n in done:
                    _complete_quiet(c, n)
                    alive.remove((t, n))
                converge(c)
            elif alive:
                # steady churn: the oldest half-plane of bursts ends
                done, alive = alive[:32], alive[32:]
                for _, name in done:
                    _complete_quiet(c, name)
                converge(c)

            wave_pods = []
            for i, team in enumerate(teams):
                for _ in range(demand(i, wave)):
                    wave_pods.append((team, c.make_pod(
                        f"b{seq}", tpu=1, priority=0,
                        labels={label: team},
                    )))
                    seq += 1
            placed = set(drive([obj for _, obj in wave_pods]))
            for team, obj in wave_pods:
                name = obj["metadata"]["name"]
                if name in placed:
                    alive.append((team, name))
                    pods_placed += 1

            # wave-end invariants
            usage = team_chips()
            snap = plane.ledger.usage()
            for tenant, quota in plane.quotas.items():
                held = (snap.usage[tenant].chips
                        if tenant in snap.usage else 0.0)
                if quota.chips is not None and held > quota.chips + 1e-6:
                    violations.append(
                        f"wave {wave}: {tenant} holds {held:g} chips over "
                        f"its {quota.chips:g} quota"
                    )
            if not committed("diurnal-train"):
                violations.append(
                    f"wave {wave}: the training gang lost its commit"
                )
            util_samples.append(c.utilization())
            if wave in steady:
                shares = [usage[t] for t in teams]
                if min(shares) > 0:
                    ratio_samples.append(max(shares) / min(shares))
                else:
                    violations.append(
                        f"wave {wave}: a tenant was starved to zero at "
                        f"steady state ({usage})"
                    )
            c.advance(3600.0)

        converge(c)
        reasons = ext.events.counts_by_reason()
        sheds, denials = plane.counter_snapshot()
        shed_total = sum(sheds.values())
        denial_total = sum(denials.values())
        leaks = leaked_reservations(c)
        div = ledger_divergence(c)
        stats = plane.stats()
        result = {
            "metric": "tenant_serving",
            "value": round(max(ratio_samples), 4) if ratio_samples else None,
            "unit": "max/min dominant-share ratio at steady state",
            "waves": waves,
            "sim_hours": round(clock.monotonic() / 3600.0, 2),
            "faults_injected": schedule_.injected(),
            "pods_placed": pods_placed,
            "preemptions": ext.preemptions,
            "quota_violations": len(violations),
            "sheds_by_tenant": sheds,
            "quota_denials_by_tenant": denials,
            "shed_events_journaled": reasons.get("TenantAdmissionShed", 0),
            "denial_events_journaled": reasons.get("TenantQuotaDenied", 0),
            "gangs_committed": [g["group"] for g in ext.gang_snapshot()
                                if g["committed"]],
            "steady_utilization_min_percent": round(
                100 * min(util_samples[w] for w in steady), 2
            ) if steady else None,
            "leaked_reservations": len(leaks),
            "ledger_divergence": len(div),
            "snapshot_audit": _audit_stats(c),
            "tenants": stats["tenants"],
        }
        problems = list(violations) + [str(p) for p in leaks] + div
        # ISSUE 12 acceptance: a shed pod's decision provenance must
        # answer why-denied naming the burning SLO and the tenant's
        # share (the explain layer's whole point — refusals are never
        # silent in it)
        if ext.decisions is not None and shed_total:
            # a shed pod may schedule in a later retry round once the
            # burn subsides; the assertion wants one whose FINAL state
            # is the refusal — scan newest-first for it
            doc = None
            for ev in reversed(
                ext.events.events(reason="TenantAdmissionShed")
            ):
                shed_key = ev["object"].split("pod/", 1)[1]
                cand = ext.decisions.explain(shed_key)
                if cand["verdict"] == "denied":
                    doc = cand
                    break
            text = json.dumps(doc) if doc is not None else ""
            slo_named = any(
                name in text for name in
                ("gang-schedule-latency", "bind-webhook-latency",
                 "tenant-admission-latency")
            )
            result["explain_shed"] = {
                "pod": doc["pod"] if doc is not None else None,
                "verdict": doc["verdict"] if doc is not None else None,
                "slo_named": slo_named,
            }
            if doc is None:
                problems.append(
                    "no shed pod explains as 'denied' — refusals are "
                    "missing from the provenance ring"
                )
            elif not slo_named or "burst_share" not in text:
                problems.append(
                    f"shed pod {doc['pod']}'s explain names no "
                    f"burning SLO / tenant share"
                )
        if ext.decisions is not None:
            result["decisions"] = ext.decisions.stats()
        if ratio_samples and max(ratio_samples) > 2.0:
            problems.append(
                f"steady-state share ratio {max(ratio_samples):.3f} > 2.0"
            )
        if waves > burn_wave:
            if shed_total == 0:
                problems.append("the SLO burn shed no admissions")
            if not committed("slo-probe"):
                problems.append("the slo-probe gang never committed")
            if ext.preemptions == 0:
                problems.append("the probe gang entered without "
                                "preemption on a full mesh")
        if denial_total == 0:
            problems.append("no quota denial was ever exercised")
        if shed_total != reasons.get("TenantAdmissionShed", 0):
            problems.append(
                f"{shed_total} sheds but "
                f"{reasons.get('TenantAdmissionShed', 0)} journaled — "
                f"sheds must never be silent"
            )
        if denial_total != reasons.get("TenantQuotaDenied", 0):
            problems.append(
                f"{denial_total} denials but "
                f"{reasons.get('TenantQuotaDenied', 0)} journaled"
            )
        if steady and min(util_samples[w] for w in steady) < 0.90:
            problems.append(
                f"steady utilization fell to "
                f"{100 * min(util_samples[w] for w in steady):.1f}%"
            )
        if problems:
            raise RuntimeError("scenario 11 invariants violated: "
                               + "; ".join(problems[:6]))
        return result


def crash_storm(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 13 (ISSUE 11): the crash-at-every-seam chaos storm —
    a scenario-8-style apiserver fault storm interleaved with repeated
    extender crash/restart cycles at kilonode-ish scale (256 nodes /
    1024 chips), with the durable-state journal carrying recovery.

    Per cycle: a burst wave schedules through the batched cycles on
    the fake clock (completion churn frees chips), the journal
    checkpoints on its cadence, then the extender "process" dies and
    the :class:`~tpukube.chaos.crash.CrashSchedule` mutilates the
    journal files the way a real crash at one of the append/checkpoint
    seams would (clean boundary, lost tail records, torn line, CRC
    corruption, torn checkpoint). The restart recovers via checkpoint
    + WAL replay + O(Δ) apiserver reconcile — under the SAME ongoing
    fault storm — and the cycle's invariants run: the committed
    training gang must still be committed, zero ledger divergence,
    zero leaked reservations.

    After the storm, checkpoint-warm recovery is timed against a cold
    ``rebuild_extender`` on the same final state (the ``recovery``
    perf-floor block's numbers). Raises on any invariant violation or
    an unbounded recovery time. ``TPUKUBE_CRASH_CYCLES`` scales the
    storm (default 8 — the acceptance minimum)."""
    import os
    import tempfile
    from dataclasses import replace as _dc_replace

    from tpukube.chaos import (
        ChaosSimCluster,
        CrashSchedule,
        FaultSchedule,
        converge,
        leaked_reservations,
        ledger_divergence,
    )
    from tpukube.core.clock import FakeClock
    from tpukube.sched import kube

    cycles = int(os.environ.get("TPUKUBE_CRASH_CYCLES", "8"))
    seed = (config.chaos_seed if config is not None
            else int(os.environ.get("TPUKUBE_CHAOS_SEED") or 0)) or 1337
    with tempfile.TemporaryDirectory(prefix="tpukube-journal-") as td:
        wal_path = os.path.join(td, "wal.jsonl")
        cfg = config or load_config(env=_env({
            "TPUKUBE_SIM_MESH_DIMS": "16,16,4",
            "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
            "TPUKUBE_BATCH_ENABLED": "1",
            "TPUKUBE_BATCH_MAX_PODS": "256",
            "TPUKUBE_JOURNAL_ENABLED": "1",
            "TPUKUBE_JOURNAL_PATH": wal_path,
            # two fake-clock waves per checkpoint: recoveries exercise
            # BOTH the checkpoint-restore and the WAL-tail-replay arms
            "TPUKUBE_CHECKPOINT_INTERVAL_SECONDS": "600",
        }))
        if not cfg.journal_enabled:
            raise RuntimeError(
                "scenario 13 needs journal_enabled (a --config must "
                "set journal_enabled + journal_path)"
            )
        wal_path = cfg.journal_path
        schedule_ = FaultSchedule(seed, scenario8_storm())
        crash_sched = CrashSchedule(seed + 7)
        clock = FakeClock()
        gang_size = 64
        violations: list[str] = []
        recovery_walls: list[float] = []
        modes: list[str] = []
        # audit totals ACROSS incarnations (each crash wipes the next
        # extender's counters; the storm's proof is the sum)
        audit_checks = audit_divergences = 0
        with ChaosSimCluster(cfg, schedule_, clock=clock,
                             in_process=True) as c:
            ext = c.extender

            def robust(pod, deadline_rounds: int = 60):
                last = None
                for _ in range(deadline_rounds):
                    try:
                        return c.schedule(pod)
                    except RuntimeError as e:
                        last = e
                        if "degraded mode" in str(e):
                            time.sleep(c.CIRCUIT_RESET_S)
                        converge(c, rounds=3)
                raise RuntimeError(f"pod never scheduled: {last}")

            def committed(name: str) -> bool:
                return any(
                    g["committed"] for g in c.extender.gang_snapshot()
                    if g["group"] == name
                )

            def drive(pods) -> int:
                """Batch-drive one wave with scheduler requeue
                semantics under chaos; unplaced pods are abandoned
                (their objects leave the store). Returns placed."""
                ext = c.extender
                remaining = list(pods)
                placed = 0
                for _ in range(8):
                    if not remaining:
                        break
                    c._sync_nodes()
                    try:
                        c.drain_evictions()
                    except RuntimeError:
                        pass  # injected fault; converge retries below
                    for obj in remaining:
                        ext.admit(kube.pod_from_k8s(obj))
                    ext.plan_pending()
                    still = []
                    for obj in remaining:
                        meta = obj["metadata"]
                        key = f"{meta['namespace']}/{meta['name']}"
                        node = ext.planned_node(key)
                        if node is None:
                            still.append(obj)
                            continue
                        bres = c._post("/bind", {
                            "PodName": meta["name"],
                            "PodNamespace": meta["namespace"],
                            "PodUID": meta["uid"],
                            "Node": node,
                        })
                        if bres.get("Error"):
                            if "degraded mode" in bres["Error"]:
                                time.sleep(c.CIRCUIT_RESET_S)
                            still.append(obj)
                            continue
                        meta.setdefault("annotations", {}).update(
                            bres.get("Annotations", {})
                        )
                        obj["spec"]["nodeName"] = node
                        placed += 1
                    remaining = still
                    converge(c, rounds=3)
                for obj in remaining:
                    meta = obj["metadata"]
                    c.pods.pop(f"{meta['namespace']}/{meta['name']}",
                               None)
                converge(c, rounds=3)
                return placed

            # the training gang whose commit every crash must survive
            group = PodGroup("stormtrain", min_member=gang_size)
            for i in range(gang_size):
                robust(c.make_pod(f"st-{i}", tpu=1, priority=100,
                                  group=group))
            if not committed("stormtrain"):
                raise RuntimeError("scenario 13: the training gang "
                                   "never committed")

            alive: list[str] = []
            seq = 0
            pods_placed = 0
            for cycle in range(cycles):
                # churn: the oldest half of the burst plane completes
                done, alive = alive[:64], alive[64:]
                for name in done:
                    _complete_quiet(c, name)
                converge(c, rounds=3)
                wave = []
                for _ in range(96):
                    wave.append(c.make_pod(f"b{seq}", tpu=1))
                    seq += 1
                names = [obj["metadata"]["name"] for obj in wave]
                got = drive(wave)
                pods_placed += got
                alive.extend(
                    n for n in names if f"default/{n}" in c.pods
                    and c.pods[f"default/{n}"]["spec"].get("nodeName")
                )
                c.advance(300.0)  # checkpoint cadence rides the clock

                # the crash: process death + one journal-seam outcome
                audit_checks += c.extender.snapshots.audit_checks
                audit_divergences += c.extender.snapshots.audit_divergences
                c.crash_extender()
                seam = crash_sched.next_seam()
                crash_sched.apply(seam, wal_path)
                t0 = time.perf_counter()
                c.restart_extender()
                recovery_walls.append(time.perf_counter() - t0)
                modes.append(c.last_recovery.get("mode", "?"))
                converge(c, rounds=5)

                # per-cycle invariants — a violation fails the storm
                if not committed("stormtrain"):
                    violations.append(
                        f"cycle {cycle} ({seam}): committed gang lost")
                div = ledger_divergence(c)
                if div:
                    violations.append(
                        f"cycle {cycle} ({seam}): ledger divergence "
                        f"{div[:2]}")
                leaks = leaked_reservations(c)
                if leaks:
                    violations.append(
                        f"cycle {cycle} ({seam}): leaked reservations "
                        f"{leaks[:2]}")

            # quiet: storm off, drain, final invariants
            schedule_.stop()
            converge(c)
            robust(c.make_pod("post-storm-probe", tpu=1))
            converge(c)

            # the acceptance measurement: checkpoint-warm recovery vs
            # a cold rebuild_extender of the SAME final state
            ext = c.extender
            ext.journal.write_checkpoint_sync(ext.checkpoint_doc())
            from tpukube.apiserver import rebuild_extender
            from tpukube.sched.extender import Extender as _Ext

            # same trace/events surface as the live extender — the cold
            # number must be the restart a journal-less daemon would
            # actually pay, not a stripped-down one
            cold_cfg = _dc_replace(cfg, journal_enabled=False,
                                   journal_path="")
            throwaway = _Ext(cold_cfg, clock=clock)
            t0 = time.perf_counter()
            cold_restored = rebuild_extender(throwaway, c._store_api)
            cold_s = time.perf_counter() - t0
            # the timing pair runs at the PRODUCTION audit setting (the
            # sentinel's two full rebuild-compares are a test-mode
            # cost); the ≥8 storm cycles above already proved the
            # recovered state correct at whatever rate the run pinned
            audit_rate = cfg.snapshot_audit_rate
            object.__setattr__(c.config, "snapshot_audit_rate", 0.0)
            try:
                c.crash_extender()
                t0 = time.perf_counter()
                c.restart_extender()
                warm_s = time.perf_counter() - t0
            finally:
                object.__setattr__(c.config, "snapshot_audit_rate",
                                   audit_rate)
            c.extender.snapshots.audit_rate = audit_rate
            warm = c.last_recovery
            converge(c)

            div = ledger_divergence(c)
            leaks = leaked_reservations(c)
            journal_stats = c.extender.journal.stats()
            reasons = c.extender.events.counts_by_reason()
            recovery_walls.sort()
            result = {
                "metric": "crash_storm",
                "value": len(recovery_walls),
                "unit": "crash/restart cycles survived",
                "crash_cycles": cycles,
                "seams": crash_sched.chosen,
                "recovery_modes": modes,
                "recovery_s_max": round(recovery_walls[-1], 4),
                "recovery_s_p50": round(
                    recovery_walls[len(recovery_walls) // 2], 4),
                "warm_recovery_s": round(warm_s, 4),
                "cold_rebuild_s": round(cold_s, 4),
                "replay_speedup": round(cold_s / warm_s, 2)
                if warm_s > 0 else None,
                "warm_mode": warm.get("mode"),
                "warm_from_checkpoint": warm.get("checkpoint"),
                "warm_replayed": warm.get("replayed"),
                "cold_restored": cold_restored,
                "pods_placed": pods_placed,
                "faults_injected": schedule_.injected(),
                "checkpoints": journal_stats["checkpoints"],
                "wal_appends": journal_stats["appends"],
                "wal_replayed_total": journal_stats["replayed_total"],
                # the LAST incarnation's journal events (an extender's
                # event ring dies with its process — that is the point)
                "recovery_events": {
                    k: reasons.get(k, 0)
                    for k in ("RecoveryCompleted", "RecoveryDiverged",
                              "JournalTruncated", "CheckpointWritten")
                },
                "ledger_divergence": len(div),
                "leaked_reservations": len(leaks),
                "snapshot_audit": {
                    "rate": cfg.snapshot_audit_rate,
                    "checks": audit_checks
                    + c.extender.snapshots.audit_checks,
                    "divergences": audit_divergences
                    + c.extender.snapshots.audit_divergences,
                },
                "utilization_percent": round(100 * c.utilization(), 2),
            }
            problems = list(violations) + div + [str(p) for p in leaks]
            if recovery_walls[-1] > 30.0:
                problems.append(
                    f"recovery took {recovery_walls[-1]:.1f}s — "
                    f"unbounded recovery time")
            if not warm.get("checkpoint"):
                problems.append(
                    "the final warm recovery did not load a checkpoint")
            if warm.get("mode") != "warm":
                problems.append(
                    f"final recovery mode {warm.get('mode')!r}, "
                    f"expected warm")
            if len(modes) != cycles:
                problems.append(
                    f"{len(modes)} recoveries ran for {cycles} crash "
                    f"cycles")
            if reasons.get("RecoveryCompleted", 0) < 1:
                problems.append(
                    "the final recovery was not journaled")
            if problems:
                raise RuntimeError("scenario 13 invariants violated: "
                                   + "; ".join(problems[:6]))
            return result


def crash_recovery(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 9: extender crash mid-gang-commit + cold restart.

    Half a gang binds, then the extender "process" dies — HTTP gone,
    ledger, reservations, and pending webhook context lost. A fresh
    extender rebuilds from the apiserver (node annotations + live
    bound pods' alloc annotations, via rebuild_from_pods), restoring
    the partial gang as a partial RESERVATION; the remaining members
    then bind and the gang commits. Rebuild residue the restart must
    skip — a finished pod's lingering annotation and an unbound pod's
    partial-failure annotation — is planted up front. The node-agent
    half restarts too: one member's Allocate runs through a device
    plugin that is torn down and re-registered mid-session.
    Acceptance: gang committed, zero leaked reservations, zero ledger
    divergence, recovery within the scenario wall.
    """
    from tpukube.chaos import converge, leaked_reservations, \
        ledger_divergence
    from tpukube.core import codec
    from tpukube.core.types import AllocResult, TopologyCoord

    cfg = config or load_config(env=_env({
        "TPUKUBE_SIM_MESH_DIMS": "4,4,1",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    }))
    with SimCluster(cfg) as c:
        group = PodGroup("phoenix", min_member=8)
        for i in range(4):
            c.schedule(c.make_pod(f"phoenix-{i}", tpu=1, priority=10,
                                  group=group))

        # rebuild-residue plants: a finished pod whose annotation
        # lingers (chips are free — restoring it would leak) and an
        # unbound pod carrying bind partial-failure residue
        c.schedule(c.make_pod("finished", tpu=1))
        c.pods["default/finished"].setdefault("status", {})[
            "phase"] = "Succeeded"
        residue = c.make_pod("residue", tpu=1)
        residue["metadata"]["annotations"][codec.ANNO_ALLOC] = (
            codec.encode_alloc(AllocResult(
                pod_key="default/residue", node_name="host-0-0-0",
                device_ids=["tpu-0"], coords=[TopologyCoord(0, 0, 0)],
                env={}, priority=0, uid="uid-default-residue",
            ))
        )

        ledger_before = len(c.extender.state.allocations())
        t0 = time.perf_counter()
        c.crash_extender()
        restored = c.restart_extender()
        gangs = c.extender.gang_snapshot()
        partial = [g for g in gangs if g["group"] == "phoenix"]
        restored_partial = bool(
            partial and not partial[0]["committed"]
            and partial[0]["members_bound"] == 4
        )

        # the crashed half's survivors + the rest of the gang
        last_alloc = None
        for i in range(4, 8):
            _, last_alloc = c.schedule(
                c.make_pod(f"phoenix-{i}", tpu=1, priority=10, group=group)
            )
        converge(c)
        recovery_s = time.perf_counter() - t0

        leaks = leaked_reservations(c)
        div = ledger_divergence(c)
        gangs = c.extender.gang_snapshot()
        committed = [g for g in gangs if g["group"] == "phoenix"
                     and g["committed"]]

        # node-agent teardown + cold restart mid-session: the restarted
        # agent re-registers and still serves the planned intent
        env = c.execute_allocation(last_alloc, restart_agent=True)

        result = {
            "metric": "crash_recovery",
            "value": round(recovery_s, 3),
            "unit": "s crash -> ledger converged",
            "recovery_s": round(recovery_s, 3),
            "members_before_crash": 4,
            "ledger_before_crash": ledger_before,
            "restored": restored,
            "partial_gang_restored": restored_partial,
            "gang_committed": bool(committed),
            "leaked_reservations": len(leaks),
            "ledger_divergence": len(div),
            "snapshot_audit": _audit_stats(c),
            "agent_restart_allocate_ok": bool(env),
        }
        problems = [str(p) for p in leaks] + div
        if restored != 4:
            problems.append(
                f"rebuild restored {restored} allocation(s), wanted 4 "
                f"(residue/finished must be skipped)")
        if not restored_partial:
            problems.append("partial gang did not restore as an "
                            "uncommitted reservation")
        if not committed:
            problems.append("gang did not commit after restart")
        if not env:
            problems.append("restarted node agent failed the Allocate")
        if problems:
            raise RuntimeError("scenario 9 invariants violated: "
                               + "; ".join(problems))
        return result


def maintenance_storm(config: TpuKubeConfig | None) -> dict[str, Any]:
    """Scenario 15 (ISSUE 19): region-scale fleet elasticity under
    chaos — maintenance events and spot churn rip capacity out of a
    live fleet while the drain choreography, the WAL, and the
    autoscaler put it back, in three phases:

    **A — maintenance storm.** A 4-slice fleet (64 chips) carries a
    committed training gang plus burst fillers on the fake clock with
    the journal on. Each cycle the seeded
    :class:`~tpukube.chaos.maintenance.MaintenanceSchedule` picks a
    slice to drain (graceful: cordon → budgeted migrate-or-preempt →
    un-ingest); every other cycle the extender CRASHES mid-choreography
    (mid-drain or mid-un-ingest — wherever the cycle's clock lands)
    and recovery must carry the cordon state through checkpoint + WAL
    replay; the :class:`~tpukube.chaos.maintenance.SpotChurnSchedule`
    additionally rips individual nodes out with no notice. Per-cycle
    invariants: the gang is allocated all-or-nothing (never partial),
    zero ledger divergence, zero leaked reservations, and the drain's
    per-tick disruption never exceeds ``drain_max_concurrent_moves``.
    Slices the schedule marks as returning are re-ingested through the
    bulk path.

    **B — autoscaler loop.** A fresh 2-slice batched cluster: a queue
    burst beyond ``autoscale_up_queue_depth`` must provision + bulk-
    ingest a new slice (time-to-capacity = one decision), and the
    post-burst idle fleet must drain the emptiest slice back down.

    **C — sharded rebalance-away.** ``planner_replicas=2`` in-process:
    draining one replica's ENTIRE slice set registers drain intent
    with the router (the health-check race fix's observable), survives
    crashing + restarting the OTHER replica mid-drain, and converges
    with zero leaks.

    Raises on any invariant violation. ``TPUKUBE_MAINT_CYCLES`` scales
    phase A (default 6 — at least one maintenance event per slice plus
    both crash arms); the acceptance drive runs the whole scenario at
    ``TPUKUBE_SNAPSHOT_AUDIT_RATE=1.0`` asserting zero divergences."""
    import os
    import tempfile

    from tpukube.chaos import (
        MaintenanceSchedule,
        SpotChurnSchedule,
        converge,
        leaked_reservations,
        ledger_divergence,
    )
    from tpukube.core.clock import FakeClock
    from tpukube.core.mesh import MeshSpec

    cycles = int(os.environ.get("TPUKUBE_MAINT_CYCLES", "6"))
    seed = (config.chaos_seed if config is not None
            else int(os.environ.get("TPUKUBE_CHAOS_SEED") or 0)) or 1337
    mesh = MeshSpec(dims=(4, 4, 1), host_block=(2, 2, 1))
    gang_size = 8
    problems: list[str] = []
    audit_checks = audit_divergences = 0
    peak_moves = 0

    def _drive_drain(c, ext, budget_ticks: int = 40) -> None:
        """Tick every active drain to completion (evictions drained
        between ticks — the effector loop a real deployment runs)."""
        for _ in range(budget_ticks):
            if ext.drain is None or not ext.drain.active():
                return
            c.clock.advance(1.0)
            ext.drain.tick()
            converge(c, rounds=3)
        raise RuntimeError("drain never completed within the tick "
                           "budget")

    def _gang_alloc_count(ext, prefix: str) -> int:
        return sum(1 for a in ext.state.allocations()
                   if a.pod_key.startswith(f"default/{prefix}"))

    def _drop_gang(c, prefix: str) -> None:
        """Tear a gang fully down: pods deleted, then the reservation
        TTL runs out on the fake clock and the sweep reclaims it — a
        dissolved gang must leave NOTHING for the leak check to find."""
        for i in range(gang_size):
            c.delete_pod(f"{prefix}{i}")
        converge(c, rounds=3)
        clock.advance(cfg.reservation_ttl_seconds + 1.0)
        c.extender.gang.sweep()
        converge(c, rounds=3)

    # ---- phase A: the maintenance storm --------------------------------
    with tempfile.TemporaryDirectory(prefix="tpukube-maint-") as td:
        wal_path = os.path.join(td, "wal.jsonl")
        cfg = config or load_config(env=_env({
            "TPUKUBE_DRAIN_ENABLED": "1",
            "TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES": "2",
            "TPUKUBE_JOURNAL_ENABLED": "1",
            "TPUKUBE_JOURNAL_PATH": wal_path,
            "TPUKUBE_CHECKPOINT_INTERVAL_SECONDS": "600",
            # the storm asserts cordons SURVIVE the crash; a buffered
            # tail would shed the latest cordon seam by design
            "TPUKUBE_JOURNAL_FSYNC": "always",
        }))
        if not cfg.drain_enabled:
            raise RuntimeError("scenario 15 needs drain_enabled (a "
                               "--config must set it)")
        maint = MaintenanceSchedule(seed, [f"s{i}" for i in range(4)],
                                    return_rate=0.5)
        spot = SpotChurnSchedule(seed + 3, kill_rate=0.5)
        clock = FakeClock()
        slices = {f"s{i}": mesh for i in range(4)}
        gang_gen = 0
        with SimCluster(cfg, slices=dict(slices), clock=clock,
                        in_process=True) as c:

            def commit_gang() -> str:
                """All-or-nothing by construction: a half-placed gang
                (fleet too small this cycle) is torn down so its
                reservations can't masquerade as a partial survival."""
                nonlocal gang_gen
                gang_gen += 1
                prefix = f"et{gang_gen}-"
                group = PodGroup(f"elastictrain{gang_gen}",
                                 min_member=gang_size)
                try:
                    for i in range(gang_size):
                        c.schedule(c.make_pod(f"{prefix}{i}", tpu=2,
                                              priority=100,
                                              group=group))
                except RuntimeError:
                    _drop_gang(c, prefix)
                    raise
                return prefix

            gang_prefix = commit_gang()
            fillers = []
            for i in range(6):
                name = f"fill-{i}"
                c.schedule(c.make_pod(name, tpu=1))
                fillers.append(name)

            drains_completed = 0
            spot_kills = 0
            returned_slices = 0
            refill_failures: list[str] = []
            for cycle in range(cycles):
                ext = c.extender
                event = maint.next_event()
                if event is None:
                    break
                sid, returns = event
                nodes = [n for n in ext.state.node_names()
                         if ext.state.slice_of_node(n) == sid]
                if not nodes:
                    # the slice left in an earlier cycle and never
                    # returned — the draw stands (determinism), the
                    # cycle's churn still runs below
                    pass
                else:
                    ext.drain.begin(nodes, reason="maintenance")
                    ext.drain.tick()
                    converge(c, rounds=3)
                    if cycle % 2 == 1:
                        # the crash arm: die mid-choreography; the
                        # cordon must ride the WAL into the fresh
                        # incarnation. When the first tick already
                        # finished the drain this is the mid-UN-INGEST
                        # crash: the provider has the capacity, so the
                        # store stops advertising it BEFORE the restart
                        # (else the O(Δ) reconcile would faithfully
                        # re-ingest what the apiserver still claims).
                        if not ext.drain.active():
                            c.forget_nodes(nodes)
                        audit_checks += ext.snapshots.audit_checks
                        audit_divergences += \
                            ext.snapshots.audit_divergences
                        c.crash_extender()
                        c.restart_extender()
                        ext = c.extender
                        still = sorted(
                            n for n in ext.state.cordoned_nodes())
                        missing = (set(nodes)
                                   & set(ext.state.node_names())
                                   ) - set(still)
                        if missing:
                            problems.append(
                                f"cycle {cycle}: cordon lost in "
                                f"recovery for {sorted(missing)[:2]}")
                        if still:
                            # the operator's resume: a fresh
                            # coordinator adopts the recovered cordon
                            ext.drain.begin(still, reason="maintenance")
                    _drive_drain(c, ext)
                    drains_completed += 1
                    peak_moves = max(peak_moves,
                                     ext.drain.peak_tick_moves)
                    if ext.drain.peak_tick_moves > \
                            cfg.drain_max_concurrent_moves:
                        problems.append(
                            f"cycle {cycle}: drain moved "
                            f"{ext.drain.peak_tick_moves} workloads in "
                            f"one tick (budget "
                            f"{cfg.drain_max_concurrent_moves})")
                    c.forget_nodes(nodes)
                    if returns:
                        items = c.add_slice(sid, mesh)
                        res = ext.handle("upsert_nodes",
                                         {"items": items})["results"]
                        errs = [r for r in res
                                if isinstance(r, dict) and r.get("error")]
                        if errs:
                            problems.append(
                                f"cycle {cycle}: re-ingest of {sid} "
                                f"failed: {errs[:1]}")
                        returned_slices += 1

                # spot churn: no cordon, no budget — the node is gone
                victim = spot.draw_kill(ext.state.node_names())
                if victim is not None:
                    doomed = [a.pod_key for a in ext.state.allocations()
                              if a.node_name == victim]
                    for key in doomed:
                        ns, name = key.split("/", 1)
                        c.delete_pod(name, namespace=ns)
                    converge(c, rounds=3)
                    out = ext.state.remove_nodes([victim])
                    if victim not in out["removed"]:
                        problems.append(
                            f"cycle {cycle}: spot victim {victim} not "
                            f"removable: {out['skipped']}")
                    c.forget_nodes([victim])
                    spot_kills += 1

                # the all-or-nothing invariant, then refill
                got = _gang_alloc_count(ext, gang_prefix)
                if got not in (0, gang_size):
                    problems.append(
                        f"cycle {cycle}: gang partially allocated "
                        f"({got}/{gang_size})")
                if got == 0:
                    _drop_gang(c, gang_prefix)
                    try:
                        gang_prefix = commit_gang()
                    except RuntimeError as e:
                        # fleet too small/fragmented this cycle — the
                        # next one retries after capacity returns
                        refill_failures.append(
                            f"cycle {cycle} (fleet "
                            f"{sorted(ext.state.slice_ids())}, "
                            f"{len(ext.state.node_names())} nodes): "
                            f"{str(e)[:200]}")
                for name in list(fillers):
                    if f"default/{name}" not in c.pods or not c.pods[
                            f"default/{name}"]["spec"].get("nodeName"):
                        c.delete_pod(name)
                        fillers.remove(name)
                div = ledger_divergence(c)
                if div:
                    problems.append(
                        f"cycle {cycle}: ledger divergence {div[:2]}")
                leaks = leaked_reservations(c)
                if leaks:
                    problems.append(
                        f"cycle {cycle}: leaked reservations "
                        f"{[str(p) for p in leaks[:2]]}")
                clock.advance(120.0)

            maint.stop()
            spot.stop()
            converge(c)
            audit_checks += c.extender.snapshots.audit_checks
            audit_divergences += c.extender.snapshots.audit_divergences
            drain_stats = c.extender.drain.stats() \
                if c.extender.drain is not None else {}
            storm_report = {
                "maintenance": maint.report(),
                "spot": spot.report(),
                "drains_completed": drains_completed,
                "spot_kills": spot_kills,
                "returned_slices": returned_slices,
                "gang_refill_failures": refill_failures,
                "final_slices": sorted(c.extender.state.slice_ids()),
                "last_incarnation_drain": drain_stats,
            }

    # ---- phase B: the autoscaler loop ----------------------------------
    cfg_b = load_config(env=_env({
        "TPUKUBE_DRAIN_ENABLED": "1",
        "TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES": "2",
        "TPUKUBE_AUTOSCALE_ENABLED": "1",
        "TPUKUBE_AUTOSCALE_MIN_SLICES": "2",
        "TPUKUBE_AUTOSCALE_MAX_SLICES": "4",
        "TPUKUBE_AUTOSCALE_UP_QUEUE_DEPTH": "4",
        "TPUKUBE_AUTOSCALE_DOWN_UTILIZATION": "0.25",
        "TPUKUBE_AUTOSCALE_COOLDOWN_SECONDS": "30",
        "TPUKUBE_BATCH_ENABLED": "1",
    }))
    clock_b = FakeClock()
    with SimCluster(cfg_b, slices={"s0": mesh, "s1": mesh},
                    clock=clock_b, in_process=True) as c:
        from tpukube.sched import kube

        ext = c.extender
        ext.autoscaler.set_provisioner(c.make_slice_provisioner(mesh))
        # saturate: 8 x 4-chip pods fill both 16-chip slices exactly
        held = [c.make_pod(f"hold-{i}", tpu=4) for i in range(8)]
        c.schedule_pending(held)
        # the burst beyond capacity: queue depth crosses the up
        # threshold; the next autoscaler decision must provision + bulk-
        # ingest a slice (time-to-capacity = one decision)
        burst = [c.make_pod(f"burst-{i}", tpu=4) for i in range(4)]
        for obj in burst:
            ext.admit(kube.pod_from_k8s(obj))
        up = ext.autoscaler.tick()
        if up != "up":
            problems.append(
                f"phase B: queued burst decided {up!r}, wanted 'up' "
                f"(depth {ext.cycle.queue_depth()})")
        n_after_up = len(ext.state.slice_ids())
        placed = c.schedule_pending(burst, retries=6)
        if len(placed) != len(burst):
            problems.append(
                f"phase B: only {len(placed)}/{len(burst)} burst pods "
                f"placed after scale-up")
        # idle down: everything completes, utilization collapses
        for obj in held + burst:
            _complete_quiet(c, obj["metadata"]["name"])
        converge(c, rounds=5)
        clock_b.advance(60.0)
        decision = ext.autoscaler.tick()
        if decision != "down":
            problems.append(
                f"phase B: idle fleet decided {decision!r}, wanted "
                f"'down'")
        _drive_drain(c, ext)
        gone = [sid for sid in list(c.slices)
                if sid not in ext.state.slice_ids()]
        c.forget_nodes([n for n in list(c.nodes)
                        if c.nodes[n].slice_id in gone])
        scale_report = {
            "scale_ups": ext.autoscaler.scale_ups,
            "scale_downs": ext.autoscaler.scale_downs,
            "slices_after_up": n_after_up,
            "slices_final": sorted(ext.state.slice_ids()),
        }
        audit_checks += ext.snapshots.audit_checks
        audit_divergences += ext.snapshots.audit_divergences

    # ---- phase C: sharded rebalance-away -------------------------------
    cfg_c = load_config(env=_env({
        "TPUKUBE_DRAIN_ENABLED": "1",
        "TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES": "2",
        "TPUKUBE_PLANNER_REPLICAS": "2",
    }))
    clock_c = FakeClock()
    with SimCluster(cfg_c, slices={f"s{i}": mesh for i in range(4)},
                    clock=clock_c, in_process=True) as c:
        router = c.extender
        for i in range(8):
            c.schedule(c.make_pod(f"sp-{i}", tpu=2))
        with router._lock:
            assign = dict(router._slice_replica)
        # drain EVERY slice the second replica owns (rebalance-away)
        target_idx = 1
        target_slices = sorted(s for s, i in assign.items()
                               if i == target_idx)
        rext = router.replicas[target_idx].extender
        drained_nodes: list[str] = []
        if not target_slices:
            problems.append("phase C: replica 1 owns no slices")
        else:
            for sid in target_slices:
                drained_nodes.extend(
                    n for n in rext.state.node_names()
                    if rext.state.slice_of_node(n) == sid)
            rext.drain.begin(drained_nodes, reason="rebalance-away")
            if "drain_intent" not in router.statusz():
                problems.append(
                    "phase C: drain intent missing from router statusz")
            # the OTHER replica dies and cold-restarts mid-drain
            c.crash_replica(0)
            c.restart_replica(0)
            for _ in range(40):
                if not rext.drain.active():
                    break
                clock_c.advance(1.0)
                rext.drain.tick()
                converge(c, rounds=3)
            if rext.drain.active():
                problems.append("phase C: rebalance drain never "
                                "completed")
            if "drain_intent" in router.statusz():
                problems.append(
                    "phase C: drain intent not cleared at completion")
            c.forget_nodes(drained_nodes)
        converge(c)
        div = ledger_divergence(c)
        if div:
            problems.append(f"phase C: ledger divergence {div[:2]}")
        leaks = leaked_reservations(c)
        if leaks:
            problems.append(
                f"phase C: leaked reservations "
                f"{[str(p) for p in leaks[:2]]}")
        shard_report = {
            "slice_assignment": assign,
            "drained_slices": target_slices,
            "drained_nodes": len(drained_nodes),
            "health_skips_draining":
                router.health_skips_draining_total,
        }

    result = {
        "metric": "maintenance_storm",
        "value": storm_report["drains_completed"]
        + scale_report["scale_downs"] + len(target_slices),
        "unit": "graceful drains survived",
        "cycles": cycles,
        "seed": seed,
        "storm": storm_report,
        "autoscale": scale_report,
        "sharded": shard_report,
        "peak_tick_moves": peak_moves,
        "budget_moves": cfg.drain_max_concurrent_moves,
        "snapshot_audit": {
            "rate": cfg.snapshot_audit_rate,
            "checks": audit_checks,
            "divergences": audit_divergences,
        },
    }
    if audit_divergences:
        problems.append(
            f"{audit_divergences} snapshot audit divergence(s)")
    if problems:
        raise RuntimeError("scenario 15 invariants violated: "
                           + "; ".join(problems[:6]))
    return result

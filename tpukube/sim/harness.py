"""SimCluster — plays apiserver + kube-scheduler against a real extender.

The extender runs as a real aiohttp server on localhost; this harness POSTs
the actual kube-scheduler webhook JSON (filter -> prioritize -> pick max ->
bind), stores returned alloc annotations on its pod records (the apiserver's
job), and can additionally execute an allocation through a real
DevicePluginServer + FakeKubelet over unix sockets to prove the scheduler
and node-agent halves compose (SURVEY.md §4.2 + §4.3 end to end).

Node data is minted directly from MeshSpec geometry — running one real
libtpuinfo-backed agent per simulated node is impossible in one process
(the native layer is single-instance by design, like NVML), and the
annotation codec is the actual interface the extender consumes anyway.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import logging
import socket
import threading
import urllib.request
from typing import Any, Optional

from aiohttp import web

from tpukube.core import codec
from tpukube.core.config import TpuKubeConfig, load_config
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    RESOURCE_TPU,
    RESOURCE_VTPU,
    AllocResult,
    ChipInfo,
    Health,
    NodeInfo,
    PodGroup,
    TopologyCoord,
    canonical_link,
)
from tpukube.apiserver import EvictionExecutor, PodLifecycleReleaseLoop
from tpukube.sched.extender import Extender, make_app

log = logging.getLogger("tpukube.sim")


class _PodStoreApi:
    """Adapter giving EvictionExecutor, PodLifecycleReleaseLoop, the
    bind effector, and the restart rebuild the apiserver surface over
    the harness's in-memory pod store (no PDBs in the sim).
    ``nodes_fn`` supplies Node objects for ``list_nodes`` (the
    restart-rebuild's topology source)."""

    def __init__(self, pods: dict[str, dict[str, Any]],
                 nodes_fn=None) -> None:
        self._pods = pods
        self._nodes_fn = nodes_fn

    def evict_pod(
        self, namespace: str, name: str, dry_run: bool = False
    ) -> bool:
        if dry_run:
            return True  # no PDBs in the sim
        pod = self._pods.pop(f"{namespace}/{name}", None)
        if pod is not None:
            pod["metadata"].get("annotations", {}).pop(codec.ANNO_ALLOC, None)
            pod["spec"].pop("nodeName", None)
        return True

    def get_pod(self, namespace: str, name: str) -> Optional[dict[str, Any]]:
        return self._pods.get(f"{namespace}/{name}")

    def list_pods(self, node_name: Optional[str] = None) -> list[dict[str, Any]]:
        return [
            p for p in list(self._pods.values())
            if node_name is None
            or p.get("spec", {}).get("nodeName") == node_name
        ]

    def list_nodes(self) -> list[dict[str, Any]]:
        return self._nodes_fn() if self._nodes_fn is not None else []

    def bind_pod(
        self, namespace: str, name: str, node: str,
        annotations: Optional[dict[str, str]] = None,
    ) -> None:
        """FakeApiServer.bind_pod semantics over the dict store:
        conflict check first, already-bound-to-the-same-node is
        idempotent-retry success (what makes torn bind writes safe to
        retry), 404 when the pod is gone."""
        from tpukube.apiserver import ApiServerError

        key = f"{namespace}/{name}"
        pod = self._pods.get(key)
        if pod is None:
            raise ApiServerError(f"pod {key} not found", code=404)
        spec = pod.setdefault("spec", {})
        bound_to = spec.get("nodeName")
        if bound_to and bound_to != node:
            raise ApiServerError(
                f"pod {key} is already bound to {bound_to!r}, "
                f"not {node!r}", code=409,
            )
        if annotations:
            pod["metadata"].setdefault("annotations", {}).update(annotations)
        spec["nodeName"] = node

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: dict[str, Optional[str]]
    ) -> None:
        """Merge-patch (None deletes), 404 on a missing pod — mirrors
        the real channel so reconcile/divergence paths run unchanged."""
        from tpukube.apiserver import ApiServerError

        key = f"{namespace}/{name}"
        pod = self._pods.get(key)
        if pod is None:
            raise ApiServerError(f"pod {key} not found", code=404)
        annos = pod["metadata"].setdefault("annotations", {})
        for k, v in annotations.items():
            if v is None:
                annos.pop(k, None)
            else:
                annos[k] = v


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _AppThread:
    """Runs an aiohttp app in a background thread with its own loop.
    ``ssl_context`` serves TLS — the same path cli.main_extender uses,
    so the auth tests exercise the real serving configuration."""

    def __init__(self, app: web.Application, host: str, port: int,
                 ssl_context=None):
        self._app = app
        self._host = host
        self._port = port
        self._ssl = ssl_context
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpukube-extender-http")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("extender HTTP server failed to start")

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        runner = web.AppRunner(self._app)
        self._loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self._host, self._port,
                           ssl_context=self._ssl)
        self._loop.run_until_complete(site.start())
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.run_until_complete(runner.cleanup())
            self._loop.close()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class SimCluster:
    """A simulated multi-node TPU cluster around one live Extender."""

    def __init__(
        self,
        config: Optional[TpuKubeConfig] = None,
        mesh: Optional[MeshSpec] = None,
        vtpu_nodes: Optional[set[str]] = None,
        vtpu_shares: int = 2,
        slices: Optional[dict[str, MeshSpec]] = None,
        clock=None,
        in_process: bool = False,
        cached_node_body: bool = False,
    ):
        """Single-slice by default (``mesh``); pass ``slices`` (slice id ->
        MeshSpec) for a multi-slice cluster — node names are then prefixed
        "<slice>-host-i-j-k" so they stay unique cluster-wide.

        ``clock`` (core/clock.py) threads an injectable — typically a
        :class:`~tpukube.core.clock.FakeClock` — through every
        scheduling-semantic timer (gang TTLs, pending-webhook pruning,
        eviction-confirm ages), so kilonode churn traces simulate hours
        in seconds of wall time. ``in_process=True`` skips the HTTP
        listener and routes the webhook protocol straight into
        ``Extender.handle`` — the same decision path minus sockets and
        JSON transport, for benches that measure scheduling compute."""
        from tpukube.core.clock import SYSTEM

        self.config = config or load_config(env={})
        self.clock = clock if clock is not None else SYSTEM
        self._in_process = in_process
        # nodeCacheCapable taken to its conclusion (ISSUE 14 satellite):
        # once the extender has ingested the node set, sampled webhooks
        # send {"NodesCached": true} instead of re-listing 10k node
        # names per request; the extender expands the candidate set
        # from its own cache. Placements are parity-tested against the
        # protocol-faithful names body (default off).
        self._cached_node_body = cached_node_body
        if slices is not None and mesh is not None:
            raise ValueError("pass either mesh or slices, not both")
        # the dynamic lock-order detector must be live BEFORE the
        # extender (and its gang/ledger locks) is constructed below;
        # install is ref-counted, so a cluster inside an outer
        # lockgraph.monitor() shares that monitor. A constructor that
        # fails later must unwind the install (stop() never runs for a
        # half-built cluster) — a leaked patch would silently wrap every
        # tpukube lock for the rest of the process.
        self.lock_monitor = None
        self._lock_monitor_installed = False
        if self.config.lock_monitor:
            from tpukube.analysis import lockgraph

            self.lock_monitor = lockgraph.install()
            self._lock_monitor_installed = True
        try:
            self._init_cluster(mesh, vtpu_nodes, vtpu_shares, slices)
        except BaseException:
            if self._lock_monitor_installed:
                from tpukube.analysis import lockgraph

                lockgraph.uninstall()
                self._lock_monitor_installed = False
            raise

    def _init_cluster(self, mesh, vtpu_nodes, vtpu_shares, slices) -> None:
        self._prefixed = slices is not None
        if slices is None:
            slices = {self.config.slice_id: mesh or self.config.sim_mesh()}
        self.slices: dict[str, MeshSpec] = dict(slices)
        # single-slice convenience handle (most tests/scenarios)
        self.mesh: Optional[MeshSpec] = (
            next(iter(self.slices.values())) if len(self.slices) == 1 else None
        )
        self._vtpu_nodes = vtpu_nodes or set()
        self._vtpu_shares = vtpu_shares
        self.nodes: dict[str, NodeInfo] = {}
        for sid in sorted(self.slices):
            m = self.slices[sid]
            for host in m.all_hosts():
                name = f"{sid}-{host}" if self._prefixed else host
                chips = [
                    ChipInfo(
                        chip_id=f"{name}-chip-{i}",
                        index=i,
                        coord=coord,
                        hbm_bytes=self.config.hbm_bytes_per_chip,
                        num_cores=self.config.cores_per_chip,
                    )
                    for i, coord in enumerate(m.coords_of_host(host))
                ]
                shares = self._vtpu_shares if name in self._vtpu_nodes else 1
                self.nodes[name] = NodeInfo(
                    name=name, chips=chips, shares_per_chip=shares,
                    slice_id=sid,
                )
        if (self.config.planner_replicas > 1
                or self.config.shard_transport == "subprocess"):
            # Slice-partitioned control plane (sched/shard.py): N full
            # planner replicas behind the router, each owning a
            # disjoint slice set. The router speaks the Extender
            # decision surface, so everything downstream (effectors,
            # schedulers, chaos checkers) runs unchanged. With
            # shard_transport=subprocess each replica is a spawned
            # worker DAEMON (even at N=1 — that point is the process-
            # mode parity/throughput baseline) and the router fans
            # calls out over the webhook HTTP contract.
            from tpukube.sched.shard import ShardRouter

            self.extender: Any = ShardRouter(self.config,
                                             clock=self.clock)
        else:
            self.extender = Extender(self.config, clock=self.clock)
        self.pods: dict[str, dict[str, Any]] = {}  # key -> pod object
        # stats of the last restart_extender() recovery (None before)
        self.last_recovery: Optional[dict[str, Any]] = None
        self._store_api = self._make_store_api()
        self._wire_extender()
        self._node_obj_cache: dict[str, dict[str, Any]] = {}
        self._node_objs_list: Optional[list[dict[str, Any]]] = None
        self._synced_objs: list[dict[str, Any]] = []  # see _extender_node_args
        # the names-only webhook body, cached alongside _synced_objs:
        # rebuilding a 10k-entry name list per sampled webhook was an
        # O(nodes) harness term the kilonode drives paid per pod
        # (ISSUE 14 satellite; parity-tested against the rebuild-
        # every-webhook protocol-faithful path)
        self._synced_names: list[str] = []
        self._port = _free_port()
        self._http: Optional[_AppThread] = None
        # keep-alive connection per client thread (kube-scheduler likewise
        # reuses connections to its extenders; per-request TCP setup was
        # the dominant term in the measured gang-commit latency).
        # http.client connections are not thread-safe, and tests drive
        # schedule() from many threads at once — hence thread-local.
        self._tls = threading.local()

    def _make_store_api(self):
        """The apiserver surface the effectors run against; the chaos
        harness overrides this to wrap it in a fault injector."""
        return _PodStoreApi(self.pods, nodes_fn=self.node_objects)

    def _wire_extender(self) -> None:
        """Attach the effectors a real extender daemon wires (eviction
        executor, lifecycle release loop, PDB precheck) to
        ``self.extender`` — called at construction AND after a
        restart_extender() cold start, exactly like a fresh daemon
        main. The chaos harness extends this with binder/retry/circuit
        wiring."""
        store_api = self._store_api
        self._evictions = EvictionExecutor(
            self.extender, store_api, clock=self.clock
        )  # drained inline by schedule(); not started as a thread
        # same release loop a real extender daemon runs, stepped
        # deterministically (delete_pod/complete_pod) instead of as a
        # thread — the sim has no manual extender.release side channel
        self._lifecycle = PodLifecycleReleaseLoop(
            self.extender, store_api, use_watch=False,
            evictions=self._evictions,
        )
        # PDB precheck for preemption plans, same dry-run shape the real
        # daemon wires (trivially true here: the sim has no PDBs)
        self.extender.evict_precheck = (
            lambda pod_key: store_api.evict_pod(
                *pod_key.split("/", 1), dry_run=True
            )
        )

    # -- lifecycle ---------------------------------------------------------
    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    def advance(self, seconds: float) -> None:
        """Advance the injected fake clock (discrete-event time).
        Raises on a real clock — a sim that thinks it is compressing
        time while actually sleeping wall time is a silent lie.
        A process-mode sharded cluster fans the advance out to its
        worker daemons so every replica's scheduling clock moves in
        lockstep with the router's."""
        advance = getattr(self.clock, "advance", None)
        if advance is None:
            raise RuntimeError(
                "advance() needs a FakeClock (pass clock=FakeClock())"
            )
        advance(seconds)
        fan = getattr(self.extender, "advance_replicas", None)
        if fan is not None:
            fan(seconds)

    def start(self) -> None:
        if self._in_process:
            return  # webhooks dispatch straight into Extender.handle
        if (self.config.planner_replicas > 1
                or self.config.shard_transport == "subprocess"):
            raise RuntimeError(
                "a sharded SimCluster (planner_replicas > 1) runs "
                "in_process=True — the in-process router is the "
                "sim/bench plane; production replicas serve as "
                "separate extender daemons"
            )
        try:
            # the same loop objects the production daemon hands
            # make_app (cli.main_extender): the sim daemon's /statusz
            # resync/evictions sections answer like the real one's
            self._http = _AppThread(
                make_app(self.extender, evictions=self._evictions,
                         lifecycle=self._lifecycle),
                "127.0.0.1", self._port)
            self._http.start()
        except BaseException:
            # __enter__ raising means __exit__/stop() never runs: the
            # process-wide threading patch must not outlive the failed
            # startup (same unwind as the constructor's failure path)
            if self._lock_monitor_installed:
                from tpukube.analysis import lockgraph

                lockgraph.uninstall()
                self._lock_monitor_installed = False
            raise

    def stop(self) -> None:
        try:
            conn = getattr(self._tls, "conn", None)
            if conn is not None:
                conn.close()
                self._tls.conn = None
            if self._http is not None:
                self._http.stop()
                self._http = None
            # sink writes drain on a background thread (trace.JsonlSink);
            # closing here is what makes "read the capture after the with
            # block" deterministic for tests and scenario code
            shutdown = getattr(self.extender, "shutdown", None)
            if shutdown is not None:
                shutdown()  # ShardRouter: closes every replica's sinks
            else:
                if self.extender.trace is not None:
                    self.extender.trace.close()
                if self.extender.capacity is not None:
                    self.extender.capacity.close()
                self.extender.events.close()
                if self.extender.journal is not None:
                    self.extender.journal.close()
                    self.extender.state.retire()
        finally:
            # the process-wide threading patch must unwind even when a
            # sink close raises (full disk) — same hazard the
            # constructor's failure path unwinds
            if self._lock_monitor_installed:
                from tpukube.analysis import lockgraph

                lockgraph.uninstall()
                self._lock_monitor_installed = False

    def __enter__(self) -> "SimCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- replica chaos (sharded plane; ISSUE 13) -----------------------------
    def _router(self):
        from tpukube.sched.shard import ShardRouter

        if not isinstance(self.extender, ShardRouter):
            raise RuntimeError(
                "replica chaos needs a sharded cluster "
                "(planner_replicas > 1)"
            )
        return self.extender

    def crash_replica(self, idx: int) -> None:
        """Kill ONE planner replica: its in-memory shard state —
        ledger, reservations, queue, plans — is gone, nothing flushed;
        the router keeps serving around it and the rendezvous janitor
        aborts any uncommitted rendezvous holding a part there."""
        self._router().kill_replica(idx)

    def partition_replica(self, idx: int) -> None:
        """Partition ONE replica away from the router (state survives,
        unreachable); ``heal_replica`` ends the partition."""
        self._router().partition_replica(idx)

    def heal_replica(self, idx: int) -> None:
        self._router().heal_replica(idx)

    def restart_replica(self, idx: int) -> int:
        """Cold-restart a killed replica the way a restarted shard
        daemon does: fresh Extender, its node subset re-ingested, its
        ledger + gangs rebuilt from the pod store's annotations
        (``rebuild_from_pods`` — the convergence path the chaos
        acceptance asserts). Returns allocations restored."""
        from tpukube.apiserver import live_alloc_pods

        router = self._router()
        node_annos = [
            (obj["metadata"]["name"], obj["metadata"]["annotations"])
            for obj in self.node_objects()
            if router._node_replica.get(obj["metadata"]["name"]) == idx
        ]
        # the SAME lifecycle filter every restart path applies:
        # terminal-phase pods' annotation residue must not be restored
        full_pods = router.replica_pods(idx, self.pods)
        pods = [
            annotations for annotations, _alloc, _key in
            live_alloc_pods(full_pods)
        ]
        # the full pod objects ride along so a journal-enabled replica
        # can replay its own segment (warm restart) and reconcile
        # against the same truth the cold rebuild would consume
        return router.restart_replica(idx, node_annos, pods,
                                      pod_objects=full_pods)

    # -- crash / cold restart (chaos scenario 9) -----------------------------
    def crash_extender(self) -> None:
        """Simulate extender process death mid-flight: the HTTP
        listener disappears and every piece of in-memory scheduler
        state — ledger, gang reservations, pending webhook context,
        queued evictions — is gone. Nothing is flushed or unwound;
        that is the point."""
        if (self.config.planner_replicas > 1
                or self.config.shard_transport == "subprocess"):
            raise RuntimeError(
                "sharded cluster: crash/restart individual replicas "
                "(crash_replica/restart_replica), not the whole plane"
            )
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
            self._tls.conn = None
        if self._http is not None:
            self._http.stop()
            self._http = None
        if self.extender.journal is not None:
            # a real crash loses the journal's queued-but-undrained
            # records and flushes nothing — crash() models exactly
            # that; retiring the ledger stops its background warmer
            # (a real crash kills threads, the sim must too)
            self.extender.journal.crash()
            self.extender.state.retire()

    def restart_extender(self) -> int:
        """Cold-start a fresh extender the way a restarted daemon does:
        new Extender, state recovered — via the durable journal
        (checkpoint + WAL replay + O(Δ) apiserver reconcile,
        sched/journal.py) when journal_enabled, else the legacy full
        rebuild from the apiserver (apiserver.rebuild_extender) —
        effectors re-wired, HTTP serving resumed on the same port.
        ``self.last_recovery`` carries the recovery stats. Returns the
        number of allocations restored/known after recovery."""
        from tpukube.apiserver import rebuild_extender

        if self._http is not None:
            raise RuntimeError("crash_extender() first — the old "
                               "extender is still serving")
        self.extender = Extender(self.config, clock=self.clock)
        self._wire_extender()
        if self.extender.journal is not None:
            from tpukube.sched import journal as journal_mod

            try:
                self.last_recovery = journal_mod.recover_extender(
                    self.extender, self._store_api
                )
                restored = len(self.extender.state.allocations())
            except journal_mod.JournalError as e:
                # the journal could not produce a trustworthy base
                # (WAL gap, undecodable checkpoint): fall back to the
                # legacy full rebuild on a FRESH extender — the failed
                # recovery may have half-restored state
                log.error("journal recovery failed (%s); falling back "
                          "to the legacy full rebuild", e)
                self.extender.journal.crash()
                self.extender = Extender(self.config, clock=self.clock)
                self._wire_extender()
                self.extender.state.set_journal(None)
                self.extender.gang.set_journal(None)
                restored = rebuild_extender(self.extender,
                                            self._store_api)
                self.extender.state.set_journal(self.extender.journal)
                self.extender.gang.set_journal(self.extender.journal)
                self.extender.journal.write_checkpoint_sync(
                    self.extender.checkpoint_doc()
                )
                self.last_recovery = {
                    "mode": "cold-fallback", "error": str(e),
                    "restored_allocs": restored,
                }
        else:
            restored = rebuild_extender(self.extender, self._store_api)
            self.last_recovery = {"mode": "cold",
                                  "restored_allocs": restored}
        # the fresh extender has ingested nothing over the webhook
        # channel yet: the next schedule() must send full node objects
        self._commit_synced([])
        if not self._in_process:
            # the same loop objects the production daemon hands
            # make_app (cli.main_extender): the sim daemon's /statusz
            # resync/evictions sections answer like the real one's
            self._http = _AppThread(
                make_app(self.extender, evictions=self._evictions,
                         lifecycle=self._lifecycle),
                "127.0.0.1", self._port)
            self._http.start()
        return restored

    # -- kube-object minting -----------------------------------------------
    def _invalidate_node(self, name: str) -> None:
        self._node_obj_cache.pop(name, None)
        self._node_objs_list = None

    def node_objects(self) -> list[dict[str, Any]]:
        """Node API objects as kube-scheduler would send them. Encoded
        annotations are cached per node (schedule() resends every node on
        every webhook; re-encoding 32 nodes per cycle dominated the sim's
        own overhead) — fault injection invalidates the touched node.
        The assembled LIST is cached too: re-sorting 10k node names per
        sampled webhook was the kilonode drives' dominant harness term
        (the measured 'filter p99' was mostly this sort)."""
        cached = getattr(self, "_node_objs_list", None)
        if cached is not None:
            return cached
        out = []
        for name, info in sorted(self.nodes.items()):
            obj = self._node_obj_cache.get(name)
            if obj is None:
                obj = {
                    "metadata": {
                        "name": name,
                        "annotations": codec.annotate_node(
                            info, self.slices[info.slice_id]
                        ),
                    }
                }
                self._node_obj_cache[name] = obj
            out.append(obj)
        self._node_objs_list = out
        return out

    def _extender_node_args(
        self,
    ) -> tuple[dict[str, Any], Optional[list[dict[str, Any]]]]:
        """The node half of ExtenderArgs, nodeCacheCapable style: full
        node objects only when some annotation changed since the last full
        send (playing the annotation syncer's cache-refresh role), names
        only otherwise — the same traffic shape a kube-scheduler
        configured with nodeCacheCapable:true produces, and the reason the
        per-cycle webhook payload is ~1KB instead of the whole topology.

        Returns (args, pending_objs): the caller commits pending_objs to
        ``_synced_objs`` only AFTER the full send's response arrives —
        marking earlier would let a concurrent scheduler thread go
        names-only against an extender that has not ingested yet."""
        objs = self.node_objects()
        # cached objects are reused between cycles, so identity comparison
        # catches "nothing changed" without hashing annotation payloads.
        # _synced_objs holds real references (not bare id()s): a freed
        # object's address can be reused, which would fake "unchanged"
        synced = self._synced_objs
        if len(objs) == len(synced) and all(
            a is b for a, b in zip(objs, synced)
        ):
            if self._cached_node_body:
                # NodesCached mode: the extender expands the candidate
                # set from its own cache — the body names no nodes at
                # all (O(1) per webhook AND per wire hop)
                return {"NodesCached": True}, None
            # the cached names list rides with the synced set (never
            # mutated downstream: the schema layer copies) — the
            # names-only body costs O(1) per webhook, not O(nodes)
            return {"NodeNames": self._synced_names}, None
        return {"Nodes": {"Items": objs}}, objs

    def _commit_synced(self, objs: list[dict[str, Any]]) -> None:
        """Record the node set the extender has ingested error-free,
        caching the names-only body alongside (see
        ``_extender_node_args``)."""
        self._synced_objs = objs
        self._synced_names = [o["metadata"]["name"] for o in objs]

    def make_pod(
        self,
        name: str,
        tpu: int = 0,
        vtpu: int = 0,
        namespace: str = "default",
        priority: int = 0,
        group: Optional[PodGroup] = None,
        labels: Optional[dict[str, str]] = None,
    ) -> dict[str, Any]:
        requests: dict[str, str] = {}
        if tpu:
            requests[RESOURCE_TPU] = str(tpu)
        if vtpu:
            requests[RESOURCE_VTPU] = str(vtpu)
        annotations: dict[str, str] = {}
        if group is not None:
            annotations.update(codec.pod_group_annotations(group))
        pod = {
            "metadata": {
                "name": name,
                "namespace": namespace,
                "uid": f"uid-{namespace}-{name}",
                "annotations": annotations,
                # the tenancy label rides here (tpukube/tenancy)
                "labels": dict(labels or {}),
            },
            "spec": {
                "priority": priority,
                "containers": [
                    {"name": "main", "resources": {"requests": requests}}
                ],
            },
        }
        self.pods[f"{namespace}/{name}"] = pod
        return pod

    # -- the scheduler loop (what kube-scheduler would do) -------------------
    def _post(self, path: str, body: dict[str, Any]) -> Any:
        if self._in_process:
            # the same webhook dispatch (decision lock, trace record,
            # plan lookups) minus sockets and JSON transport — what the
            # kilonode scenarios and the no-HTTP microbench measure
            from tpukube.sched import kube

            try:
                return self.extender.handle(path.strip("/"), body)
            except kube.KubeSchemaError as e:
                raise RuntimeError(f"HTTP 400 from {path}: {e}")
        payload = json.dumps(body).encode()
        for attempt in (0, 1):  # one reconnect if the kept-alive conn died
            conn = getattr(self._tls, "conn", None)
            if conn is None:
                conn = self._tls.conn = http.client.HTTPConnection(
                    "127.0.0.1", self._port, timeout=10
                )
            try:
                # send and receive are separated: a failure to SEND means
                # the server never saw the request (stale keep-alive conn,
                # safe to retry); a failure AFTER send must not be retried
                # — the server may have executed the (non-idempotent) bind
                conn.request(
                    "POST", path, body=payload,
                    headers={"Content-Type": "application/json"},
                )
            except (http.client.HTTPException, OSError):
                conn.close()
                self._tls.conn = None
                if attempt:
                    raise
                continue
            try:
                resp = conn.getresponse()
                raw = resp.read()
            except (http.client.HTTPException, OSError):
                conn.close()
                self._tls.conn = None
                raise
            if resp.status >= 400:
                raise RuntimeError(
                    f"HTTP {resp.status} from {path}: "
                    f"{raw.decode(errors='replace')[:300]}"
                )
            return json.loads(raw)

    def drain_evictions(self) -> list[str]:
        """Delete pods the gang layer rolled back (all-or-nothing: a
        half-assembled gang's running members must not keep their chips).
        Thin wrapper over the same :class:`~tpukube.apiserver.
        EvictionExecutor` a real cluster runs, pointed at this harness's
        pod store instead of the REST channel. A process-mode router
        first pulls each worker daemon's local eviction queue onto the
        shared bus the executor drains."""
        pull = getattr(self.extender, "pull_evictions", None)
        if pull is not None:
            pull()
        return self._evictions.drain()

    def schedule(
        self, pod: dict[str, Any], retries: int = 8
    ) -> tuple[str, AllocResult]:
        """One scheduling cycle for one pod, with kube-scheduler's requeue
        semantics: a lost bind race (another pod took the chips between
        filter and bind) re-runs the whole cycle. Raises on failure.

        Evictions drain at the top of EVERY cycle, not just the first: a
        gang's first bind now executes its preemption plan and fails
        retryably until the victims are confirmed gone, so the retry path
        must run the executor (as the real daemon's eviction loop would
        concurrently) for the cycle to make progress."""
        last_err = ""
        for _ in range(retries):
            self.drain_evictions()
            node_args, pending_objs = self._extender_node_args()
            args = {"Pod": pod, **node_args}
            fres = self._post("/filter", args)
            if fres.get("Error"):
                raise RuntimeError(f"filter error: {fres['Error']}")
            if pending_objs is not None:
                # the extender ingested this node set error-free; later
                # cycles (any thread) may go names-only
                self._commit_synced(pending_objs)
            feasible_names = fres["NodeNames"]
            if not feasible_names:
                raise RuntimeError(f"unschedulable: {fres['FailedNodes']}")
            pres = self._post(
                "/prioritize", {"Pod": pod, "NodeNames": feasible_names}
            )
            scores = {e["Host"]: e["Score"] for e in pres}
            best = max(sorted(scores), key=lambda h: scores[h])
            meta = pod["metadata"]
            bres = self._post(
                "/bind",
                {
                    "PodName": meta["name"],
                    "PodNamespace": meta["namespace"],
                    "PodUID": meta["uid"],
                    "Node": best,
                },
            )
            if bres.get("Error"):
                last_err = bres["Error"]  # lost the race; requeue
                continue
            # apiserver role: persist alloc annotation + nodeName on the pod
            meta.setdefault("annotations", {}).update(bres.get("Annotations", {}))
            pod["spec"]["nodeName"] = best
            alloc = codec.decode_alloc(meta["annotations"][codec.ANNO_ALLOC])
            return best, alloc
        raise RuntimeError(f"bind error after {retries} cycles: {last_err}")

    def schedule_pending(
        self, pods: list[dict[str, Any]], retries: int = 4
    ) -> dict[str, tuple[str, AllocResult]]:
        """Batch-drive many pending pods through the scheduling-cycle
        planner (requires ``batch_enabled``): admit them all into the
        extender's queue, run planning cycles, then issue each pod's
        /bind against the planned node — the protocol's one mandatory
        per-pod step (the commitment + annotation write-back). The
        planner already computed every pod's filter/prioritize answer;
        pods whose plan failed (lost races, victims terminating) requeue
        for another round. Returns pod key -> (node, alloc); raises if
        any pod stays unschedulable after ``retries`` rounds."""
        from tpukube.sched import kube

        ext = self.extender
        if ext.cycle is None:
            raise RuntimeError("schedule_pending needs batch_enabled=true")
        self._sync_nodes()
        # the router's batched driver surface (admit_many /
        # planned_many / bind_many): one fanned-out call per replica
        # per round instead of one dispatch per pod — in process mode
        # a per-pod HTTP round-trip would hand the router tax the
        # whole multi-core win back. Absent (plain Extender), the
        # per-pod path below is the same protocol.
        admit_many = getattr(ext, "admit_many", None)
        planned_many = getattr(ext, "planned_many", None)
        bind_many = getattr(ext, "bind_many", None)
        results: dict[str, tuple[str, AllocResult]] = {}
        remaining = list(pods)
        for _ in range(retries):
            if not remaining:
                break
            self.drain_evictions()
            infos = [kube.pod_from_k8s(obj) for obj in remaining]
            if admit_many is not None:
                admit_many(infos)
            else:
                for info in infos:
                    ext.admit(info)
            ext.plan_pending()
            keys = [f"{o['metadata']['namespace']}/"
                    f"{o['metadata']['name']}" for o in remaining]
            if planned_many is not None:
                planned = planned_many(keys)
            else:
                planned = {k: ext.planned_node(k) for k in keys}
            still: list[dict[str, Any]] = []
            bind_objs: list[dict[str, Any]] = []
            bind_bodies: list[dict[str, Any]] = []
            for obj, key in zip(remaining, keys):
                meta = obj["metadata"]
                node = planned.get(key)
                if node is None:
                    still.append(obj)
                    continue
                body = {
                    "PodName": meta["name"],
                    "PodNamespace": meta["namespace"],
                    "PodUID": meta["uid"],
                    "Node": node,
                }
                if bind_many is not None:
                    bind_objs.append(obj)
                    bind_bodies.append(body)
                    continue
                bres = self._post("/bind", body)
                if bres.get("Error"):
                    still.append(obj)
                    continue
                self._apply_bind(obj, node, bres, results)
            if bind_bodies:
                for obj, body, bres in zip(bind_objs, bind_bodies,
                                           bind_many(bind_bodies)):
                    if bres.get("Error"):
                        still.append(obj)
                        continue
                    self._apply_bind(obj, body["Node"], bres, results)
            remaining = still
        if remaining:
            names = [o["metadata"]["name"] for o in remaining[:3]]
            raise RuntimeError(
                f"{len(remaining)} pod(s) unschedulable after {retries} "
                f"batch rounds (first: {names})"
            )
        return results

    def _apply_bind(self, obj: dict[str, Any], node: str,
                    bres: dict[str, Any],
                    results: dict[str, tuple[str, AllocResult]]) -> None:
        """The apiserver role for one successful bind answer: persist
        the alloc annotation + nodeName on the pod object and record
        the result (shared by the per-pod and batched bind paths)."""
        meta = obj["metadata"]
        meta.setdefault("annotations", {}).update(
            bres.get("Annotations", {})
        )
        obj["spec"]["nodeName"] = node
        key = f"{meta['namespace']}/{meta['name']}"
        results[key] = (node, codec.decode_alloc(
            meta["annotations"][codec.ANNO_ALLOC]
        ))

    def _sync_nodes(self) -> None:
        """Push node annotations through the recorded ``upsert_node``
        decision (the nodeCacheCapable out-of-band refresh): the batch
        driver skips /filter webhooks, which are how node topology
        normally reaches the extender. Identity-cached like
        _extender_node_args — unchanged node sets cost nothing. A
        sharded router ingests the whole fleet through its batched
        ``upsert_nodes_many`` (one fan-out instead of one dispatch —
        in process mode one HTTP round-trip — per node)."""
        objs = self.node_objects()
        synced = self._synced_objs
        if len(objs) == len(synced) and all(
            a is b for a, b in zip(objs, synced)
        ):
            return
        items = [{
            "name": obj["metadata"]["name"],
            "annotations": obj["metadata"]["annotations"],
        } for obj in objs]
        batched = getattr(self.extender, "upsert_nodes_many", None)
        if batched is not None:
            answers = batched(items)
        else:
            answers = [self.extender.handle("upsert_node", item)
                       for item in items]
        for item, res in zip(items, answers):
            if isinstance(res, dict) and res.get("error"):
                raise RuntimeError(
                    f"node sync failed for {item['name']}: "
                    f"{res['error']}"
                )
        self._commit_synced(objs)

    def delete_pod(self, name: str, namespace: str = "default") -> None:
        """Remove the pod object, then let the lifecycle release loop
        observe the absence — the path a real cluster takes (DELETED
        event → recorded release decision), not a manual release call."""
        self.pods.pop(f"{namespace}/{name}", None)
        self._lifecycle.check_once()

    def complete_pod(self, name: str, namespace: str = "default",
                     phase: str = "Succeeded") -> None:
        """Mark a pod's containers finished (terminal phase). The object
        lingers — exactly how a completed Job pod looks on a real cluster
        — and the lifecycle loop frees its chips from the phase alone."""
        pod = self.pods.get(f"{namespace}/{name}")
        if pod is None:
            raise KeyError(f"no pod {namespace}/{name}")
        pod.setdefault("status", {})["phase"] = phase
        self._lifecycle.check_once()

    # -- fault injection (SURVEY.md §6) -------------------------------------
    def inject_fault(self, node_name: str, chip_index: int,
                     healthy: bool = False) -> None:
        """Flip a chip's health in the node data — the node agent's health
        watcher would do exactly this re-annotation on a real cluster."""
        info = self.nodes[node_name]
        for chip in info.chips:
            if chip.index == chip_index:
                chip.health = Health.HEALTHY if healthy else Health.UNHEALTHY
                self._invalidate_node(node_name)
                return
        raise KeyError(f"{node_name} has no chip {chip_index}")

    def inject_link_fault(
        self, a, b, up: bool = False, slice_id: Optional[str] = None
    ) -> None:
        """Drop (or restore) the ICI link between adjacent coords ``a``/``b``
        — each endpoint's owning node agent reports its side, exactly as the
        real health watch would re-annotate (SURVEY.md §6). ``slice_id``
        names the ICI domain on multi-slice clusters."""
        if slice_id is None:
            if len(self.slices) != 1:
                raise ValueError("multi-slice cluster: pass slice_id")
            slice_id = next(iter(self.slices))
        mesh = self.slices[slice_id]
        link = canonical_link(a, b)
        ca, cb = link
        if cb not in mesh.neighbors(ca):
            raise ValueError(f"{ca} and {cb} are not ICI-adjacent")
        for coord in link:
            host = mesh.host_of(coord)
            name = f"{slice_id}-{host}" if self._prefixed else host
            info = self.nodes[name]
            if up:
                if link in info.bad_links:
                    info.bad_links.remove(link)
            elif link not in info.bad_links:
                info.bad_links.append(link)
            self._invalidate_node(name)

    # -- node-agent composition check (config 2's fan-out leg) ---------------
    def execute_allocation(self, alloc: AllocResult,
                           restart_agent: bool = False) -> dict[str, str]:
        """Run the bound pod's Allocate through a REAL device-plugin stack
        (gRPC over unix sockets) for the target node, returning the env the
        container would receive. Sessions are sequential because libtpuinfo
        is single-instance per process.

        ``restart_agent=True`` tears the plugin server down and cold-starts
        it between registration and Allocate (socket unlinked + rebound +
        re-registered) — the node-agent half of the chaos crash story: a
        restarted agent must still serve the extender's planned intent."""
        import tempfile

        from tpukube.core.config import load_config as _load
        from tpukube.device import TpuDeviceManager
        from tpukube.plugin import DevicePluginServer, FakeKubelet

        info = self.nodes[alloc.node_name]
        mesh = self.slices[info.slice_id]
        origin = min(c.coord for c in info.chips)
        with tempfile.TemporaryDirectory() as td:
            env_overrides = {
                "TPUKUBE_DEVICE_PLUGIN_DIR": td,
                "TPUKUBE_SIM_MESH_DIMS": ",".join(str(d) for d in mesh.dims),
                "TPUKUBE_SIM_HOST_BLOCK": ",".join(
                    str(d) for d in mesh.host_block
                ),
                "TPUKUBE_SIM_TORUS": ",".join(
                    str(t).lower() for t in mesh.torus
                ),
                "TPUKUBE_SIM_HOST_ORIGIN": ",".join(str(v) for v in origin),
                "TPUKUBE_SLICE_ID": info.slice_id,
                "TPUKUBE_HBM_BYTES_PER_CHIP": str(self.config.hbm_bytes_per_chip),
                "TPUKUBE_SHARES_PER_CHIP": str(info.shares_per_chip),
            }
            cfg = _load(env=env_overrides)
            with FakeKubelet(td) as kubelet, \
                 TpuDeviceManager(cfg, host=alloc.node_name) as device, \
                 DevicePluginServer(cfg, device) as server:
                # the node-agent leg of the per-pod timeline: feed the
                # planned intent (the intent watcher's job on a real
                # node) and record allocate/intent-match spans into the
                # extender's decision trace
                if self.extender.trace is not None:
                    server.span_sink = self.extender.trace.span
                server.intents.put(alloc.pod_key, list(alloc.device_ids))
                server.register_with_kubelet()
                kubelet.wait_for_devices(
                    server.resource_name, len(device.device_list())
                )
                if restart_agent:
                    # cold restart mid-session: socket torn down and
                    # rebound, registration redone, intent re-fed (a
                    # restarted agent's intent watcher re-syncs from
                    # the pod's alloc annotation exactly like this)
                    server.restart()
                    server.intents.put(alloc.pod_key,
                                       list(alloc.device_ids))
                    server.register_with_kubelet()
                    kubelet.wait_for_devices(
                        server.resource_name, len(device.device_list())
                    )
                return kubelet.allocate(server.resource_name, alloc.device_ids)

    # -- fleet elasticity (ISSUE 19) -----------------------------------------
    def add_slice(self, slice_id: str, mesh: MeshSpec) -> list[dict[str, Any]]:
        """Mint the nodes of a NEW slice into the harness's world
        (node names are always "<slice>-<host>" prefixed so they stay
        unique cluster-wide) and return their ``upsert_nodes`` items —
        the autoscaler's provisioner feeds these straight to the
        extender; a webhook-driven cluster picks them up on the next
        full node send. The extender learns nothing here."""
        if slice_id in self.slices:
            raise ValueError(f"slice {slice_id!r} already exists")
        self.slices[slice_id] = mesh
        self._prefixed = True
        self.mesh = (next(iter(self.slices.values()))
                     if len(self.slices) == 1 else None)
        items: list[dict[str, Any]] = []
        for host in mesh.all_hosts():
            name = f"{slice_id}-{host}"
            if name in self.nodes:
                raise ValueError(f"node {name!r} already exists")
            chips = [
                ChipInfo(
                    chip_id=f"{name}-chip-{i}",
                    index=i,
                    coord=coord,
                    hbm_bytes=self.config.hbm_bytes_per_chip,
                    num_cores=self.config.cores_per_chip,
                )
                for i, coord in enumerate(mesh.coords_of_host(host))
            ]
            info = NodeInfo(name=name, chips=chips, shares_per_chip=1,
                            slice_id=slice_id)
            self.nodes[name] = info
            items.append({
                "name": name,
                "annotations": codec.annotate_node(info, mesh),
            })
        self._node_objs_list = None
        return items

    def forget_nodes(self, names) -> list[str]:
        """Drop nodes from the harness's world AFTER a drain
        un-ingested them from the extender — the node objects stop
        riding webhook sends, so the next full sync cannot silently
        re-register decommissioned capacity. Slices left empty are
        forgotten too. Returns the names actually dropped."""
        dropped: list[str] = []
        touched: set[str] = set()
        for name in names:
            info = self.nodes.pop(name, None)
            if info is None:
                continue
            dropped.append(name)
            touched.add(info.slice_id)
            self._node_obj_cache.pop(name, None)
        for sid in touched:
            if not any(i.slice_id == sid for i in self.nodes.values()):
                self.slices.pop(sid, None)
        if dropped:
            self._node_objs_list = None
            self.mesh = (next(iter(self.slices.values()))
                         if len(self.slices) == 1 else None)
        return dropped

    def remove_slice(self, slice_id: str) -> list[str]:
        """``forget_nodes`` for one whole slice (the scale-down /
        maintenance bookkeeping after its drain completes)."""
        return self.forget_nodes([
            n for n, i in self.nodes.items() if i.slice_id == slice_id
        ])

    def make_slice_provisioner(self, mesh: MeshSpec, prefix: str = "as"):
        """An :class:`~tpukube.sched.autoscale.Autoscaler` provisioner
        closure: each call mints one fresh slice of ``mesh`` geometry
        (ids "<prefix>1", "<prefix>2", ...) and returns its upsert
        items — the sim stand-in for a cloud instance API."""
        import itertools

        counter = itertools.count(1)

        def provision() -> list[dict[str, Any]]:
            sid = f"{prefix}{next(counter)}"
            while sid in self.slices:
                sid = f"{prefix}{next(counter)}"
            return self.add_slice(sid, mesh)

        return provision

    # -- metrics ------------------------------------------------------------
    def utilization(self) -> float:
        return self.extender.state.utilization()

"""Multi-tenant serving plane (ISSUE 9): per-tenant quotas, DRF
fairness, and SLO-aware admission over fractional vTPUs."""

from tpukube.tenancy.core import (
    BurnMonitor,
    TenantLedger,
    TenantPlane,
    TenantQuota,
    TenantUsage,
    parse_quotas,
)

__all__ = [
    "BurnMonitor",
    "TenantLedger",
    "TenantPlane",
    "TenantQuota",
    "TenantUsage",
    "parse_quotas",
]

"""The multi-tenant serving plane (ISSUE 9) — tenancy policy over the
mechanisms the tree already has.

BASELINE configs 3 and 5 (HBM-quota vTPU sharing; 70B train + burst
infer) are the "millions of users" story, and the fractional layer
(``device/tpu.py``, ``native/hbmguard.cpp``), the preemption planner,
and the burn-rate math (``obs/slo.py``) all exist — what was missing is
the TRAFFIC side: who may take how much, in what order, and what gets
shed when the control plane's SLOs burn. This module is that policy
layer, three pieces:

  * **Tenant model + ledger** — the tenant id comes from a pod label
    (``tenancy_label``, default ``tpu.qiniu.com/tenant``; unlabeled
    pods belong to ``tenancy_default_tenant``). :class:`TenantLedger`
    derives per-tenant, per-ICI-slice usage (whole-chip equivalents and
    HBM bytes) as a PURE FUNCTION of the cluster ledger plus live gang
    reservations, cached on the same (ledger epoch, gang epoch) key the
    scheduling snapshot uses — so tenant accounting can never diverge
    from the placement truth (there is no second bookkeeping to leak).
    Bound pods carry their tenant in the alloc annotation's env
    (``TPU_KUBE_TENANT``), so attribution survives an extender restart
    exactly like the allocations themselves.
  * **DRF fairness** — a tenant's *dominant share* is the classic DRF
    quantity: max(chips used / cluster chips, HBM used / cluster HBM).
    :meth:`TenantPlane.drf_order` orders the batched scheduling queue
    (sched/cycle.py) progressively: within a priority band, the next
    unit (a whole gang, or one stray pod) always comes from the tenant
    with the lowest virtual dominant share, the virtual share charged
    as units are picked — so a thousand-pod burst from one tenant
    interleaves with everyone else's instead of draining first. The
    preemption planner gets the mirror-image signal: victims from
    tenants furthest OVER their share are preferred at equal priority
    cost (``policy.find_preemption_plan``'s ``overshare`` bias).
  * **SLO-aware admission** — :class:`BurnMonitor` evaluates the
    DEFAULT_SLOS burn rates (obs/slo.py math, the same objectives the
    Prometheus rules encode) directly over the extender's own
    gang-commit and webhook histograms, on a sliding window of the
    scheduling clock. While any SLO burns at the page threshold,
    low-priority non-gang admissions from tenants above the burst
    population's mean share are SHED — refused with a typed journal
    event (``TenantAdmissionShed``), never silently dropped; the
    scheduler's requeue makes refusal a deferral. Per-tenant quota
    breaches are refused the same way (``TenantQuotaDenied``).

Everything here is constructed only when ``tenancy_enabled`` is on;
with the default OFF config the extender holds ``tenants = None``, no
tenant series render, and every placement path is byte-identical to
the pre-tenancy behavior (the parity suite in tests/test_tenancy.py
additionally proves that a NEUTRAL plane — one tenant, no quotas, no
burn — changes no placement either).

Locking: the plane owns one leaf lock for its counters and the burn
monitor's window state; usage snapshots build OUTSIDE it by reading
the gang and ledger locks (decision -> gang -> ledger order, same as
the scheduling snapshot). Callers are the webhook paths (under the
decision lock) and the metrics/statusz renderers (lock-free reads of
the epoch-cached snapshot).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from tpukube.core.types import (
    RESOURCE_TPU,
    RESOURCE_VTPU,
    PodInfo,
    parse_device_id,
)
from tpukube.device.tpu import ENV_KUBE_TENANT
from tpukube.obs import slo as slo_mod
from tpukube.obs.registry import Histogram

log = logging.getLogger("tpukube.tenancy")

#: margin over the burst population's mean share before a tenant
#: counts as over-share for SLO shedding — strictly-above-the-mean
#: would shed at fair equilibrium on float noise
OVER_SHARE_MARGIN = 1.05


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant caps. ``chips`` bounds whole-chip equivalents
    (vTPU shares count fractionally); ``hbm_fraction`` bounds the
    tenant's slice of total cluster HBM. None = uncapped."""

    chips: Optional[float] = None
    hbm_fraction: Optional[float] = None


def parse_quotas(spec: str) -> dict[str, TenantQuota]:
    """Parse the ``tenancy_quotas`` config string:
    ``"teamA=chips:16,hbm:0.25;teamB=chips:8"`` — ``;`` separates
    tenants, ``,`` separates caps, ``chips`` is a positive number of
    whole-chip equivalents, ``hbm`` a fraction of cluster HBM in
    (0, 1]. Raises ValueError with the offending fragment."""
    out: dict[str, TenantQuota] = {}
    if not spec.strip():
        return out
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, caps_raw = entry.partition("=")
        name = name.strip()
        if not sep or not name or not caps_raw.strip():
            raise ValueError(
                f"tenancy_quotas entry {entry!r}: want "
                f"'<tenant>=chips:<n>[,hbm:<frac>]'"
            )
        if name in out:
            raise ValueError(f"tenancy_quotas: duplicate tenant {name!r}")
        chips: Optional[float] = None
        hbm: Optional[float] = None
        for cap in caps_raw.split(","):
            key, sep, val = cap.strip().partition(":")
            key = key.strip()
            try:
                num = float(val)
            except ValueError:
                num = float("nan")
            if not sep or num != num:
                raise ValueError(
                    f"tenancy_quotas cap {cap!r} for {name!r}: want "
                    f"'chips:<n>' or 'hbm:<frac>'"
                )
            if key == "chips":
                if num <= 0:
                    raise ValueError(
                        f"tenancy_quotas: {name!r} chips cap must be > 0"
                    )
                chips = num
            elif key == "hbm":
                if not 0 < num <= 1:
                    raise ValueError(
                        f"tenancy_quotas: {name!r} hbm cap must be in "
                        f"(0, 1]"
                    )
                hbm = num
            else:
                raise ValueError(
                    f"tenancy_quotas cap key {key!r} for {name!r}: "
                    f"known caps are 'chips' and 'hbm'"
                )
        out[name] = TenantQuota(chips=chips, hbm_fraction=hbm)
    return out


@dataclass
class TenantUsage:
    """One tenant's live consumption."""

    chips: float = 0.0       # whole-chip equivalents (vTPU shares 1/n)
    hbm_bytes: float = 0.0
    pods: int = 0
    #: chips held by shed-ELIGIBLE work (non-gang, priority at or below
    #: the shed cutoff) — the population SLO shedding reasons about
    burst_chips: float = 0.0
    #: chips per ICI slice (gang reservation chips included)
    by_slice: dict[str, float] = field(default_factory=dict)


class _UsageSnapshot:
    """Per-tenant usage plus cluster capacity, frozen at an epoch key."""

    __slots__ = ("key", "usage", "capacity_chips", "capacity_hbm",
                 "vtpu_shares")

    def __init__(self, key, usage: dict[str, TenantUsage],
                 capacity_chips: int, capacity_hbm: int,
                 vtpu_shares: int):
        self.key = key
        self.usage = usage
        self.capacity_chips = capacity_chips
        self.capacity_hbm = capacity_hbm
        #: largest shares_per_chip advertised by any node (1 = no vTPU
        #: nodes) — the pre-bind chip-equivalent estimate for vTPU asks
        self.vtpu_shares = vtpu_shares

    def dominant_share(self, tenant: str) -> float:
        u = self.usage.get(tenant)
        if u is None:
            return 0.0
        chips = u.chips / self.capacity_chips if self.capacity_chips else 0.0
        hbm = u.hbm_bytes / self.capacity_hbm if self.capacity_hbm else 0.0
        return max(chips, hbm)

    def burst_share(self, tenant: str) -> float:
        u = self.usage.get(tenant)
        if u is None or not self.capacity_chips:
            return 0.0
        return u.burst_chips / self.capacity_chips

    def mean_burst_share(self) -> float:
        """Mean burst share over tenants that HAVE burst usage — the
        over-share reference for SLO shedding (a tenant above it is
        consuming more of the contended burst plane than its peers)."""
        shares = [self.burst_share(t) for t, u in self.usage.items()
                  if u.burst_chips > 0]
        return sum(shares) / len(shares) if shares else 0.0


class TenantLedger:
    """Per-tenant usage derived from the cluster ledger + live gang
    reservations, epoch-cached. There is deliberately NO incremental
    bookkeeping: usage is recomputed (at most once per epoch pair)
    from the same state every placement decision reads, so tenant
    accounting cannot drift from placement truth."""

    def __init__(self, state, gang, default_tenant: str,
                 shed_priority_max: int = 0) -> None:
        self._state = state
        self._gang = gang
        self._default = default_tenant
        self._shed_priority_max = shed_priority_max
        self._lock = threading.Lock()  # leaf: guards only the cache slot
        self._snap: Optional[_UsageSnapshot] = None

    def tenant_of_alloc(self, alloc) -> str:
        return alloc.env.get(ENV_KUBE_TENANT) or self._default

    def usage(self) -> _UsageSnapshot:
        key = (self._state.epoch(), self._gang.epoch())
        with self._lock:
            snap = self._snap
        if snap is not None and snap.key == key:
            return snap
        snap = self._build(key)
        if (self._state.epoch(), self._gang.epoch()) == key:
            with self._lock:
                self._snap = snap
        return snap  # raced a mutation: serve this one uncached

    def _build(self, key) -> _UsageSnapshot:
        state, gang = self._state, self._gang
        usage: dict[str, TenantUsage] = {}
        cap_chips = 0
        cap_hbm = 0
        vtpu_shares = 1
        views = {}
        for name in state.node_names():
            view = state.node(name)
            if view is None:
                continue
            views[name] = view
            vtpu_shares = max(vtpu_shares, view.shares_per_chip)
            for chip in view.info.chips:
                if chip.health.value == "Healthy":
                    cap_chips += 1
                    cap_hbm += chip.hbm_bytes

        def entry(tenant: str) -> TenantUsage:
            u = usage.get(tenant)
            if u is None:
                u = usage[tenant] = TenantUsage()
            return u

        gang_pods: set[str] = set()
        for res in gang.snapshot():
            gang_pods.update(res.assigned)
            tenant = res.tenant or self._default
            u = entry(tenant)
            for sid, coords in res.slice_coords.items():
                unassigned = res.unassigned_in(sid)
                if not unassigned:
                    continue
                hosts = state.hosts_by_coord(sid)
                for c in unassigned:
                    host = hosts.get(c)
                    view = views.get(host) if host is not None else None
                    u.chips += 1.0
                    u.by_slice[sid] = u.by_slice.get(sid, 0.0) + 1.0
                    if view is not None:
                        try:
                            u.hbm_bytes += view.chip(
                                view.index_at(c)).hbm_bytes
                        except Exception:
                            log.debug("no chip at %s in %s for hbm "
                                      "attribution", c, sid)
        for alloc in state.allocations():
            tenant = self.tenant_of_alloc(alloc)
            u = entry(tenant)
            u.pods += 1
            view = views.get(alloc.node_name)
            sid = (view.info.slice_id if view is not None
                   else state.slice_of_node(alloc.node_name) or "?")
            chips = 0.0
            hbm = 0.0
            for did in alloc.device_ids:
                try:
                    index, frac = parse_device_id(did)
                except ValueError:
                    continue
                chip_hbm = 0
                if view is not None:
                    try:
                        chip_hbm = view.chip(index).hbm_bytes
                    except Exception:
                        log.debug("chip %s gone from %s mid-build",
                                  index, alloc.node_name)
                if frac is not None:
                    _, n = frac
                    chips += 1.0 / n
                    hbm += chip_hbm / n
                else:
                    chips += 1.0
                    hbm += chip_hbm
            u.chips += chips
            u.hbm_bytes += hbm
            u.by_slice[sid] = u.by_slice.get(sid, 0.0) + chips
            if (alloc.pod_key not in gang_pods
                    and alloc.priority <= self._shed_priority_max):
                u.burst_chips += chips
        return _UsageSnapshot(key, usage, cap_chips, cap_hbm, vtpu_shares)


def _hist_totals_by_tenant(hist, threshold_le: str) -> dict[
        str, tuple[float, float]]:
    """tenant -> (good, total) over one histogram's rendered
    ``_bucket`` samples, keyed by the ``tenant`` label — the
    per-tenant twin of :func:`_hist_totals` (ISSUE 12 tenancy v2:
    the BurnMonitor slides one window pair per tenant over these)."""
    out: dict[str, list[float]] = {}
    for name, labels, value in hist.samples():
        if not name.endswith("_bucket"):
            continue
        labels = labels or {}
        tenant = labels.get("tenant")
        if tenant is None:
            continue
        le = labels.get("le")
        acc = out.setdefault(tenant, [0.0, 0.0])
        if le == threshold_le:
            acc[0] += value
        elif le == "+Inf":
            acc[1] += value
    return {t: (g, tot) for t, (g, tot) in out.items()}


def _hist_totals(hist, threshold_le: str,
                 match: dict[str, str]) -> tuple[float, float]:
    """(good, total) over one histogram's rendered ``_bucket`` samples,
    restricted to the children matching ``match`` — the in-process twin
    of ``obs.slo.histogram_totals`` (same bucket-counter semantics,
    read off the live Histogram instead of a scrape)."""
    good = total = 0.0
    for name, labels, value in hist.samples():
        if not name.endswith("_bucket"):
            continue
        labels = labels or {}
        if any(labels.get(k) != v for k, v in match.items()):
            continue
        le = labels.get("le")
        if le == threshold_le:
            good += value
        elif le == "+Inf":
            total += value
    return good, total


class _BurnSource:
    __slots__ = ("name", "hist", "threshold_le", "objective", "match")

    def __init__(self, name, hist, threshold_le, objective, match):
        self.name = name
        self.hist = hist
        self.threshold_le = threshold_le
        self.objective = objective
        self.match = dict(match or {})


class BurnMonitor:
    """Sliding-window SLO burn over live histograms.

    Two baselines A (older) and B (newer) slide forward: burn is the
    obs/slo burn-rate of the delta since A, and whenever B is a full
    window old, A <- B and B <- now — so the evaluated window always
    spans between one and two ``window`` lengths of the SCHEDULING
    clock (the fake clock in sims, so burn windows compress with the
    rest of simulated time), PROVIDED evaluations keep arriving.
    Evaluations only happen on shed-eligible admissions, so after an
    idle gap longer than two windows both baselines are stale; rather
    than conflate hours of quiet (and any sample inside them) into one
    giant pseudo-window — shedding morning traffic for last night's
    slow commit — a gap that long RESETS the baselines to the current
    totals and reports no burn for that evaluation (a burn that is
    genuinely still happening re-crosses the threshold within one
    window of resumed traffic). ``threshold`` is the page burn from
    the multiwindow policy; 0 disables the monitor entirely."""

    def __init__(self, clock, threshold: float = 14.4,
                 window: float = 60.0) -> None:
        self._clock = clock
        self.threshold = threshold
        self.window = window
        self._sources: list[_BurnSource] = []
        # per-tenant sources (ISSUE 12 tenancy v2): each evaluates one
        # (good, total) window pair PER tenant-labeled child, sliding
        # on the same A/B baselines and clock as the global sources —
        # so a shed can cite the refused tenant's own burn, not just
        # the plane-global one
        self._tenant_sources: list[_BurnSource] = []
        self._lock = threading.Lock()
        # name -> (good, total) at the older (A) and newer (B)
        # baselines; only B's timestamp drives the sliding
        self._a: dict[str, tuple[float, float]] = {}
        self._b: dict[str, tuple[float, float]] = {}
        # (name, tenant) -> (good, total): the per-tenant baselines
        self._ta: dict[tuple[str, str], tuple[float, float]] = {}
        self._tb: dict[tuple[str, str], tuple[float, float]] = {}
        self._b_t = clock.monotonic()
        self.last_burns: dict[str, Optional[float]] = {}
        # tenant -> {slo name -> last windowed burn} (read-only views:
        # tenant_burn(), the tpukube_tenant_slo_burn gauge, /statusz)
        self.last_tenant_burns: dict[str, dict[str, Optional[float]]] = {}
        # one verdict per clock instant: kilonode-scale sims run whole
        # drains at a single fake-clock tick, and every admission in a
        # drain must see one consistent verdict without re-scanning
        # the histograms per pod
        self._verdict_t: Optional[float] = None
        self._verdict: Optional[str] = None

    def attach(self, name: str, hist, threshold_le: str,
               objective: float, match=None) -> None:
        self._sources.append(
            _BurnSource(name, hist, threshold_le, objective, match)
        )

    def attach_tenant(self, name: str, hist, threshold_le: str,
                      objective: float) -> None:
        """Attach a tenant-labeled histogram as a PER-TENANT burn
        source: every tenant child gets its own sliding window pair
        and its own burn in ``last_tenant_burns``."""
        self._tenant_sources.append(
            _BurnSource(name, hist, threshold_le, objective, None)
        )

    def attach_default_slos(self, hists: dict[str, Any]) -> None:
        """Wire the DEFAULT_SLOS (obs/slo.py) against the live
        histograms that back them — the same objectives and bucket
        thresholds the Prometheus rules alert on."""
        for spec in slo_mod.DEFAULT_SLOS:
            hist = hists.get(spec.family)
            if hist is not None:
                self.attach(spec.name, hist, spec.threshold_le,
                            spec.objective, match=dict(spec.labels))

    def evaluate(self) -> dict[str, Optional[float]]:
        """Current burn per source over the sliding window; slides the
        baselines (global AND per-tenant — one clock, one window pair
        policy) as a side effect."""
        now = self._clock.monotonic()
        totals = {
            s.name: _hist_totals(s.hist, s.threshold_le, s.match)
            for s in self._sources
        }
        tenant_totals: dict[tuple[str, str], tuple[float, float]] = {}
        for s in self._tenant_sources:
            for tenant, gt in _hist_totals_by_tenant(
                    s.hist, s.threshold_le).items():
                tenant_totals[(s.name, tenant)] = gt
        with self._lock:
            if now - self._b_t >= 2 * self.window:
                # idle gap past the window contract: reset instead of
                # judging a giant stale pseudo-window (see class doc)
                self._a = totals
                self._b, self._b_t = totals, now
                self._ta = tenant_totals
                self._tb = dict(tenant_totals)
                self.last_burns = {s.name: None for s in self._sources}
                self.last_tenant_burns = {}
                return dict(self.last_burns)
            burns: dict[str, Optional[float]] = {}
            for s in self._sources:
                good, total = totals[s.name]
                bg, bt = self._a.get(s.name, (0.0, 0.0))
                burns[s.name] = slo_mod.burn_rate(
                    good - bg, total - bt, s.objective
                )
            objectives = {s.name: s.objective
                          for s in self._tenant_sources}
            tburns: dict[str, dict[str, Optional[float]]] = {}
            for (name, tenant), (good, total) in tenant_totals.items():
                bg, bt = self._ta.get((name, tenant), (0.0, 0.0))
                tburns.setdefault(tenant, {})[name] = slo_mod.burn_rate(
                    good - bg, total - bt, objectives[name]
                )
            if now - self._b_t >= self.window:
                self._a = self._b
                self._b, self._b_t = totals, now
                self._ta = self._tb
                self._tb = dict(tenant_totals)
            self.last_burns = burns
            self.last_tenant_burns = tburns
            return burns

    def page_burning(self) -> Optional[str]:
        """A human reason while any source burns at or above the page
        threshold, else None. Memoized per clock instant — a batch
        drain's admissions all land on one fake-clock tick and must
        not re-scan the histograms per pod."""
        if self.threshold <= 0 or not self._sources:
            return None
        now = self._clock.monotonic()
        with self._lock:
            if self._verdict_t == now:
                return self._verdict
        worst_name, worst = None, None
        for name, burn in self.evaluate().items():
            if burn is not None and (worst is None or burn > worst):
                worst_name, worst = name, burn
        verdict = None
        if worst is not None and worst >= self.threshold:
            verdict = (f"{worst_name} burning at {worst:.1f}x "
                       f"(page threshold {self.threshold:g}x)")
        with self._lock:
            self._verdict_t, self._verdict = now, verdict
        return verdict

    def tenant_burn(self, tenant: str) -> Optional[float]:
        """The tenant's WORST last-evaluated burn across the per-tenant
        sources (None = no traffic / no per-tenant source). Read-only —
        the admission path's page_burning() evaluation already slid the
        windows this reads."""
        with self._lock:
            burns = self.last_tenant_burns.get(tenant)
            if not burns:
                return None
            vals = [b for b in burns.values() if b is not None]
            return max(vals) if vals else None

    def last_tenant_burn(self, tenant: str, slo: str) -> float:
        """One (tenant, slo) cell of the last evaluation, 0.0 when
        unknown — the tpukube_tenant_slo_burn gauge's pull callback."""
        with self._lock:
            return (self.last_tenant_burns.get(tenant) or {}).get(
                slo) or 0.0

    def last_page_burning(self) -> bool:
        """Read-only view of the LAST evaluation — the metrics/statusz
        renderers must never slide the admission windows themselves."""
        if self.threshold <= 0:
            return False
        with self._lock:
            return any(b is not None and b >= self.threshold
                       for b in self.last_burns.values())

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "threshold": self.threshold,
                "window_seconds": self.window,
                "sources": [s.name for s in self._sources],
                "tenant_sources": [s.name
                                   for s in self._tenant_sources],
                "last_burns": dict(self.last_burns),
                "last_tenant_burns": {
                    t: dict(b)
                    for t, b in self.last_tenant_burns.items()
                },
            }


class TenantPlane:
    """The tenancy policy facade the Extender owns when
    ``tenancy_enabled`` is on (None otherwise — nothing below runs)."""

    def __init__(self, config, state, gang, events=None,
                 clock=None) -> None:
        from tpukube.core.clock import SYSTEM

        self.label = config.tenancy_label
        self.default = config.tenancy_default_tenant
        self.quotas = parse_quotas(config.tenancy_quotas)
        self.shed_priority_max = config.tenancy_shed_priority_max
        self.ledger = TenantLedger(
            state, gang, default_tenant=self.default,
            shed_priority_max=self.shed_priority_max,
        )
        self._gang = gang
        self._events = events
        self.burn = BurnMonitor(
            clock if clock is not None else SYSTEM,
            threshold=config.tenancy_burn_threshold,
            window=config.tenancy_burn_window_seconds,
        )
        # per-tenant latency histograms (ISSUE 12 tenancy v2): the
        # extender observes each filter decision's wall into
        # admission_hist{tenant} and each successful bind's into
        # commit_hist{tenant}; both render whenever tenancy is on
        # (tpukube_tenant_admission_seconds / _commit_seconds), and
        # the admission one doubles as the per-tenant burn source —
        # so a shed can cite the refused tenant's OWN burn
        self.admission_hist = Histogram(
            "tpukube_tenant_admission_seconds",
            help_text="Admission (filter) decision wall per tenant; "
                      "the per-tenant SLO-burn source.")
        self.commit_hist = Histogram(
            "tpukube_tenant_commit_seconds",
            help_text="Successful bind decision wall per tenant.")
        self.burn.attach_tenant(
            "tenant-admission-latency", self.admission_hist,
            threshold_le="0.25", objective=0.999,
        )
        # decision-provenance hook (obs/decisions.py): the Extender
        # wires its DecisionLog here so every refusal's verdict —
        # shares and tenant-local burn at decision time — lands in the
        # refused pod's provenance chain. None = no recording.
        self.decisions = None
        self._lock = threading.Lock()  # leaf: counters only
        self.sheds: dict[str, int] = {}
        self.quota_denials: dict[str, int] = {}

    # -- identity ------------------------------------------------------------
    def tenant_of(self, pod: PodInfo) -> str:
        return pod.labels.get(self.label) or self.default

    def tenant_of_alloc(self, alloc) -> str:
        return self.ledger.tenant_of_alloc(alloc)

    # -- per-tenant latency (the burn monitor's windows slide on these) ------
    def observe_admission(self, tenant: str, seconds: float) -> None:
        self.admission_hist.labels(tenant=tenant).observe(seconds)

    def observe_commit(self, tenant: str, seconds: float) -> None:
        self.commit_hist.labels(tenant=tenant).observe(seconds)

    def known_tenants(self) -> list[str]:
        with self._lock:
            counted = set(self.sheds) | set(self.quota_denials)
        return sorted(
            set(self.quotas) | set(self.ledger.usage().usage) | counted
        )

    # -- request sizing ------------------------------------------------------
    def request_chips(self, pod: PodInfo) -> float:
        """Whole-chip-equivalent estimate of a pod's ask: exact for
        whole-chip requests; vTPU shares charged at 1/n of the largest
        advertised share count pre-bind (the post-bind ledger then
        carries the node's exact fraction)."""
        req = pod.requests()
        tpu = req.get(RESOURCE_TPU, 0)
        if tpu:
            return float(tpu)
        vtpu = req.get(RESOURCE_VTPU, 0)
        if vtpu:
            return vtpu / max(1, self.ledger.usage().vtpu_shares)
        return 0.0

    # -- admission -----------------------------------------------------------
    def admit(self, pod: PodInfo, resource: str,
              count: int) -> Optional[str]:
        """None to admit; a human reason to refuse (the caller turns it
        into the webhook's error answer — the scheduler's requeue makes
        refusal a deferral). Every refusal lands in the journal as a
        typed event; nothing is ever silently dropped."""
        tenant = self.tenant_of(pod)
        snap = self.ledger.usage()
        overflow = False
        if pod.group is not None:
            res = self._gang.reservation(pod.namespace, pod.group.name)
            if res is not None and self._gang.assignable(res, count):
                # the gang's chips are already held (and charged) by
                # its reservation; a member bind moves, not adds
                return None
            if res is not None:
                # replica beyond min_member of a full gang: the
                # extender schedules it as a NORMAL pod on fresh chips
                # (gang.assignable is False), so it is charged — and
                # shed-eligible — like any other burst
                overflow = True
                req_chips = float(count)
            else:
                req_chips = float(pod.group.min_member * count)
        elif resource == RESOURCE_VTPU:
            req_chips = count / max(1, snap.vtpu_shares)
        else:
            req_chips = float(count)
        quota = self.quotas.get(tenant)
        if quota is not None:
            u = snap.usage.get(tenant)
            used_chips = u.chips if u is not None else 0.0
            used_hbm = u.hbm_bytes if u is not None else 0.0
            if (quota.chips is not None
                    and used_chips + req_chips > quota.chips + 1e-9):
                reason = (
                    f"tenant {tenant}: {used_chips:g} chips held + "
                    f"{req_chips:g} asked exceeds the {quota.chips:g}-chip "
                    f"quota"
                )
                self._refuse("TenantQuotaDenied", self.quota_denials,
                             tenant, pod, reason)
                return reason
            if quota.hbm_fraction is not None and snap.capacity_hbm:
                req_hbm = req_chips * snap.capacity_hbm / max(
                    1, snap.capacity_chips
                )
                cap = quota.hbm_fraction * snap.capacity_hbm
                if used_hbm + req_hbm > cap + 1.0:
                    reason = (
                        f"tenant {tenant}: HBM quota exceeded — "
                        f"{used_hbm / snap.capacity_hbm:.3f} of cluster "
                        f"HBM held, cap {quota.hbm_fraction:g}"
                    )
                    self._refuse("TenantQuotaDenied", self.quota_denials,
                                 tenant, pod, reason)
                    return reason
        # SLO-aware shedding: only low-priority, non-gang burst work is
        # ever shed, and only from tenants above the burst population's
        # mean share — committed training gangs and on-quota tenants
        # ride out the burn untouched. Deliberate corollary: with ONE
        # bursting tenant its share IS the mean, so nothing sheds —
        # fairness-based shedding has no over-share target to select,
        # and refusing the only tenant's traffic would just fail the
        # cluster (this is also what keeps a neutral single-tenant
        # plane placement-identical to tenancy off). Single-tenant
        # overload protection is the quota knob, not the shed.
        if ((pod.group is None or overflow)
                and pod.priority <= self.shed_priority_max):
            burning = self.burn.page_burning()
            if burning is not None:
                share = snap.burst_share(tenant)
                mean = snap.mean_burst_share()
                if mean > 0 and share > OVER_SHARE_MARGIN * mean:
                    # the shed cites the TENANT-LOCAL burn alongside
                    # the plane-global trigger: "your own admissions
                    # are burning Nx" is the answer the refused tenant
                    # actually disputes (None = tenant idle so far)
                    tburn = self.burn.tenant_burn(tenant)
                    reason = (
                        f"tenant {tenant}: admission shed — {burning}; "
                        f"burst share {share:.4f} above "
                        f"{OVER_SHARE_MARGIN:g}x the population mean "
                        f"{mean:.4f}"
                        + (f"; tenant-local admission burn {tburn:.1f}x"
                           if tburn is not None else "")
                    )
                    self._refuse("TenantAdmissionShed", self.sheds,
                                 tenant, pod, reason)
                    return reason
        return None

    def _refuse(self, reason: str, counter: dict[str, int], tenant: str,
                pod: PodInfo, message: str) -> None:
        with self._lock:
            counter[tenant] = counter.get(tenant, 0) + 1
        dlog = self.decisions
        if dlog is not None and dlog.wants(pod.key()):
            # the tenancy verdict, with the shares and tenant-local
            # burn AT DECISION TIME — `tpukube-obs explain` renders
            # this as the why-denied line (decision-provenance lint
            # holds every refusal seam to recording one of these)
            try:
                snap = self.ledger.usage()
                dlog.record(
                    pod.key(), "tenancy", verdict=reason,
                    tenant=tenant, message=message,
                    dominant_share=round(snap.dominant_share(tenant), 6),
                    burst_share=round(snap.burst_share(tenant), 6),
                    tenant_burn=self.burn.tenant_burn(tenant),
                )
            except Exception:
                log.exception("decision record failed: %s %s",
                              reason, pod.key())
        if self._events is None:
            return
        try:
            self._events.emit(reason, obj=f"pod/{pod.key()}",
                              message=message, type="Warning")
        except Exception:
            log.exception("event emit failed: %s %s", reason, pod.key())

    # -- DRF ordering (the batched scheduling queue) -------------------------
    def drf_order(self, entries: list) -> list:
        """Order queue entries ``(pod, seq, names)`` for a cycle drain:
        priority bands first (unchanged — priority always dominates),
        then progressive dominant-resource fairness within each band.
        Units are whole gangs (members plan adjacently, as the legacy
        order guaranteed) or single stray pods; each pick charges the
        tenant's VIRTUAL share so one tenant's burst interleaves with
        everyone else's. Ties (equal virtual share) fall back to the
        legacy key — gangs before strays, then arrival — so a neutral
        plane (one tenant) reproduces the legacy order exactly."""
        snap = self.ledger.usage()
        cap = max(1, snap.capacity_chips)
        virtual: dict[str, float] = {}
        # (priority, unit key) -> [entries in seq order]
        units: dict[tuple, list] = {}
        for e in sorted(entries, key=lambda e: e[1]):
            pod = e[0]
            if pod.group is not None:
                ukey = (pod.priority,
                        (0, f"{pod.namespace}/{pod.group.name}"))
            else:
                ukey = (pod.priority, (1, "", e[1]))
            units.setdefault(ukey, []).append(e)
        # per-unit facts resolved ONCE (tenant label lookups and chip
        # estimates must not re-run on every pick of the loop below)
        facts: dict[tuple, tuple[str, float]] = {}
        by_prio: dict[int, list[tuple]] = {}
        for ukey, unit in units.items():
            tenant = self.tenant_of(unit[0][0])
            cost = sum(self.request_chips(e[0]) for e in unit) / cap
            facts[ukey] = (tenant, cost)
            by_prio.setdefault(ukey[0], []).append(ukey)
            virtual.setdefault(tenant, snap.dominant_share(tenant))
        out: list = []
        for prio in sorted(by_prio, reverse=True):
            remaining = list(by_prio[prio])
            # selection loop, O(units^2) per band with a tuple compare
            # per step: queue drains are a few hundred units at most in
            # tenancy deployments (the kilonode trace runs tenancy off
            # and keeps the O(n log n) legacy sort)
            while remaining:
                best_i = 0
                best_key = None
                for i, ukey in enumerate(remaining):
                    k = (virtual[facts[ukey][0]], ukey[1])
                    if best_key is None or k < best_key:
                        best_key, best_i = k, i
                ukey = remaining.pop(best_i)
                out.extend(units[ukey])
                tenant, cost = facts[ukey]
                virtual[tenant] += cost
        return out

    # -- preemption bias -----------------------------------------------------
    def overshare_map(self) -> dict[str, float]:
        """tenant -> how far its dominant share sits above entitlement
        (quota share when capped, else an equal split of the cluster
        among known tenants). The preemption planner prefers victim
        boxes whose owners are furthest over — priority cost still
        dominates the plan ranking."""
        snap = self.ledger.usage()
        known = set(self.quotas) | set(snap.usage)
        n = max(1, len(known))
        out: dict[str, float] = {}
        for tenant in known:
            share = snap.dominant_share(tenant)
            quota = self.quotas.get(tenant)
            entitled = 1.0 / n
            if quota is not None:
                parts = []
                if quota.chips is not None and snap.capacity_chips:
                    parts.append(quota.chips / snap.capacity_chips)
                if quota.hbm_fraction is not None:
                    parts.append(quota.hbm_fraction)
                if parts:
                    entitled = max(parts)
            over = share - entitled
            if over > 1e-9:
                out[tenant] = round(over, 9)
        return out

    # -- observability -------------------------------------------------------
    def shed_total(self) -> int:
        with self._lock:
            return sum(self.sheds.values())

    def quota_denied_total(self) -> int:
        with self._lock:
            return sum(self.quota_denials.values())

    def counter_snapshot(self) -> tuple[dict[str, int], dict[str, int]]:
        with self._lock:
            return dict(self.sheds), dict(self.quota_denials)

    def stats(self) -> dict[str, Any]:
        """The /statusz "tenants" section."""
        snap = self.ledger.usage()
        sheds, denials = self.counter_snapshot()
        tenants: dict[str, Any] = {}
        for tenant in sorted(set(self.quotas) | set(snap.usage)
                             | set(sheds) | set(denials)):
            u = snap.usage.get(tenant, TenantUsage())
            quota = self.quotas.get(tenant)
            tenants[tenant] = {
                "chips_used": round(u.chips, 4),
                "hbm_used_bytes": int(u.hbm_bytes),
                "pods": u.pods,
                "dominant_share": round(snap.dominant_share(tenant), 6),
                "burst_chips": round(u.burst_chips, 4),
                "by_slice": {s: round(c, 4)
                             for s, c in sorted(u.by_slice.items())},
                "quota": (
                    {"chips": quota.chips,
                     "hbm_fraction": quota.hbm_fraction}
                    if quota is not None else None
                ),
                "sheds": sheds.get(tenant, 0),
                "quota_denials": denials.get(tenant, 0),
            }
        shares = [t["dominant_share"] for t in tenants.values()
                  if t["dominant_share"] > 0]
        return {
            "enabled": True,
            "label": self.label,
            "default_tenant": self.default,
            "capacity": {
                "chips": snap.capacity_chips,
                "hbm_bytes": snap.capacity_hbm,
            },
            "tenants": tenants,
            "max_min_share_ratio": (
                round(max(shares) / min(shares), 4) if shares else None
            ),
            "burn": self.burn.stats(),
        }

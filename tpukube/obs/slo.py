"""SLOs + burn-rate math over the /metrics histograms.

PR 1 gave the latency distributions monotonic ``_bucket`` counters; this
module turns them into service-level objectives: "99% of gang commits
land within 2.5s", "99.9% of bind webhooks answer within 250ms". The
burn rate is the standard SRE quantity — observed error ratio divided
by the error budget (1 - objective) — so burn 1.0 spends the budget
exactly at the SLO window's natural pace, burn 14.4 exhausts a 30-day
budget in ~2 days. ``deploy/prometheus-rules.yaml`` encodes the same
SLOs as multi-window burn-rate recording+alerting rules for a real
Prometheus; `tpukube-obs slo` evaluates them offline from a live
/metrics endpoint or a captured snapshot (lifetime burn from one
snapshot, windowed burn from two).

This module also owns the exposition-format PARSER and the lint
validator the tier-1 format test runs over both daemons' /metrics —
the SLO evaluator and the linter must agree on what a series is, so
they share one parser.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)(?:\s+\d+)?$"
)
_LABEL_RE = re.compile(
    r'\s*(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


@dataclass(frozen=True)
class Sample:
    name: str
    labels: tuple[tuple[str, str], ...]
    value: float

    def label(self, key: str, default: str = "") -> str:
        for k, v in self.labels:
            if k == key:
                return v
        return default


def _unescape(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _parse_labels(raw: Optional[str]) -> Optional[tuple]:
    """label tuple, or None on malformed label syntax."""
    if raw is None:
        return ()
    out = []
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            return None
        out.append((m.group("key"), _unescape(m.group("val"))))
        pos = m.end()
    return tuple(out)


def parse_metrics(text: str) -> list[Sample]:
    """Every sample line of an exposition page (comments skipped;
    malformed lines raise — a scrape either parses or it doesn't)."""
    out = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparsable sample: {line!r}")
        labels = _parse_labels(m.group("labels"))
        if labels is None:
            raise ValueError(f"line {lineno}: bad label syntax: {line!r}")
        try:
            value = float(m.group("value"))
        except ValueError as e:
            raise ValueError(f"line {lineno}: bad value: {line!r}") from e
        out.append(Sample(m.group("name"), labels, value))
    return out


# -- exposition lint (the tier-1 format test) --------------------------------

def validate_exposition(text: str) -> list[str]:
    """Prometheus text-format lint: returns a list of violations (empty
    = clean). Checks the properties every series addition must keep:

      * every line parses (names, label syntax/escaping, float values);
      * at most one ``# TYPE`` per family, placed before that family's
        first sample;
      * no duplicate (name, label set) series;
      * a family's samples are contiguous (no other family's TYPE'd
        samples interleaved — untyped singleton lines are legal, which
        is the documented ``tpukube_plugin_resource_info`` quirk);
      * histogram ``_bucket`` samples carry an ``le`` label, summary
        quantile lines a ``quantile`` label.
    """
    errors: list[str] = []
    types: dict[str, str] = {}          # family -> kind
    type_declared_at: dict[str, int] = {}
    first_sample_at: dict[str, int] = {}
    last_family: Optional[str] = None
    closed: set[str] = set()            # families whose block ended
    seen: set[tuple[str, tuple]] = set()

    def family_of(name: str) -> str:
        for suffix in ("_bucket", "_count", "_sum"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                # suffix of a TYPE'd family — unless the suffixed name
                # is itself a TYPE'd family (bucket_only histograms)
                if name not in types:
                    return name[: -len(suffix)]
        return name

    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 4)
            if len(parts) < 4:
                errors.append(f"line {lineno}: malformed TYPE line")
                continue
            fam, kind = parts[2], parts[3]
            if fam in types:
                errors.append(f"line {lineno}: duplicate TYPE for {fam}")
            if fam in first_sample_at:
                errors.append(
                    f"line {lineno}: TYPE for {fam} after its samples "
                    f"(line {first_sample_at[fam]})"
                )
            types[fam] = kind
            type_declared_at[fam] = lineno
            continue
        if line.startswith("#"):
            continue  # HELP / comments: free-form
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparsable sample: {line!r}")
            continue
        name = m.group("name")
        labels = _parse_labels(m.group("labels"))
        if labels is None:
            errors.append(f"line {lineno}: bad label syntax: {line!r}")
            continue
        try:
            float(m.group("value"))
        except ValueError:
            errors.append(f"line {lineno}: non-float value: {line!r}")
            continue
        key = (name, tuple(sorted(labels)))
        if key in seen:
            errors.append(f"line {lineno}: duplicate series {name}"
                          f"{dict(labels)}")
        seen.add(key)
        fam = family_of(name)
        first_sample_at.setdefault(fam, lineno)
        if fam != last_family:
            if last_family is not None:
                closed.add(last_family)
            if fam in closed and fam in types:
                errors.append(
                    f"line {lineno}: family {fam} re-opened after other "
                    f"families (samples must be grouped)"
                )
            last_family = fam
        kind = types.get(fam)
        label_keys = {k for k, _ in labels}
        if name.endswith("_bucket") and kind in ("histogram", "counter"):
            if "le" not in label_keys:
                errors.append(f"line {lineno}: {name} without an le label")
        if kind == "summary" and name == fam and "quantile" not in label_keys:
            errors.append(
                f"line {lineno}: summary {fam} sample without a quantile "
                f"label"
            )
    return errors


# -- SLO definitions ---------------------------------------------------------

@dataclass(frozen=True)
class SloSpec:
    """One latency SLO over a cumulative-bucket histogram family:
    ``objective`` of requests must land in the bucket at
    ``threshold_le`` (which must be a real boundary the registry
    renders — the rules test cross-checks that)."""

    name: str
    family: str           # e.g. "gang_schedule_latency_seconds"
    threshold_le: str     # bucket label, e.g. "2.5"
    objective: float      # e.g. 0.99
    labels: tuple[tuple[str, str], ...] = ()  # child filter (handler=...)
    description: str = ""

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


DEFAULT_SLOS: tuple[SloSpec, ...] = (
    SloSpec(
        name="gang-schedule-latency",
        family="gang_schedule_latency_seconds",
        threshold_le="2.5",
        objective=0.99,
        description="99% of gang commits assemble within 2.5s of the "
                    "slice reservation",
    ),
    SloSpec(
        name="bind-webhook-latency",
        family="tpukube_webhook_latency_seconds",
        threshold_le="0.25",
        objective=0.999,
        labels=(("handler", "bind"),),
        description="99.9% of /bind webhooks answer within 250ms",
    ),
)

# Multi-window multi-burn-rate alert policy (Google SRE workbook ch.5):
# page when the budget burns fast over BOTH a short and a long window
# (the short window makes the alert reset quickly once the burn stops).
MULTIWINDOW_ALERTS: tuple[dict[str, Any], ...] = (
    {"severity": "page", "long": "1h", "short": "5m", "burn": 14.4},
    {"severity": "ticket", "long": "6h", "short": "30m", "burn": 6.0},
)


def histogram_totals(
    samples: Iterable[Sample], family: str, threshold_le: str,
    labels: tuple[tuple[str, str], ...] = (),
) -> tuple[float, float]:
    """(good, total) over a bucket family: good = observations in the
    threshold bucket, total = the +Inf bucket, summed across every
    child matching the label filter."""
    want = dict(labels)
    good = total = 0.0
    for s in samples:
        if s.name != f"{family}_bucket":
            continue
        if any(s.label(k) != v for k, v in want.items()):
            continue
        le = s.label("le")
        if le == threshold_le:
            good += s.value
        elif le == "+Inf":
            total += s.value
    return good, total


def burn_rate(good: float, total: float, objective: float) -> Optional[float]:
    """Observed error ratio over the error budget; None with no
    traffic (no traffic is not a burning SLO)."""
    if total <= 0:
        return None
    error_ratio = 1.0 - good / total
    return round(error_ratio / (1.0 - objective), 6)


def evaluate(
    text: str, slos: Iterable[SloSpec] = DEFAULT_SLOS,
    prev_text: Optional[str] = None,
    window_seconds: Optional[float] = None,
) -> dict[str, Any]:
    """Evaluate SLOs against one exposition page (lifetime burn since
    process start) or a pair (windowed burn over the scrape interval —
    what `tpukube-obs slo --url --window` does)."""
    samples = parse_metrics(text)
    prev = parse_metrics(prev_text) if prev_text is not None else None
    out: dict[str, Any] = {}
    for slo in slos:
        good, total = histogram_totals(
            samples, slo.family, slo.threshold_le, slo.labels
        )
        entry: dict[str, Any] = {
            "slo": slo.description or slo.name,
            "family": slo.family,
            "threshold_seconds": float(slo.threshold_le),
            "objective": slo.objective,
            "good": good,
            "total": total,
            "error_ratio": (round(1.0 - good / total, 6) if total else None),
            "burn_rate": burn_rate(good, total, slo.objective),
            "window": "lifetime",
        }
        if prev is not None:
            pgood, ptotal = histogram_totals(
                prev, slo.family, slo.threshold_le, slo.labels
            )
            dgood, dtotal = good - pgood, total - ptotal
            entry["window"] = (
                f"{window_seconds:g}s" if window_seconds else "delta"
            )
            entry["good"], entry["total"] = dgood, dtotal
            entry["error_ratio"] = (
                round(1.0 - dgood / dtotal, 6) if dtotal > 0 else None
            )
            entry["burn_rate"] = burn_rate(dgood, dtotal, slo.objective)
        br = entry["burn_rate"]
        entry["alerts"] = [
            a["severity"] for a in MULTIWINDOW_ALERTS
            if br is not None and br >= a["burn"]
        ]
        out[slo.name] = entry
    return out


def referenced_metric_names(expr: str) -> set[str]:
    """Base metric names a PromQL expression reads — identifiers that
    are not PromQL functions/keywords or recording-rule names (those
    contain ':'). The rules test cross-checks these against the series
    the registries actually render."""
    ignore = {
        "sum", "rate", "irate", "increase", "histogram_quantile", "by",
        "on", "ignoring", "group_left", "group_right", "avg", "max",
        "min", "count", "abs", "clamp_min", "clamp_max", "le", "and",
        "or", "unless", "without", "offset", "bool", "absent", "topk",
        "bottomk", "delta", "idelta", "changes", "time", "vector",
        "scalar", "label_replace", "Inf", "inf", "nan", "NaN", "m", "h",
        "s", "d",
    }
    out = set()
    # strip label matcher bodies and quoted strings first: their values
    # (handler="bind") are not metric names
    cleaned = re.sub(r'"(?:[^"\\]|\\.)*"', "", expr)
    cleaned = re.sub(r"\{[^}]*\}", "", cleaned)   # label matcher bodies
    cleaned = re.sub(r"\[[^\]]*\]", "", cleaned)  # range selectors [5m]
    # grouping clauses name LABELS, not metrics: by (handler, le)
    cleaned = re.sub(
        r"\b(?:by|on|ignoring|without|group_left|group_right)\s*"
        r"\([^)]*\)", "", cleaned,
    )
    for name in _NAME_RE.findall(cleaned):
        if ":" in name:
            continue  # recording rule
        if name in ignore:
            continue
        out.add(name)
    return out

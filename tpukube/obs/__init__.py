"""Unified observability layer.

Six subsystems, all control-plane-agnostic:

  * :mod:`tpukube.obs.registry` — a small metrics registry
    (Counter/Gauge/Summary/Histogram with label sets, opt-in ``# HELP``)
    rendering Prometheus text format. ``tpukube.metrics``'s renderers
    are built on it; every legacy series name/label renders
    byte-identically, plus histogram ``_bucket`` series for the gang
    and webhook latency distributions.
  * :mod:`tpukube.obs.timeline` — per-pod scheduling timelines:
    correlates DecisionTrace events (webhook decisions + span
    annotations) by pod key into span chains and exports Chrome
    trace-event JSON (Perfetto-loadable) — ``tpukube-obs timeline``.
  * :mod:`tpukube.obs.statusz` — /statusz JSON introspection documents
    for the extender daemon and the node agent: ledger/reservation
    summary, pending-eviction queue with ages, watch liveness with a
    last-event timestamp, trace-ring stats, inventory source, fleet
    health rollup per ICI slice.
  * :mod:`tpukube.obs.health` — per-chip fleet telemetry: the node
    agent's sampler loop over the device layer's
    health/HBM/duty-cycle/ICI-link-error counters, rolling windows,
    health-state transitions, per-chip /metrics series, and the
    compact health summary the node annotation carries upstream.
  * :mod:`tpukube.obs.events` — the structured "why did that happen"
    journal: typed, deduplicated events (GangCommitted, ChipUnhealthy,
    PreemptionPlanned, ...) in a bounded ring + JSONL sink, queryable
    via /statusz, /events, and ``tpukube-obs events``.
  * :mod:`tpukube.obs.slo` — SLO definitions over the latency
    histograms with multi-window burn-rate math (``tpukube-obs slo``,
    deploy/prometheus-rules.yaml), plus the exposition-format parser
    and lint the tier-1 format test runs over both daemons.
"""

from tpukube.obs.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    Summary,
    escape_label_value,
    format_sample,
    quantile,
)

"""Unified observability layer (ISSUE 1 tentpole).

Three subsystems, all control-plane-agnostic:

  * :mod:`tpukube.obs.registry` — a small metrics registry
    (Counter/Gauge/Summary/Histogram with label sets) rendering
    Prometheus text format. ``tpukube.metrics``'s renderers are built on
    it; every legacy series name/label renders byte-identically, plus
    new histogram ``_bucket`` series for the gang and webhook latency
    distributions.
  * :mod:`tpukube.obs.timeline` — per-pod scheduling timelines:
    correlates DecisionTrace events (webhook decisions + span
    annotations) by pod key into span chains and exports Chrome
    trace-event JSON (Perfetto-loadable) — ``tpukube-obs timeline``.
  * :mod:`tpukube.obs.statusz` — /statusz JSON introspection documents
    for the extender daemon and the node agent: ledger/reservation
    summary, pending-eviction queue with ages, watch liveness with a
    last-event timestamp, trace-ring stats, inventory source.
"""

from tpukube.obs.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    Summary,
    escape_label_value,
    format_sample,
    quantile,
)

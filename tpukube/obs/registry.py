"""Metrics registry: Counter/Gauge/Summary/Histogram with label sets,
rendered as Prometheus text format.

This replaces the three bespoke string-assembling ``render_*`` functions
in ``tpukube.metrics`` (which remain as thin builders on top of this).
Design constraints inherited from them:

  * no prometheus_client dependency (not in this environment);
  * byte-compatibility — the renderers built on this registry must emit
    every pre-existing series name/label/value formatted EXACTLY as the
    old renderers did (``%.6g`` values, sorted labels, ``# TYPE`` lines,
    no HELP lines), so dashboards and the golden-file test survive the
    refactor;
  * label values can carry arbitrary runtime text (inventory_source
    embeds PJRT error strings) and must be escaped, not trusted.

Metrics render in registration order; labeled children render in
creation order — both are the emission orders the legacy renderers
produced, and both are deterministic.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Callable, Iterable, Optional, Sequence

# the prometheus_client defaults: request-latency-shaped, which is what
# both gang-commit and webhook latencies are
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: The declared series registry: every metric family any daemon's
#: builders may construct. tpukube-lint's name-consistency pass checks
#: source-level constructor calls (reg.counter/gauge/summary/histogram,
#: Histogram(...)) AND every metric name deploy/prometheus-rules.yaml
#: expressions reference against this set — a renamed or typo'd series
#: fails lint before any dashboard or alert silently goes blind.
#: Summary/histogram families imply their _bucket/_count/_sum children.
DECLARED_SERIES: frozenset[str] = frozenset({
    # extender (tpukube.metrics.build_extender_registry)
    "tpu_chip_utilization_percent",
    "gang_schedule_latency_seconds",
    "tpukube_ici_links_down",
    "tpukube_binds_total",
    "tpukube_gang_rollbacks_total",
    "tpukube_preemptions_total",
    "tpukube_webhook_latency_seconds",
    "tpukube_gang_victims_terminating",
    "tpukube_evictions_pending",
    "tpukube_evictions_total",
    "tpukube_evictions_blocked_total",
    "tpukube_eviction_failures_total",
    "tpukube_eviction_oldest_age_seconds",
    "tpukube_reconciles_total",
    "tpukube_node_refreshes_total",
    "tpukube_lifecycle_releases_total",
    # both daemons (event journal)
    "tpukube_events_total",
    # extender: epoch-cached scheduling snapshot (sched/snapshot.py) —
    # cache effectiveness + the per-slice free-space health it serves
    "tpukube_snapshot_rebuilds_total",
    "tpukube_snapshot_hits_total",
    "tpukube_snapshot_rebuild_seconds",
    # snapshot audit sentinel (snapshot_audit_rate > 0): sampled
    # cache-hit rebuild-and-compare checks and the divergences they
    # caught (any nonzero divergence count is a missed epoch bump)
    "tpukube_snapshot_audit_checks_total",
    "tpukube_snapshot_audit_divergence_total",
    # incremental snapshot maintenance (ISSUE 10; series render only
    # while snapshot_delta_enabled — legacy exposition stays
    # byte-identical with the feature off): O(Δ) delta advances vs the
    # full rebuilds the log could not cover, and the apply latency
    "tpukube_snapshot_delta_applies_total",
    "tpukube_snapshot_delta_overflows_total",
    "tpukube_snapshot_delta_apply_seconds",
    # extender: durable-state journal + crash recovery (sched/
    # journal.py; series render only while journal_enabled built a
    # StateJournal — legacy exposition stays byte-identical with the
    # journal off)
    # extender: bulk cold-start ingestion + generation-based
    # incremental resync (ISSUE 15; ingest series render only while
    # bulk_ingest_enabled, resync series only when the extender runs a
    # generation log AND a lifecycle loop is wired — the
    # feature-off exposition stays byte-identical)
    "tpukube_ingest_nodes_total",
    "tpukube_ingest_seconds",
    "tpukube_resync_full_total",
    "tpukube_resync_incremental_total",
    "tpukube_resync_bytes_total",
    "tpukube_journal_appends_total",
    "tpukube_journal_bytes_total",
    "tpukube_checkpoint_seconds",
    "tpukube_recovery_seconds",
    "tpukube_recovery_replayed_deltas_total",
    "tpukube_slice_fragmentation",
    "tpukube_slice_largest_free_box_chips",
    # extender: batched scheduling cycles (sched/cycle.py; series
    # render only when batch_enabled is on — legacy exposition stays
    # byte-identical with batching off)
    "tpukube_cycles_total",
    "tpukube_cycle_pods_planned_total",
    "tpukube_cycle_plan_hits_total",
    "tpukube_cycle_plan_misses_total",
    "tpukube_cycle_assumes_total",
    "tpukube_cycle_batch_size",
    "tpukube_cycle_wall_seconds",
    "tpukube_cycle_queue_depth",
    # queue-age histogram (ISSUE 17): the starvation signal as _bucket
    # series so it can be alerted on (renders only with batching on)
    "tpukube_cycle_queue_age_seconds",
    # extender: decision provenance + cycle phase profiling
    # (tpukube/obs/decisions.py, ISSUE 12; series render only when
    # decisions_enabled built a DecisionLog — legacy exposition stays
    # byte-identical with provenance off)
    "tpukube_decisions_total",
    "tpukube_decisions_record_seconds_total",
    "tpukube_cycle_phase_seconds",
    # extender: multi-tenant serving plane (tpukube/tenancy; series
    # render only when tenancy_enabled built a TenantPlane — legacy
    # exposition stays byte-identical with tenancy off)
    "tpukube_tenant_chips_used",
    "tpukube_tenant_hbm_used_bytes",
    "tpukube_tenant_dominant_share",
    "tpukube_tenant_quota_chips",
    "tpukube_tenant_quota_hbm_fraction",
    "tpukube_tenant_sheds_total",
    "tpukube_tenant_quota_denials_total",
    "tpukube_tenancy_burn_rate",
    "tpukube_tenancy_shedding",
    # tenancy v2 (ISSUE 12): per-tenant admission/commit latency
    # histograms and the per-tenant windowed SLO burn the shedding
    # decision cites (all render whenever tenancy is on)
    "tpukube_tenant_admission_seconds",
    "tpukube_tenant_commit_seconds",
    "tpukube_tenant_slo_burn",
    # sharded control plane (sched/shard.py, ISSUE 13; series render
    # only from tpukube.metrics.render_router_metrics on a
    # planner_replicas > 1 plane — single-planner exposition is
    # untouched): router topology, routing volume, the two-phase
    # rendezvous ledger, and one summary row per replica
    "tpukube_router_replicas",
    "tpukube_router_rendezvous_total",
    "tpukube_replica_up",
    "tpukube_replica_nodes",
    "tpukube_replica_slices",
    "tpukube_replica_allocs",
    "tpukube_replica_pods_routed_total",
    "tpukube_replica_binds_total",
    "tpukube_replica_utilization",
    "tpukube_replica_queue_depth",
    # process-mode transport telemetry (ISSUE 14): per-replica wire
    # RTT + router health-check counters, rendered ONLY when the
    # router runs the subprocess transport
    "tpukube_replica_rtt_seconds",
    "tpukube_replica_health_checks_total",
    "tpukube_replica_health_check_failures_total",
    # federated observability plane (ISSUE 16): cumulative wire bytes
    # per {op, dir, replica} over the subprocess transport — the
    # measured baseline the ROADMAP codec item is judged against
    "tpukube_router_wire_bytes_total",
    # compact binary wire codec (sched/wirecodec.py, ISSUE 20): bytes
    # the TKW1 codec kept off the transport, per {op, replica} —
    # rendered ONLY when a binary-codec transport exists, so the
    # default (wire_codec: json) exposition stays byte-identical
    "tpukube_router_wire_saved_bytes_total",
    # capacity analytics & demand forensics (tpukube/obs/capacity.py,
    # ISSUE 17; series render only when capacity_enabled built a
    # CapacityRecorder — legacy exposition stays byte-identical with
    # the recorder off)
    "tpukube_capacity_samples_total",
    "tpukube_capacity_sample_seconds_total",
    "tpukube_capacity_fleet_chips",
    "tpukube_capacity_stranded_chips",
    "tpukube_capacity_stranded_demands",
    "tpukube_capacity_recoverable_chips",
    "tpukube_unschedulable_pods",
    # fleet elasticity (sched/drain.py + sched/autoscale.py, ISSUE 19;
    # rendered only when drain_enabled / autoscale_enabled built them)
    "tpukube_drain_started_total",
    "tpukube_drain_completed_total",
    "tpukube_drain_evictions_total",
    "tpukube_drain_nodes_removed_total",
    "tpukube_drain_chips_removed_total",
    "tpukube_drain_slices_dropped_total",
    "tpukube_drain_peak_tick_moves",
    "tpukube_drain_active",
    "tpukube_autoscaler_scale_ups_total",
    "tpukube_autoscaler_scale_downs_total",
    "tpukube_autoscaler_nodes_added_total",
    "tpukube_autoscaler_ticks_total",
    # both daemons (unified retry/circuit layer, core/retry.py; series
    # render only where a Retrier/CircuitBreaker is actually wired)
    "tpukube_retry_attempts_total",
    "tpukube_retry_retries_total",
    "tpukube_retry_exhausted_total",
    "tpukube_circuit_state",
    "tpukube_circuit_opens_total",
    "tpukube_degraded_mode",
    # node agent (tpukube.metrics.build_plugin_registry)
    "tpukube_plugin_allocations_total",
    "tpukube_plugin_devices",
    "tpukube_plugin_resource_info",
    "tpukube_plugin_inventory_source",
    "tpukube_plugin_intent_depth",
    "tpukube_plugin_divergences_total",
    "tpukube_plugin_health_transitions_total",
    "tpukube_plugin_reregistrations_total",
    "tpukube_plugin_intent_watch_events_total",
    "tpukube_chip_healthy",
    "tpukube_chip_duty_cycle_percent",
    "tpukube_chip_hbm_used_bytes",
    "tpukube_chip_hbm_total_bytes",
    "tpukube_chip_ici_link_errors_total",
    "tpukube_chip_health_transitions_total",
    "tpukube_node_chips",
    "tpukube_telemetry_samples_total",
    # annotation syncer sidecar
    "tpukube_syncer_syncs_total",
})


def quantile(values: Iterable[float], q: float) -> float:
    """Nearest-rank quantile; 0.0 on empty input."""
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = min(len(vs) - 1, max(0, round(q * (len(vs) - 1))))
    return vs[idx]


def escape_label_value(value) -> str:
    """Prometheus text-format label-value escaping. An unescaped quote or
    newline would corrupt the whole scrape — on exactly the degraded
    nodes the metric exists to flag."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help_text(text: str) -> str:
    """# HELP docstring escaping (backslash and newline only, per the
    exposition-format spec — quotes are legal in HELP)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_sample(name: str, value: float,
                  labels: Optional[dict[str, str]] = None) -> str:
    """One exposition line, identical to the legacy ``_fmt``."""
    if labels:
        inner = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{inner}}} {value:.6g}\n"
    return f"{name} {value:.6g}\n"


def _bucket_label(bound: float) -> str:
    return "+Inf" if bound == float("inf") else f"{bound:g}"


class Metric:
    """Base: a named family with a ``# TYPE`` line and samples.

    ``emit_type=False`` suppresses the TYPE line (legacy quirk:
    ``tpukube_plugin_resource_info`` rides under the previous family's
    header; byte-compat keeps it that way).

    ``help_text`` opts a family into a ``# HELP`` line before its TYPE.
    Off by default: the pre-registry renderers never emitted HELP and
    the byte-identical goldens must survive; new telemetry/event series
    pass it explicitly.
    """

    kind = "untyped"

    def __init__(self, name: str, emit_type: bool = True,
                 help_text: Optional[str] = None):
        self.name = name
        self.emit_type = emit_type
        self.help_text = help_text
        self._lock = threading.Lock()

    def samples(self) -> list[tuple[str, Optional[dict[str, str]], float]]:
        raise NotImplementedError

    def render(self) -> str:
        out = []
        if self.help_text:
            out.append(f"# HELP {self.name} "
                       f"{escape_help_text(self.help_text)}\n")
        if self.emit_type:
            out.append(f"# TYPE {self.name} {self.kind}\n")
        for name, labels, value in self.samples():
            out.append(format_sample(name, value, labels))
        return "".join(out)


class _ValueChild:
    """One (metric, label set) time series: a stored value or a pull
    callback evaluated at render time."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def get(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value


class _LabeledMetric(Metric):
    """Shared child bookkeeping for Counter/Gauge: ``labels(**kv)``
    returns the per-label-set series, created on first use and emitted
    in creation order."""

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None,
                 emit_type: bool = True, help_text: Optional[str] = None):
        super().__init__(name, emit_type=emit_type, help_text=help_text)
        self._self_child = _ValueChild(fn)
        # label-tuple -> child, insertion-ordered (emission order)
        self._children: dict[tuple[tuple[str, str], ...], _ValueChild] = {}
        self._has_unlabeled = fn is not None

    def labels(self, **labelset: str) -> _ValueChild:
        key = tuple(sorted((k, str(v)) for k, v in labelset.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _ValueChild()
            return child

    # unlabeled convenience surface
    def inc(self, amount: float = 1.0) -> None:
        self._has_unlabeled = True
        self._self_child.inc(amount)

    def set(self, value: float) -> None:
        self._has_unlabeled = True
        self._self_child.set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._has_unlabeled = True
        self._self_child.set_function(fn)

    def samples(self):
        out = []
        with self._lock:
            children = list(self._children.items())
        if self._has_unlabeled or not children:
            out.append((self.name, None, self._self_child.get()))
        for key, child in children:
            out.append((self.name, dict(key), child.get()))
        return out


class Counter(_LabeledMetric):
    kind = "counter"

    def set(self, value: float) -> None:  # counters only go up by contract
        raise TypeError("Counter has no set(); use inc() or set_function()")


class Gauge(_LabeledMetric):
    kind = "gauge"


class _DistChild:
    """Observation store shared by Summary and Histogram children: either
    an explicit observation list (``observe``) or a pull callback
    returning the current value window (``values_fn`` — how the
    renderers wrap the daemons' bounded latency deques)."""

    __slots__ = ("_lock", "_values", "_fn")

    def __init__(self, values_fn: Optional[Callable[[], Iterable[float]]] = None):
        self._lock = threading.Lock()
        self._values: list[float] = []
        self._fn = values_fn

    def observe(self, value: float) -> None:
        with self._lock:
            self._values.append(float(value))

    def values(self) -> list[float]:
        if self._fn is not None:
            return [float(v) for v in self._fn()]
        with self._lock:
            return list(self._values)


class _DistMetric(Metric):
    """Shared child bookkeeping for Summary/Histogram."""

    def __init__(self, name: str,
                 values_fn: Optional[Callable[[], Iterable[float]]] = None,
                 emit_type: bool = True, help_text: Optional[str] = None):
        super().__init__(name, emit_type=emit_type, help_text=help_text)
        self._self_child = _DistChild(values_fn)
        self._has_unlabeled = values_fn is not None
        self._children: dict[tuple[tuple[str, str], ...], _DistChild] = {}

    def labels(self, _values_fn=None, **labelset: str) -> _DistChild:
        key = tuple(sorted((k, str(v)) for k, v in labelset.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _DistChild(_values_fn)
            return child

    def observe(self, value: float) -> None:
        self._has_unlabeled = True
        self._self_child.observe(value)

    def _series(self) -> list[tuple[Optional[dict[str, str]], _DistChild]]:
        out: list[tuple[Optional[dict[str, str]], _DistChild]] = []
        with self._lock:
            children = list(self._children.items())
        if self._has_unlabeled or not children:
            out.append((None, self._self_child))
        for key, child in children:
            out.append((dict(key), child))
        return out


class Summary(_DistMetric):
    """Quantile summary, matching the legacy renderers' shape: one
    ``name{quantile=...}`` line per configured quantile (nearest-rank
    over the current window) plus optional ``_count``/``_sum``."""

    kind = "summary"

    def __init__(self, name: str,
                 quantiles: Sequence[float] = (0.5, 0.9, 0.99),
                 values_fn: Optional[Callable[[], Iterable[float]]] = None,
                 emit_count_sum: bool = True, emit_type: bool = True,
                 help_text: Optional[str] = None):
        super().__init__(name, values_fn=values_fn, emit_type=emit_type,
                         help_text=help_text)
        self.quantiles = tuple(quantiles)
        self.emit_count_sum = emit_count_sum

    def samples(self):
        out = []
        for labels, child in self._series():
            vs = child.values()
            for q in self.quantiles:
                labelset = dict(labels or {})
                labelset["quantile"] = str(q)
                out.append((self.name, labelset, quantile(vs, q)))
            if self.emit_count_sum:
                out.append((f"{self.name}_count", labels, len(vs)))
                out.append((f"{self.name}_sum", labels, sum(vs)))
        return out


class _HistChild:
    """One histogram series: monotonic cumulative state updated at
    ``observe()`` time. Prometheus counters (and ``_bucket`` series ARE
    counters) must never decrease between scrapes — a snapshot of a
    bounded window deque would, the moment the window evicts, and every
    ``rate()``/``histogram_quantile()`` over the series would read the
    dip as a counter reset. So observations fold into per-bucket counts
    immediately (O(len(buckets)) memory, daemon-safe) and the raw values
    are never retained."""

    __slots__ = ("_lock", "_finite", "_counts", "_count", "_sum")

    def __init__(self, finite_bounds: tuple[float, ...]):
        self._lock = threading.Lock()
        self._finite = finite_bounds
        self._counts = [0] * (len(finite_bounds) + 1)  # + the +Inf bucket
        self._count = 0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._counts[bisect_left(self._finite, v)] += 1
            self._count += 1
            self._sum += v

    def snapshot(self) -> tuple[list[int], int, float]:
        """(cumulative count per bucket incl. +Inf, total count, sum)."""
        with self._lock:
            counts, count, total = list(self._counts), self._count, self._sum
        cum, c = [], 0
        for n in counts:
            c += n
            cum.append(c)
        return cum, count, total


class Histogram(Metric):
    """Cumulative-bucket histogram: ``name_bucket{le=...}`` series with a
    ``+Inf`` terminal bucket, plus ``_count``/``_sum``. Observation-only
    (no pull callback): bucket series are counters, and a counter fed
    from a sliding-window snapshot would decrease — see
    :class:`_HistChild`.

    ``bucket_only=True`` pairs the histogram with a pre-existing legacy
    summary of the same family name: only the ``_bucket`` series render
    (typed as their own counter family), so the summary's
    ``_count``/``_sum`` lines are not duplicated and the legacy output
    stays byte-identical.
    """

    kind = "histogram"

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 bucket_only: bool = False, emit_type: bool = True,
                 help_text: Optional[str] = None):
        super().__init__(name, emit_type=emit_type, help_text=help_text)
        bs = sorted(float(b) for b in buckets)
        if not bs or bs[-1] != float("inf"):
            bs.append(float("inf"))
        self.buckets = tuple(bs)
        self.bucket_only = bucket_only
        self._self_child = _HistChild(self.buckets[:-1])
        self._has_unlabeled = False
        self._children: dict[tuple[tuple[str, str], ...], _HistChild] = {}

    def labels(self, **labelset: str) -> _HistChild:
        key = tuple(sorted((k, str(v)) for k, v in labelset.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _HistChild(self.buckets[:-1])
            return child

    def observe(self, value: float) -> None:
        self._has_unlabeled = True
        self._self_child.observe(value)

    def _series(self) -> list[tuple[Optional[dict[str, str]], _HistChild]]:
        out: list[tuple[Optional[dict[str, str]], _HistChild]] = []
        with self._lock:
            children = list(self._children.items())
        if self._has_unlabeled or not children:
            out.append((None, self._self_child))
        for key, child in children:
            out.append((dict(key), child))
        return out

    def render(self) -> str:
        out = []
        # bucket_only: the family proper is already TYPEd (legacy
        # summary); the bucket series get their own counter family
        # header, so HELP must name that family too
        family = f"{self.name}_bucket" if self.bucket_only else self.name
        if self.help_text:
            out.append(f"# HELP {family} "
                       f"{escape_help_text(self.help_text)}\n")
        if self.emit_type:
            if self.bucket_only:
                out.append(f"# TYPE {family} counter\n")
            else:
                out.append(f"# TYPE {self.name} {self.kind}\n")
        for name, labels, value in self.samples():
            out.append(format_sample(name, value, labels))
        return "".join(out)

    def bucket_counts(self, values: Iterable[float]) -> list[int]:
        """Cumulative count per bucket boundary (last = total)."""
        finite = self.buckets[:-1]
        counts = [0] * len(self.buckets)
        total = 0
        for v in values:
            total += 1
            counts[bisect_left(finite, v)] += 1
        cum = 0
        out = []
        for c in counts:
            cum += c
            out.append(cum)
        assert out[-1] == total
        return out

    def samples(self):
        out = []
        for labels, child in self._series():
            cum, count, total = child.snapshot()
            for bound, c in zip(self.buckets, cum):
                labelset = dict(labels or {})
                labelset["le"] = _bucket_label(bound)
                out.append((f"{self.name}_bucket", labelset, c))
            if not self.bucket_only:
                out.append((f"{self.name}_count", labels, count))
                out.append((f"{self.name}_sum", labels, total))
        return out


class Registry:
    """An ordered collection of metrics rendering as one exposition page.

    Registration order IS emission order — the renderers rely on that to
    keep the legacy output byte-identical.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: list[Metric] = []

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            for m in self._metrics:
                if m.name == metric.name and type(m) is type(metric):
                    raise ValueError(
                        f"duplicate {type(metric).__name__} {metric.name!r}"
                    )
            self._metrics.append(metric)
        return metric

    # one-line builders (register + return, for fluent renderer code)
    def counter(self, name: str, **kw) -> Counter:
        return self.register(Counter(name, **kw))  # type: ignore[return-value]

    def gauge(self, name: str, **kw) -> Gauge:
        return self.register(Gauge(name, **kw))  # type: ignore[return-value]

    def summary(self, name: str, **kw) -> Summary:
        return self.register(Summary(name, **kw))  # type: ignore[return-value]

    def histogram(self, name: str, **kw) -> Histogram:
        return self.register(Histogram(name, **kw))  # type: ignore[return-value]

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        return "".join(m.render() for m in metrics)

"""Scheduling decision provenance (ISSUE 12 tentpole).

The control plane can place 10k pods a second and still not answer the
one question an operator asks during an incident: *why* did THIS pod
land where it did — or why is it Pending, or who refused it? The
decision trace replays, the event journal aggregates, the histograms
distribute; none of them assemble one pod's causal chain:

    admit -> queue wait -> cycle pin (snapshot epoch, delta-advance vs
    forced rebuild, fast-path vs general vs batched-gang arm) ->
    per-stage candidate pruning (which filter rejected how many nodes,
    top-k scores) -> gang rendezvous / preemption plan (victims + bias)
    -> tenancy verdict (quota / DRF / shed, with tenant shares at
    decision time) -> assume / bind / undo -> sink append

:class:`DecisionLog` is that chain: a bounded, sampled,
lock-free-on-record ring of per-pod *stage events*. Recording is one
``deque.append`` (atomic under the GIL — no lock is ever taken on the
record path; the optional JSONL sink enqueues to its own drain thread,
trace.JsonlSink style, so a stalled disk never reaches the decision
lock). Sampling is a pure hash of the pod key against a seed, so the
sampled set is deterministic across processes and replica restarts —
``explain`` answers the same pods on every replica that saw them.

Consumers:

  * ``tpukube-obs explain <pod>`` — why-pending / why-here /
    why-denied, rendered from a JSONL sink capture or a live
    extender's ``/explain?pod=`` route;
  * the extender's ``/statusz`` "decisions" section (ring occupancy,
    record overhead, sample rate);
  * scenario 12's measured-overhead guard (``record_seconds`` —
    tools/check.sh fails when provenance at sampling 1.0 costs more
    than the ``decisions.overhead_pct_max`` floor).

Everything is off by default (``decisions_enabled``): with the flag
off the extender holds ``decisions = None``, no series render, no
stage is ever built, and placements are untouched (parity-tested —
provenance observes decisions, it never makes them).
"""

from __future__ import annotations

import itertools
import json
import time
import zlib
from collections import deque
from typing import Any, Iterable, Optional

from tpukube.trace import TRACE_CONTEXT

#: stage vocabulary in use (documentation, not an enum — the explain
#: renderer treats unknown stages as opaque provenance lines):
#:   admit        pod entered the batch scheduling queue
#:   cycle_plan   a batch cycle planned it (arm, epoch, snapshot
#:                advance kind, queue age, assumed node or error)
#:   filter       feasibility answer (candidates, feasible, per-reason
#:                pruning counts)
#:   prioritize   scoring answer (top-k scores)
#:   gang_reserve the pod attached to / created its gang's reservation
#:   preemption_plan  a victim plan was recorded for its gang
#:   tenancy      the tenancy gate refused (quota / shed, with shares
#:                and the tenant-local burn at decision time)
#:   refusal      any other refusal seam (degraded mode, filter error)
#:   bind         the /bind decision (node, ok or error, plan/legacy)
#:   assume_undo  an assumed allocation was undone (re-plan)
#:   plan_expired the plan TTL'd out unbound
#:   preempted    the pod lost its chips to a higher-priority gang
#:   release      the pod's allocation was released
#:   route        (router) the fan-out router chose a replica to score
#:                the pod on
#:   spillover    (router) the home replica refused and the router
#:                spilled the pod to another replica
#:   rendezvous   (router) a two-phase DCN rendezvous verdict for the
#:                pod's gang (outcome prepared/committed/aborted, with
#:                the per-replica parts)
#:   stranded     the capacity forensics root-caused a failed/deferred
#:                plan (reason from the unschedulable taxonomy, with
#:                free-chip / largest-box / recoverable counts)
STAGES = (
    "admit", "cycle_plan", "filter", "prioritize", "gang_reserve",
    "preemption_plan", "tenancy", "refusal", "bind", "assume_undo",
    "plan_expired", "preempted", "release",
    "route", "spillover", "rendezvous", "stranded",
)

#: stages that are refusals — the consistency lint
#: (tpukube.analysis.provenance) holds every refusal/denial seam in the
#: tree to recording one of these
REFUSAL_STAGES = frozenset({"tenancy", "refusal"})


class DecisionLog:
    """Bounded, sampled, lock-free-on-record provenance ring.

    ``capacity`` bounds the ring (stage events, not pods); the oldest
    events rotate out — incident captures that need full depth set
    ``path`` and read the JSONL sink. ``sample_rate`` selects pods by
    a deterministic hash of the pod key (seeded), so 0.01 on a
    kilonode fleet keeps 1% of pods FULLY explained instead of 100% of
    pods 1% explained. Readers (``events``/``explain``/``stats``)
    snapshot the ring with a bounded retry — they never block a
    recording webhook.
    """

    def __init__(self, capacity: int = 8192, sample_rate: float = 1.0,
                 seed: int = 0, path: Optional[str] = None,
                 max_sink_bytes: int = 0) -> None:
        self.capacity = max(1, capacity)
        self.sample_rate = sample_rate
        self.seed = seed
        self._ring: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._seq = itertools.count(1)
        #: stage events recorded (cumulative — feeds
        #: tpukube_decisions_total)
        self.recorded = 0
        #: cumulative wall spent inside record() — the measured
        #: overhead the scenario-12 guard divides by the drive wall
        self.record_seconds = 0.0
        self.path = path or None
        self._sink = None
        if self.path:
            from tpukube.trace import JsonlSink

            self._sink = JsonlSink(self.path, max_bytes=max_sink_bytes)

    # -- sampling ----------------------------------------------------------
    def wants(self, pod_key: str) -> bool:
        """True when this pod is in the sampled set. Pure function of
        (pod key, seed): deterministic across processes, so call sites
        can gate stage construction cheaply and every replica samples
        the same pods."""
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        h = zlib.crc32(pod_key.encode("utf-8"), self.seed & 0xFFFFFFFF)
        return (h % 1000000) < rate * 1000000

    # -- recording ---------------------------------------------------------
    def record(self, pod_key: str, stage: str, **fields: Any) -> None:
        """Append one stage event. Callers gate on :meth:`wants` first
        (``if dlog is not None and dlog.wants(key):``) so unsampled
        pods never even build the kwargs. The ring append is lock-free
        (one atomic deque append); the sink write is an enqueue to the
        drain thread."""
        t0 = time.perf_counter()
        ev: dict[str, Any] = {
            "seq": next(self._seq),
            "ts": time.time(),
            "pod": pod_key,
            "stage": stage,
        }
        ev.update(fields)
        ctx = TRACE_CONTEXT.get()
        if ctx is not None:
            # router-originated request (sharded mode): tag the stage
            # so the stitched /explain and merged timeline can join it
            # to the router's fan-out span; absent outside that path
            ev.setdefault("ctx", dict(ctx))
        self._ring.append(ev)
        self.recorded += 1
        if self._sink is not None:
            # default=str: provenance fields embed runtime values
            # (coords, enums); an unserializable one must degrade to
            # its repr, never fail the webhook that recorded it
            self._sink.write(json.dumps(ev, sort_keys=True,
                                        default=str) + "\n")
        self.record_seconds += time.perf_counter() - t0

    # -- queries -----------------------------------------------------------
    def events(self, pod: Optional[str] = None,
               limit: Optional[int] = None) -> list[dict[str, Any]]:
        """Snapshot of the ring, oldest first. Reads retry around the
        (rare) concurrent-append RuntimeError instead of locking the
        record path."""
        evs: list[dict[str, Any]] = []
        for _ in range(5):
            try:
                evs = list(self._ring)
                break
            except RuntimeError:  # deque mutated mid-iteration
                continue
        if pod is not None:
            evs = [e for e in evs if e.get("pod") == pod]
        if limit is not None:
            evs = evs[-limit:]
        return evs

    def explain(self, pod_key: str) -> dict[str, Any]:
        """The assembled why-pending / why-here / why-denied document
        for one pod, from the live ring."""
        return explain_doc(self.events(), pod_key)

    def stats(self) -> dict[str, Any]:
        """The /statusz "decisions" section."""
        evs = self.events()
        sink_bytes, rotations = (
            self._sink.stats() if self._sink is not None else (None, 0)
        )
        return {
            "enabled": True,
            "capacity": self.capacity,
            "sample_rate": self.sample_rate,
            "seed": self.seed,
            "events": len(evs),
            "pods": len({e.get("pod") for e in evs}),
            "recorded": self.recorded,
            "record_seconds": round(self.record_seconds, 6),
            "sink_path": self.path,
            "sink_bytes": sink_bytes,
            "sink_rotations": rotations,
        }

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()


# -- explain assembly --------------------------------------------------------

def load(path: str) -> list[dict[str, Any]]:
    """Read a decisions JSONL sink back (torn-line tolerant — shared
    loader with the trace/events captures)."""
    import os

    if not os.path.exists(path):
        return []
    from tpukube.trace import load as _load_jsonl

    return _load_jsonl(path)


def pod_events(events: Iterable[dict[str, Any]],
               pod_key: str) -> list[dict[str, Any]]:
    """One pod's stage events in record order."""
    out = [e for e in events
           if isinstance(e, dict) and e.get("pod") == pod_key]
    out.sort(key=lambda e: e.get("seq", 0))
    return out


def merge_stage_events(
    groups: Iterable[tuple[str, Iterable[dict[str, Any]]]],
) -> list[dict[str, Any]]:
    """Stitch stage-event streams from several processes (the router's
    own log plus each owning replica's /explain chain) into ONE stream:
    every event gains a ``replica`` attribution (kept when the source
    already set one), ordering falls back from per-process seq to the
    wall clock (the only ordering that exists across processes), and
    seq is reassigned so :func:`explain_doc` renders the merged chain
    exactly like a local one."""
    merged: list[dict[str, Any]] = []
    for label, evs in groups:
        for ev in evs:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev.setdefault("replica", label)
            merged.append(ev)
    merged.sort(key=lambda e: (float(e.get("ts", 0.0)),
                               str(e.get("replica", "")),
                               int(e.get("seq", 0))))
    for i, ev in enumerate(merged, start=1):
        ev["seq"] = i
    return merged


def explain_doc(events: Iterable[dict[str, Any]],
                pod_key: str) -> dict[str, Any]:
    """Assemble one pod's provenance into a verdict + human "why"
    lines. Verdicts:

      placed     why-here: bound (or assumed) on a node — the chain
                 shows the candidates, pruning, scores, and arm
      denied     why-denied: a refusal seam answered last (tenancy
                 quota/shed, degraded mode, filter error)
      pending    why-pending: known but unbound — zero feasible nodes,
                 an undone/expired plan, or mid-flight
      preempted  placed, then evicted for a higher-priority gang
      released   placed, then released (completed/deleted)
      unknown    no provenance (unsampled pod, rotated out, or off)
    """
    evs = pod_events(events, pod_key)
    verdict = "unknown"
    node: Optional[str] = None
    why: list[str] = []
    for ev in evs:
        stage = ev.get("stage")
        if stage == "admit":
            verdict = "pending" if verdict == "unknown" else verdict
            why.append("admitted to the scheduling queue")
        elif stage == "cycle_plan":
            age = ev.get("queue_age_s")
            pin = ", ".join(
                str(x) for x in (
                    f"arm={ev.get('arm')}",
                    f"cycle={ev.get('cycle')}",
                    f"snapshot={ev.get('snapshot')}",
                    f"epoch={ev.get('epoch')}",
                ) if "None" not in x
            )
            if ev.get("assumed"):
                node = ev.get("node")
                verdict = "placed"
                why.append(
                    f"batch cycle planned it onto {node} ({pin}"
                    + (f", queued {age:.3f}s" if age is not None else "")
                    + ")"
                )
            elif ev.get("error"):
                verdict = "denied"
                why.append(f"batch plan refused it: {ev['error']}")
            elif ev.get("bind_error"):
                verdict = "pending"
                why.append(
                    f"batch plan could not bind it: {ev['bind_error']}"
                )
            else:
                verdict = "pending"
                why.append(f"batch cycle planned it unschedulable ({pin})")
        elif stage == "filter":
            feasible = ev.get("feasible")
            pruned = ev.get("pruned") or {}
            if feasible == 0:
                verdict = "pending"
            line = (f"filter: {feasible}/{ev.get('candidates')} node(s) "
                    f"feasible")
            if pruned:
                tops = sorted(pruned.items(), key=lambda kv: -kv[1])[:3]
                line += "; pruned: " + "; ".join(
                    f"{n}x {reason}" for reason, n in tops
                )
            why.append(line)
        elif stage == "prioritize":
            top = ev.get("top") or []
            why.append("scores: " + ", ".join(
                f"{n}={s}" for n, s in top
            ))
        elif stage == "gang_reserve":
            why.append(
                f"gang {ev.get('gang')}: reservation holds "
                f"{ev.get('chips')} chip(s)"
                + (" (committed)" if ev.get("committed") else "")
            )
        elif stage == "preemption_plan":
            why.append(
                f"gang {ev.get('gang')}: preemption planned — "
                f"{ev.get('victims')} victim workload(s) in "
                f"{ev.get('slices')}"
            )
        elif stage in REFUSAL_STAGES:
            verdict = "denied"
            reason = ev.get("message") or ev.get("reason") or "refused"
            if stage == "tenancy":
                extra = []
                if ev.get("burst_share") is not None:
                    extra.append(f"burst share {ev['burst_share']}")
                if ev.get("dominant_share") is not None:
                    extra.append(
                        f"dominant share {ev['dominant_share']}")
                if ev.get("tenant_burn") is not None:
                    extra.append(
                        f"tenant-local burn {ev['tenant_burn']}x")
                why.append(
                    f"tenancy gate refused ({ev.get('tenant')}): "
                    f"{reason}"
                    + (f" [{'; '.join(extra)}]" if extra else "")
                )
            else:
                why.append(f"refused ({ev.get('kind')}): {reason}")
        elif stage == "bind":
            if ev.get("ok"):
                verdict = "placed"
                node = ev.get("node")
                why.append(
                    f"bound on {node} (served from the "
                    f"{ev.get('served_from')} path)"
                )
            else:
                verdict = "pending"
                why.append(f"bind to {ev.get('node')} failed: "
                           f"{ev.get('error')}")
        elif stage == "assume_undo":
            verdict = "pending"
            why.append("assumed allocation undone (re-plan)")
        elif stage == "plan_expired":
            verdict = "pending"
            why.append("batch plan expired unbound (reservation TTL)")
        elif stage == "preempted":
            verdict = "preempted"
            why.append(
                "evicted: chips taken by a higher-priority gang"
                + (f" ({ev['by']})" if ev.get("by") else "")
            )
        elif stage == "release":
            if verdict == "placed":
                verdict = "released"
            why.append("allocation released")
        elif stage == "route":
            why.append(
                f"router: scored on replica {ev.get('replica')}"
                + (f" ({ev.get('reason')})" if ev.get("reason") else "")
            )
        elif stage == "spillover":
            why.append(
                f"router: spilled over from replica {ev.get('primary')} "
                f"to replica {ev.get('replica')}"
            )
        elif stage == "stranded":
            # verdict stays pending/unschedulable — forensics explains
            # WHY the demand cannot place, it is not a new outcome
            bits = []
            if ev.get("free_chips") is not None:
                bits.append(f"{ev['free_chips']} chips free")
            if ev.get("largest_free_box") is not None:
                bits.append(
                    f"largest contiguous box {ev['largest_free_box']}")
            if ev.get("recoverable_chips"):
                bits.append(
                    f"{ev['recoverable_chips']} recoverable by repack")
            why.append(
                f"stranded: {ev.get('chips')} chip(s) unschedulable — "
                f"root cause {ev.get('reason')}"
                + (f" ({', '.join(bits)})" if bits else "")
            )
        elif stage == "rendezvous":
            parts = ev.get("parts") or []
            detail = ", ".join(
                f"{p.get('chips')} chip(s) on {p.get('slice')} "
                f"(replica {p.get('replica')})" for p in parts
            )
            why.append(
                f"router: DCN rendezvous {ev.get('outcome')} for gang "
                f"{ev.get('gang')}"
                + (f" — {detail}" if detail else "")
                + (f" ({ev.get('reason')})" if ev.get("reason") else "")
            )
        else:
            why.append(f"{stage}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("seq", "ts", "pod", "stage")
            ))
    if verdict == "unknown" and evs:
        # stages exist but none was verdict-moving (a mid-flight pod:
        # filter/prioritize recorded, bind not yet) — that is a
        # PENDING pod, and "no provenance recorded" would deny the
        # very lines rendered below it
        verdict = "pending"
    return {
        "pod": pod_key,
        "verdict": verdict,
        "node": node,
        "stages": evs,
        "why": why,
    }


def format_explain(doc: dict[str, Any]) -> str:
    """Human rendering for `tpukube-obs explain` (the --json flag
    prints the raw document instead)."""
    head = {
        "placed": f"PLACED on {doc.get('node')}",
        "denied": "DENIED",
        "pending": "PENDING",
        "preempted": "PREEMPTED",
        "released": f"RELEASED (was on {doc.get('node')})",
        "unknown": ("UNKNOWN — no provenance recorded (pod unsampled, "
                    "rotated out of the ring, or decisions_enabled "
                    "is off)"),
    }[doc.get("verdict", "unknown")]
    lines = [f"{doc.get('pod')}: {head}"]
    for i, line in enumerate(doc.get("why", []), start=1):
        lines.append(f"  {i:2d}. {line}")
    return "\n".join(lines)

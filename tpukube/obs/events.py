"""Structured event journal — the control plane's "why did that happen".

Metrics say HOW MUCH and /statusz says WHAT RIGHT NOW; neither answers
"why is gang X not binding" an hour later. This journal is the
K8s-Events-style answer: typed, deduplicated records emitted at the
same seams the decision-trace span hooks use (gang reserve/commit/
rollback, preemption plan/execute, chip and ICI-link health
transitions, watch reconnects, kubelet divergences), held in a bounded
ring and optionally streamed to a JSONL sink for `tpukube-obs events`.

Reasons in use are DECLARED in ``REASONS`` below — tpukube-lint's
name-consistency pass checks every source-level ``emit(...)`` literal
against it, so adding a reason means adding it to the enum (a typo'd
reason fails lint instead of silently fragmenting the journal):

  GangReserved, GangCommitted, GangRollback, GangDissolved,
  PreemptionPlanned, PreemptionExecuted, VictimEvicted, VictimGone,
  ChipUnhealthy, ChipRecovered, LinkFault, LinkRecovered,
  WatchReconnected, AllocDiverged, KubeletReregistered, BindFailed,
  CircuitOpen, CircuitClosed, RetryExhausted, DegradedMode,
  TenantQuotaDenied, TenantAdmissionShed, CheckpointWritten,
  JournalTruncated, RecoveryCompleted, RecoveryDiverged,
  DrainStarted, DrainCompleted, DrainCancelled, AutoscaleUp,
  AutoscaleDown

Dedup follows the K8s model: an event with the same (reason, object,
message) as a live ring entry bumps that entry's ``count`` and
``last_ts`` instead of appending — a flapping chip makes one line with
count=40, not 40 lines. Every emission still writes its own JSONL sink
line (carrying the current count), so file-based forensics keep the
full timing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterable, Optional

# event severities, K8s-style
NORMAL = "Normal"
WARNING = "Warning"

#: The declared reason enum. Every emit() call in the tree must use one
#: of these (enforced source-level by tpukube-lint name-consistency;
#: consumers — /events filters, tpukube-obs events, the
#: tpukube_events_total{reason} counter — key off these strings).
REASONS: tuple[str, ...] = (
    "AllocDiverged",
    "AutoscaleDown",
    "AutoscaleUp",
    "BindFailed",
    "CheckpointWritten",
    "ChipRecovered",
    "ChipUnhealthy",
    "CircuitClosed",
    "CircuitOpen",
    "DegradedMode",
    "DrainCancelled",
    "DrainCompleted",
    "DrainStarted",
    "GangCommitted",
    "GangDissolved",
    "GangReserved",
    "GangRollback",
    "JournalTruncated",
    "KubeletReregistered",
    "LinkFault",
    "LinkRecovered",
    "PreemptionExecuted",
    "PreemptionPlanned",
    "RecoveryCompleted",
    "RecoveryDiverged",
    "RetryExhausted",
    "TenantAdmissionShed",
    "TenantQuotaDenied",
    "VictimEvicted",
    "VictimGone",
    "WatchReconnected",
)


class EventJournal:
    """Bounded, deduplicating ring of typed events + optional JSONL sink.

    ``capacity=0`` disables the journal entirely (emit becomes a no-op),
    which is how config turns it off without every emitter re-checking.
    """

    def __init__(self, capacity: int = 4096, path: Optional[str] = None,
                 max_sink_bytes: int = 0) -> None:
        self.capacity = capacity
        self.path = path or None
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque()
        # (reason, object, message) -> live ring entry, for dedup; keys
        # leave the map when their entry is evicted from the ring
        self._live: dict[tuple[str, str, str], dict[str, Any]] = {}
        self._seq = 0
        self._total = 0  # emissions including deduped (metrics)
        self._by_reason: dict[str, int] = {}
        # The sink is a trace.JsonlSink: emit() only ENQUEUES the
        # serialized line — the file write happens on the sink's drain
        # thread. Emitters call from inside the gang manager's lock and
        # the extender's decision paths, where one blocked write
        # syscall would freeze every concurrent webhook.
        # ``max_sink_bytes`` rotates the file once to ``<path>.1`` at
        # the cap, same policy as the decision-trace sink.
        self._sink = None
        if self.path and capacity > 0:
            from tpukube.trace import JsonlSink

            self._sink = JsonlSink(self.path, max_bytes=max_sink_bytes)

    # -- emission ----------------------------------------------------------
    def emit(self, reason: str, obj: str = "", message: str = "",
             type: str = NORMAL, node: str = "") -> Optional[dict[str, Any]]:
        """Record one event. ``obj`` names what the event is about, in
        ``kind/name`` form ("pod/default/p0", "gang/default/llama",
        "chip/tpu-3", "node/host-0-0-0"); ``node`` optionally pins the
        host for node-scoped filtering. Returns the (possibly deduped)
        ring entry, or None when the journal is disabled."""
        if self.capacity <= 0:
            return None
        now = time.time()
        key = (reason, obj, message)
        with self._lock:
            self._total += 1
            self._by_reason[reason] = self._by_reason.get(reason, 0) + 1
            ev = self._live.get(key)
            if ev is not None:
                ev["count"] += 1
                ev["last_ts"] = now
            else:
                self._seq += 1
                ev = {
                    "seq": self._seq,
                    "type": type,
                    "reason": reason,
                    "object": obj,
                    "node": node,
                    "message": message,
                    "count": 1,
                    "first_ts": now,
                    "last_ts": now,
                }
                self._ring.append(ev)
                self._live[key] = ev
                while len(self._ring) > self.capacity:
                    old = self._ring.popleft()
                    okey = (old["reason"], old["object"], old["message"])
                    if self._live.get(okey) is old:
                        del self._live[okey]
            if self._sink is not None:
                # serialize under the lock (the ring entry mutates on
                # later dedups; enqueue order = emission order)
                self._sink.write(json.dumps(ev, sort_keys=True) + "\n")
            return ev

    # -- queries -----------------------------------------------------------
    def events(self, reason: Optional[str] = None,
               pod: Optional[str] = None, node: Optional[str] = None,
               since: Optional[float] = None,
               limit: Optional[int] = None) -> list[dict[str, Any]]:
        """Filtered view of the ring, oldest first. ``pod`` matches the
        object's pod identity (``pod/<key>`` objects and any object whose
        name embeds the pod key); ``since`` is an absolute unix ts."""
        with self._lock:
            out = [dict(ev) for ev in self._ring]
        out = filter_events(out, reason=reason, pod=pod, node=node,
                            since=since)
        if limit is not None:
            out = out[-limit:]
        return out

    def counts_by_reason(self) -> dict[str, int]:
        """Cumulative emissions per reason (feeds the
        ``tpukube_events_total{reason=...}`` counter)."""
        with self._lock:
            return dict(self._by_reason)

    def stats(self) -> dict[str, Any]:
        sink_bytes, rotations = (
            self._sink.stats() if self._sink is not None else (None, 0)
        )
        with self._lock:
            return {
                "enabled": self.capacity > 0,
                "capacity": self.capacity,
                "events": len(self._ring),
                "total_emitted": self._total,
                "sink_path": self.path,
                "sink_bytes": sink_bytes,
                "sink_rotations": rotations,
            }

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()


def filter_events(events: Iterable[dict[str, Any]],
                  reason: Optional[str] = None, pod: Optional[str] = None,
                  node: Optional[str] = None,
                  since: Optional[float] = None,
                  replica: Optional[str] = None) -> list[dict[str, Any]]:
    """The journal's query predicate over plain event dicts — shared by
    the live ring and `tpukube-obs events` reading a JSONL sink.
    ``replica`` matches the source-replica attribution a federated
    merge stamps (sched/shard.py ``events_federated``); events without
    one (a single-planner journal) never match a replica filter."""
    out = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        if reason is not None and ev.get("reason") != reason:
            continue
        if node is not None and ev.get("node") != node:
            continue
        if replica is not None and ev.get("replica") != replica:
            continue
        if pod is not None:
            # exact pod identity only: "pod/<key>" or any object whose
            # name tail IS the key — substring matching would leak
            # default/p10..p19's events into a default/p1 query
            obj = str(ev.get("object", ""))
            if obj != f"pod/{pod}" and not obj.endswith(f"/{pod}"):
                continue
        if since is not None and float(ev.get("last_ts", 0)) < since:
            continue
        out.append(ev)
    return out


def load(path: str) -> list[dict[str, Any]]:
    """Read a JSONL event sink back into a list ([] for a missing
    file). Delegates to the trace module's torn-line-tolerant loader —
    one JSONL reader, one skipped-line diagnostic, for both capture
    formats."""
    if not os.path.exists(path):
        return []
    from tpukube.trace import load as _load_jsonl

    return _load_jsonl(path)


def format_event(ev: dict[str, Any]) -> str:
    """One human line per event (the `tpukube-obs events` default)."""
    ts = time.strftime("%H:%M:%S", time.localtime(ev.get("last_ts", 0)))
    count = ev.get("count", 1)
    suffix = f" (x{count})" if count > 1 else ""
    node = f" [{ev['node']}]" if ev.get("node") else ""
    replica = f" @{ev['replica']}" if ev.get("replica") else ""
    return (f"{ts} {ev.get('type', NORMAL):7s} {ev.get('reason', '?'):20s} "
            f"{ev.get('object', ''):32s} {ev.get('message', '')}"
            f"{suffix}{node}{replica}")

"""Per-pod scheduling timelines from a DecisionTrace event stream.

The decision trace is a flat transcript of the control plane: webhook
decisions (filter/prioritize/bind), releases, victim confirmations, and
— new with the obs layer — ``span`` annotations recorded at interesting
internal points (gang reserve, preemption plan, gang commit, plugin
Allocate/intent-match). This module answers "where did pod X spend its
93 ms between first filter and Allocate?" by correlating all of those by
pod key into one track per pod and exporting Chrome trace-event JSON
(load in Perfetto / chrome://tracing), plus per-phase aggregate stats
for the bench line.

Each event becomes one slice on its pod's track: the slice is NAMED for
the event that ends it and SPANS the time since the pod's previous
event — so a wide "bind" slice literally is the wait between filter and
bind, the quantity an incident investigation needs.
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Optional

from tpukube.obs.registry import quantile

# events with no pod affiliation land on this synthetic track
CLUSTER_TRACK = "(cluster)"


def _pod_key_of_pod_obj(pod: Any) -> Optional[str]:
    if not isinstance(pod, dict):
        return None
    meta = pod.get("metadata") or {}
    name = meta.get("name")
    if not name:
        return None
    return f"{meta.get('namespace', 'default')}/{name}"


def event_pod_key(ev: dict) -> Optional[str]:
    """The pod a trace event is about, or None for cluster-scoped events
    (upsert_node, unattributable spans)."""
    kind = ev.get("kind")
    req = ev.get("request")
    if kind in ("filter", "prioritize"):
        return _pod_key_of_pod_obj((req or {}).get("Pod"))
    if kind == "bind":
        if not isinstance(req, dict) or "PodName" not in req:
            return None
        return f"{req.get('PodNamespace', 'default')}/{req['PodName']}"
    if kind in ("release", "victim_gone", "reconcile"):
        return (req or {}).get("pod_key") if isinstance(req, dict) else None
    if kind == "span":
        key = (req or {}).get("pod_key") if isinstance(req, dict) else None
        return key or None
    return None


def event_phase(ev: dict) -> str:
    """Display name of the phase an event completes (span events carry
    their own name: gang_reserve, preemption_plan, gang_commit,
    intent_match, allocate, ...)."""
    if ev.get("kind") == "span":
        req = ev.get("request") or {}
        return str(req.get("name") or "span")
    return str(ev.get("kind"))


def _event_args(ev: dict) -> dict[str, Any]:
    args: dict[str, Any] = {"seq": ev.get("seq"), "kind": ev.get("kind")}
    kind = ev.get("kind")
    resp = ev.get("response")
    if kind == "span" and isinstance(ev.get("request"), dict):
        args.update({
            k: v for k, v in ev["request"].items() if k not in ("name",)
        })
    elif kind == "filter" and isinstance(resp, dict):
        args["feasible"] = len(resp.get("NodeNames") or [])
        args["failed"] = len(resp.get("FailedNodes") or {})
        if resp.get("Error"):
            args["error"] = resp["Error"]
    elif kind == "bind" and isinstance(resp, dict):
        if resp.get("Error"):
            args["error"] = resp["Error"]
    return args


def correlate(events: Iterable[dict]) -> dict[str, list[dict]]:
    """pod key -> that pod's events, each sorted by (ts, seq). Cluster-
    scoped events group under :data:`CLUSTER_TRACK`."""
    tracks: dict[str, list[dict]] = {}
    for ev in events:
        # tolerate partial events: a crashed or still-pending pod's
        # track may hold only span annotations (no bind/filter), and a
        # torn capture may carry junk — neither must break the exporter
        if not isinstance(ev, dict):
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            continue
        key = event_pod_key(ev) or CLUSTER_TRACK
        tracks.setdefault(key, []).append(ev)
    for evs in tracks.values():
        evs.sort(key=lambda e: (e["ts"], e.get("seq", 0)))
    return tracks


def chrome_trace(events: Iterable[dict]) -> dict[str, Any]:
    """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` object
    format Perfetto and chrome://tracing load).

    One thread per pod (tid = rank in sorted pod-key order, thread_name
    metadata carries the key); each event is a complete ("X") slice from
    the pod's previous event to this one, so gaps between decisions are
    visible as slice widths.
    """
    tracks = correlate(events)
    all_ts = [e["ts"] for evs in tracks.values() for e in evs]
    t0 = min(all_ts) if all_ts else 0.0
    trace_events: list[dict[str, Any]] = []
    for tid, pod_key in enumerate(sorted(tracks), start=1):
        trace_events.append({
            "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
            "args": {"name": pod_key},
        })
        prev_us: Optional[float] = None
        for ev in tracks[pod_key]:
            us = (ev["ts"] - t0) * 1e6
            start = us if prev_us is None else prev_us
            trace_events.append({
                "name": event_phase(ev),
                "ph": "X",
                "ts": round(start, 3),
                "dur": round(max(us - start, 1.0), 3),
                "pid": 1,
                "tid": tid,
                "args": _event_args(ev),
            })
            prev_us = us
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def _span_bounds(ev: dict) -> Optional[tuple[float, float]]:
    """Explicit wall-clock bounds on a span event, when recorded. The
    router's fan-out spans (sched/shard.py ``_traced``) stamp t0/t1 so
    the merged timeline can render them as true enclosing slices
    instead of width-since-previous-event slices."""
    req = ev.get("request")
    if ev.get("kind") == "span" and isinstance(req, dict) \
            and isinstance(req.get("t0"), (int, float)) \
            and isinstance(req.get("t1"), (int, float)):
        return float(req["t0"]), float(req["t1"])
    return None


def merged_chrome_trace(
    captures: list[tuple[str, list[dict]]],
) -> dict[str, Any]:
    """One Chrome trace stitched from several per-process captures
    (ISSUE 16 federated observability): each capture renders as its own
    process (pid; process_name metadata carries the label — router,
    r0, r1, ...), sharing ONE time zero, so the router's fan-out spans
    visibly enclose/overlap the worker slices they fanned out to.
    Events tagged with a propagated trace context (``ctx``) surface
    ``trace``/``parent`` in their args — the join key across
    processes. Span events carrying explicit t0/t1 bounds render as
    true wall-clock slices; everything else keeps the
    width-since-previous-event semantics of :func:`chrome_trace`."""
    all_ts: list[float] = []
    for _, events in captures:
        for ev in events:
            if not isinstance(ev, dict):
                continue
            bounds = _span_bounds(ev)
            if bounds is not None:
                all_ts.append(bounds[0])
            elif isinstance(ev.get("ts"), (int, float)):
                all_ts.append(ev["ts"])
    zero = min(all_ts) if all_ts else 0.0
    trace_events: list[dict[str, Any]] = []
    for pid, (label, events) in enumerate(captures, start=1):
        trace_events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        tracks = correlate(events)
        for tid, pod_key in enumerate(sorted(tracks), start=1):
            trace_events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid, "args": {"name": pod_key},
            })
            prev_us: Optional[float] = None
            for ev in tracks[pod_key]:
                args = _event_args(ev)
                ctx = ev.get("ctx")
                if isinstance(ctx, dict):
                    args["trace"] = ctx.get("trace")
                    args["parent"] = ctx.get("parent")
                bounds = _span_bounds(ev)
                if bounds is not None:
                    start = (bounds[0] - zero) * 1e6
                    end = (bounds[1] - zero) * 1e6
                    trace_events.append({
                        "name": event_phase(ev),
                        "ph": "X",
                        "ts": round(start, 3),
                        "dur": round(max(end - start, 1.0), 3),
                        "pid": pid,
                        "tid": tid,
                        "args": args,
                    })
                    prev_us = end
                    continue
                us = (ev["ts"] - zero) * 1e6
                start = us if prev_us is None else prev_us
                trace_events.append({
                    "name": event_phase(ev),
                    "ph": "X",
                    "ts": round(start, 3),
                    "dur": round(max(us - start, 1.0), 3),
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                })
                prev_us = us
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def span_chains(events: Iterable[dict]) -> dict[str, list[str]]:
    """pod key -> ordered phase names on its track (the chain the 16-pod
    gang acceptance check inspects: filter→gang_reserve→bind→allocate)."""
    return {
        key: [event_phase(ev) for ev in evs]
        for key, evs in correlate(events).items()
        if key != CLUSTER_TRACK
    }


def phase_stats(events: Iterable[dict]) -> dict[str, dict[str, Any]]:
    """Per-phase timing aggregates across all pods: for each phase name,
    the count of slices and the p50/p99/max slice width in ms (a slice's
    width = time since the pod's previous event — "time spent reaching
    this phase"). Feeds the bench line's ``phases`` key.

    A pod's FIRST event has no predecessor, so its width is undefined:
    it contributes to ``count`` but not to the percentiles (recording it
    as 0.0 would drag the entry phase's p50 toward zero and misreport
    the very attribution this exists for). A phase observed only as
    first events reports null percentiles."""
    counts: dict[str, int] = {}
    widths: dict[str, list[float]] = {}
    for key, evs in correlate(events).items():
        if key == CLUSTER_TRACK:
            continue
        prev: Optional[float] = None
        for ev in evs:
            phase = event_phase(ev)
            counts[phase] = counts.get(phase, 0) + 1
            if prev is not None:
                widths.setdefault(phase, []).append((ev["ts"] - prev) * 1e3)
            prev = ev["ts"]
    out: dict[str, dict[str, Any]] = {}
    for phase in sorted(counts):
        ws = widths.get(phase)
        out[phase] = {
            "count": counts[phase],
            "p50_ms": round(quantile(ws, 0.5), 3) if ws else None,
            "p99_ms": round(quantile(ws, 0.99), 3) if ws else None,
            "max_ms": round(max(ws), 3) if ws else None,
        }
    return out


def dump_chrome_trace(events: Iterable[dict], fp) -> None:
    json.dump(chrome_trace(events), fp, sort_keys=True)
    fp.write("\n")

"""Capacity analytics & demand forensics plane (ISSUE 17).

/metrics answers "how much, right now"; this module answers the three
questions the ROADMAP's defragmenter and autoscaler consume and nothing
else records: *how did fleet capacity evolve* (flight recorder), *why
exactly is demand unschedulable* (stranded-demand forensics), and *what
would fit if we acted* (what-if placement probes).

Three pillars, one subsystem:

  * **Flight recorder** — a bounded ring of periodic fleet samples
    (per-slice utilization / fragmentation / largest-free-box /
    unhealthy+terminating counts, queue depth + oldest age, per-tenant
    dominant shares + burn verdict, the live stranded rollup), sampled
    on the SCHEDULING clock (FakeClock-compressible) and served from
    the epoch-cached snapshot's ``observe()`` view so a sample rides
    the existing O(Δ) maintenance chain instead of rebuilding anything.
    An optional JSONL sink on the :class:`tpukube.trace.JsonlSink`
    drain-thread pattern persists samples for `tpukube-obs capacity
    --merge` stitching.

  * **Stranded-demand forensics** — every failed/deferred plan is
    root-caused into a typed taxonomy: ``fragmented`` (chips free but
    no contiguous box — the repack signal), ``capacity`` (not enough
    free chips anywhere), ``quota`` / ``shed`` (tenancy refusals, also
    in the DecisionLog), ``unhealthy`` (free-if-healed capacity would
    cover it), ``dcn-ineligible`` (only multi-slice spanning could
    serve it and the gang did not opt in), plus ``transient`` for the
    honest race where a fit exists by the time forensics re-probes
    (degrade loudly, never misattribute). Counts feed
    ``tpukube_unschedulable_pods{reason}``; live demands feed the
    per-shape stranded ledger on /statusz and the explain chain's
    ``stranded`` stage.

  * **What-if probes** — a read-only fit dry-run against the current
    epoch-pinned snapshot: per-slice contiguous verdicts through the
    REAL vectorized sweep (``slicefit.find_slice_in``) plus the greedy
    DCN-split fallback, the API a defragmenter or autoscaler calls
    before acting. Served on ``/capacity/probe`` and federated by the
    shard router.

Everything is gated on ``capacity_enabled`` (default off): nothing is
constructed, sampled, or rendered when the flag is off, so the legacy
exposition stays byte-identical.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Optional

from tpukube import trace as trace_mod
from tpukube.sched import slicefit

#: the forensics taxonomy (``tpukube_unschedulable_pods{reason}``);
#: ``transient`` is the loud fallback for plans whose failure no longer
#: reproduces against the current snapshot (a racing release) — honest
#: over plausible
UNSCHEDULABLE_REASONS = (
    "capacity", "dcn-ineligible", "draining", "fragmented", "quota",
    "shed", "transient", "unhealthy",
)

#: scheduling-clock seconds a stranded-ledger entry survives without a
#: refreshing re-classification when no batch queue exists to consult
#: for liveness (batching on: the entry dies the moment its pod leaves
#: the queue's first-admit stamps)
STRANDED_TTL_SECONDS = 900.0

#: the utilization sparkline ramp (`tpukube-obs capacity`)
_SPARK = "▁▂▃▄▅▆▇█"

_DURATION_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(text: Any) -> float:
    """``"15m"`` / ``"2h"`` / ``"90s"`` / ``"1d"`` → seconds; bare
    numbers pass through as float seconds. Raises ValueError on junk —
    the CLI turns that into an argparse error."""
    t = str(text).strip()
    if not t:
        raise ValueError("empty duration")
    unit = _DURATION_UNITS.get(t[-1].lower())
    if unit is not None:
        return float(t[:-1]) * unit
    return float(t)


def parse_since(text: Any) -> float:
    """The shared ``--since`` parser (`tpukube-obs events` /
    `capacity`): a suffixed duration is RELATIVE (its seconds value is
    far below any epoch timestamp, so the existing newest-minus-delta
    branch applies); a bare number keeps the legacy float semantics
    (epoch seconds, or a small relative number)."""
    return parse_duration(text)


def parse_shape(text: str) -> tuple[int, int, int]:
    """``"4x4x4"`` → ``(4, 4, 4)`` (the /capacity/probe query shape)."""
    parts = str(text).lower().split("x")
    if len(parts) != 3:
        raise ValueError(f"shape {text!r}: want XxYxZ")
    dims = tuple(int(p) for p in parts)
    if any(d < 1 for d in dims):
        raise ValueError(f"shape {text!r}: extents must be >= 1")
    return dims  # type: ignore[return-value]


def _healed_free(ss) -> int:
    """Chips free for a new placement if every unhealthy/terminating
    chip healed — the counterfactual that separates ``unhealthy`` from
    ``capacity`` in the taxonomy. Cordoned chips stay blocked: healing
    does not un-drain a node (that counterfactual is ``draining``'s,
    probed separately)."""
    blocked = ((ss.occupied | ss.reserved | ss.cordoned | ss.absent)
               - ((ss.unhealthy | ss.terminating) - ss.cordoned))
    return ss.mesh.num_chips - len(blocked)


class CapacityRecorder:
    """The capacity analytics subsystem one extender owns (None unless
    ``capacity_enabled``). Constructed after the snapshot cache and the
    optional cycle/tenant planes so samples can read all of them.

    Recording is observer-grade: samples read
    ``snapshots.observe()`` (never ``current()`` — an observer read
    must not warm or fork the scheduling path's cache discipline), and
    both the sampler and the forensics accumulate their wall into
    ``sample_seconds`` so check.sh's capacity smoke can floor the
    measured overhead exactly like the decisions smoke floors
    ``record_seconds``."""

    def __init__(self, extender, config) -> None:
        self._ext = extender
        self._interval = config.capacity_sample_interval_seconds
        self.ring_capacity = config.capacity_samples
        self.ring: deque[dict] = deque(maxlen=self.ring_capacity)
        self.sink: Optional[trace_mod.JsonlSink] = (
            trace_mod.JsonlSink(
                config.capacity_path,
                max_bytes=config.capacity_sink_max_bytes,
            ) if config.capacity_path else None
        )
        # cumulative counters (lock-free, like DecisionLog.record):
        # plain int/float adds under the GIL — a racing reader sees a
        # slightly stale number, never a torn one
        self.samples_taken = 0
        self.sample_seconds = 0.0
        self.classified = 0
        self._unschedulable: dict[str, int] = {}
        # scheduling-clock instant of the last sample (None = never)
        self._last_sample: Optional[float] = None
        # stranded ledger: shape label -> demand key -> record; leaf
        # lock only (dict updates, no calls out under it)
        self._lock = threading.Lock()
        self._stranded: dict[str, dict[str, dict]] = {}
        # demand key -> (snapshot epoch, reason): a gang refused 128
        # times against ONE epoch classifies once — the counter still
        # counts every refusal, with the memoized reason
        self._classified_at: dict[str, tuple[tuple[int, int], str]] = {}
        # cluster-wide repack-recoverable chips at the last
        # classification/sample (the stranded ledger's headline)
        self._recoverable_last = 0
        # fleet size at the last sample (the stranded-ratio
        # recording rule's denominator)
        self.fleet_chips = 0

    # -- flight recorder -----------------------------------------------------
    def maybe_sample(self) -> None:
        """Amortized per-decision hook (the extender calls it where it
        checkpoints): a clock read per decision, a real sample only
        when the scheduling-clock interval elapsed — FakeClock drives
        compress wall-free."""
        now = self._ext.clock.monotonic()
        last = self._last_sample
        if last is not None and now - last < self._interval:
            return
        self._last_sample = now
        self.sample_now(now)

    def sample_now(self, now: Optional[float] = None) -> dict:
        """Take one fleet sample (also the test/CLI forced-sample
        seam). Reads the observer snapshot view only."""
        t0 = time.perf_counter()
        ext = self._ext
        if now is None:
            now = ext.clock.monotonic()
        snap = ext.snapshots.observe()
        slices: dict[str, dict[str, Any]] = {}
        chips = free = bfree = 0
        used_shares = total_shares = 0
        unhealthy = terminating = 0
        for sid in sorted(snap.slices):
            ss = snap.slices[sid]
            # the snapshot-memoized pair (shared with /metrics gauges
            # and the shard capacity exchange) — one box sweep per
            # slice per epoch fleet-wide, not one per sample
            slices[sid] = {
                "utilization": round(ss.utilization, 4),
                "fragmentation": round(ss.fragmentation(), 4),
                "largest_free_box": ss.largest_free_box(),
                "free_chips": ss.free_chips,
                "blocked_free_chips": ss.blocked_free_chips,
                "unhealthy": len(ss.unhealthy),
                "terminating": len(ss.terminating),
            }
            chips += ss.mesh.num_chips
            free += ss.free_chips
            bfree += ss.blocked_free_chips
            used_shares += ss.used_shares
            total_shares += ss.total_shares
            unhealthy += len(ss.unhealthy)
            terminating += len(ss.terminating)
        cycle = getattr(ext, "cycle", None)
        queue: dict[str, Any] = {"depth": 0, "oldest_age_s": None}
        if cycle is not None:
            queue = {
                "depth": cycle.queue_depth(),
                "oldest_age_s": cycle.pending_oldest_age(now),
            }
        tenants = getattr(ext, "tenants", None)
        tenant_doc: Optional[dict[str, Any]] = None
        if tenants is not None:
            usage = tenants.ledger.usage()
            tenant_doc = {
                "dominant_share": {
                    t: round(usage.dominant_share(t), 4)
                    for t in sorted(tenants.known_tenants())
                },
                "shedding": bool(tenants.burn.last_page_burning()),
            }
        with self._lock:
            self._expire_stranded_locked(now)
            stranded = self._stranded_rollup_locked()
        sample: dict[str, Any] = {
            # wall ts orders cross-replica merges; the scheduling-clock
            # instant is what --since windows and tests reason about
            "ts": time.time(),
            "clock": round(now, 6),
            "fleet": {
                "chips": chips,
                "free_chips": free,
                "blocked_free_chips": bfree,
                "utilization": (
                    round(used_shares / total_shares, 4)
                    if total_shares else 0.0
                ),
                "unhealthy": unhealthy,
                "terminating": terminating,
            },
            "slices": slices,
            "queue": queue,
            "tenants": tenant_doc,
            "stranded": stranded,
        }
        self.fleet_chips = chips
        self.ring.append(sample)
        if self.sink is not None:
            self.sink.write(json.dumps(sample, sort_keys=True) + "\n")
        self.samples_taken += 1
        self.sample_seconds += time.perf_counter() - t0
        return sample

    def samples(self, since: Optional[float] = None) -> list[dict]:
        """Ring contents, oldest first, optionally clipped to samples
        at/after ``since`` (epoch seconds — the CLI resolves relative
        windows before asking)."""
        out = list(self.ring)
        if since is not None:
            out = [s for s in out if float(s.get("ts", 0.0)) >= since]
        return out

    # -- stranded-demand forensics -------------------------------------------
    def note_failed_plan(self, pod, error: Optional[str] = None) -> None:
        """Root-cause one failed/deferred plan. Called from the batch
        planner's plan-store seam and the legacy filter's refusal seam;
        must stay cheap — the geometric probe memoizes per (demand,
        snapshot epoch), and every wall spent lands in
        ``sample_seconds`` (the measured-overhead guard)."""
        t0 = time.perf_counter()
        try:
            demand = self._demand_of(pod)
            if demand is None:
                return
            key, total, shape, dcn, cpp = demand
            epoch = self._ext.snapshots.epoch_key()
            # the memo is shared with _expire_stranded_locked (which
            # pops entries from another thread's sample tick): read and
            # write under the lock; the expensive _classify probe stays
            # OUTSIDE it (lock-discipline: no heavy work under _lock)
            with self._lock:
                memo = self._classified_at.get(key)
            if memo is not None and memo[0] == epoch:
                reason, detail = memo[1], None
            else:
                reason, detail = self._classify(total, shape, dcn, cpp,
                                                error)
                with self._lock:
                    self._classified_at[key] = (epoch, reason)
                self.classified += 1
            self._unschedulable[reason] = \
                self._unschedulable.get(reason, 0) + 1
            now = self._ext.clock.monotonic()
            label = ("x".join(str(d) for d in shape) if shape
                     else str(total))
            with self._lock:
                rec = self._stranded.setdefault(label, {}).setdefault(
                    key, {})
                rec.update({
                    "demand": key,
                    "pod": pod.key(),
                    "chips": total,
                    "reason": reason,
                    "ts": now,
                })
                if detail:
                    rec.update(detail)
                self._expire_stranded_locked(now)
            if detail and "recoverable_chips" in detail:
                self._recoverable_last = detail["recoverable_chips"]
            ext = self._ext
            if ext.decisions is not None:
                ext._note_decision(
                    pod.key(), "stranded", reason=reason, chips=total,
                    shape=(list(shape) if shape else None),
                    **(detail or {}),
                )
        finally:
            self.sample_seconds += time.perf_counter() - t0

    def note_refusal(self, pod, error: str) -> None:
        """The legacy (non-batch) refusal seam: a filter exception is a
        failed plan with a reason string."""
        self.note_failed_plan(pod, error=error)

    def _demand_of(self, pod):
        """(demand key, chips, shape, dcn-allowed, chips/pod) for a
        failed pod, or None for non-TPU asks (nothing geometric to
        strand). Gang members collapse onto one demand so a 128-member
        refusal storm is one ledger row."""
        from tpukube.core.types import RESOURCE_TPU
        from tpukube.sched.extender import Extender, ExtenderError

        try:
            ask = Extender.device_request(pod)
        except ExtenderError:
            return None
        if ask is None or ask[0] != RESOURCE_TPU:
            return None
        count = ask[1]
        if pod.group is not None:
            return (
                f"gang:{pod.namespace}/{pod.group.name}",
                pod.group.min_member * count,
                pod.group.shape,
                bool(pod.group.allow_dcn),
                count,
            )
        return (pod.key(), count, None, False, count)

    def _classify(self, total: int, shape, dcn: bool, cpp: int,
                  error: Optional[str]):
        """(reason, detail) for one unschedulable demand. String-routed
        tenancy refusals first (their reason is authoritative — the
        plane refused, geometry did not); everything else re-probes the
        observer snapshot with the real sweep primitives."""
        if error:
            if "quota" in error:
                return "quota", None
            if "admission shed" in error:
                return "shed", None
        snap = self._ext.snapshots.observe()
        rows = sorted(snap.slices.items())
        bfree = sum(ss.blocked_free_chips for _, ss in rows)
        detail: dict[str, Any] = {"free_chips": bfree}
        if bfree < total:
            healed = sum(_healed_free(ss) for _, ss in rows)
            if healed >= total:
                detail["healed_free_chips"] = healed
                return "unhealthy", detail
            dsid = self._fits_if_uncordoned(rows, total, shape)
            if dsid is not None:
                detail["fits_if_uncordoned"] = dsid
                return "draining", detail
            return "capacity", detail
        candidates = [(sid, ss) for sid, ss in rows
                      if ss.blocked_free_chips >= total]
        for sid, ss in candidates:
            coords = slicefit.find_slice_in(
                ss.blocked_sweep(),
                count=None if shape is not None else total,
                shape=shape,
                broken=ss.broken,
            )
            if coords is not None:
                detail["fits_in"] = sid
                return "transient", detail
        dsid = self._fits_if_uncordoned(rows, total, shape)
        if dsid is not None:
            # the demand fits once the drain gives the chips back (or
            # is cancelled) — stranded by elasticity, not by geometry
            detail["fits_if_uncordoned"] = dsid
            return "draining", detail
        boxes = {sid: slicefit.largest_free_box_in(ss.blocked_sweep())
                 for sid, ss in rows}
        detail["largest_free_box"] = max(boxes.values(), default=0)
        recoverable = sum(
            max(0, ss.blocked_free_chips - boxes[sid])
            for sid, ss in rows
        )
        detail["recoverable_chips"] = recoverable
        self._recoverable_last = recoverable
        if not candidates:
            # enough chips fleet-wide but no single slice holds them:
            # only DCN spanning could serve this demand
            if dcn and shape is None:
                if self._dcn_covers(rows, total, cpp, boxes):
                    return "transient", detail
                return "fragmented", detail
            return "dcn-ineligible", detail
        return "fragmented", detail

    @staticmethod
    def _fits_if_uncordoned(rows, total: int, shape):
        """The drain counterfactual (ISSUE 19): the slice id where this
        demand would fit if no chip were cordoned, else None. Probed
        only when a placement failed AND some slice is mid-drain — the
        operator's remedy is waiting out (or cancelling) the drain, not
        adding capacity or defragmenting, and the taxonomy must say
        so. The pre-filter skips slices whose UNCORDONED occupancy
        already exceeds the demand (the probe could never fit)."""
        for sid, ss in rows:
            if not ss.cordoned:
                continue
            if ss.mesh.num_chips - len(
                    ss.occupied | ss.reserved | ss.absent) < total:
                continue
            coords = slicefit.find_slice_in(
                ss.uncordoned_sweep(),
                count=None if shape is not None else total,
                shape=shape,
                broken=ss.broken,
            )
            if coords is not None:
                return sid
        return None

    @staticmethod
    def _dcn_covers(rows, total: int, cpp: int, boxes) -> bool:
        """Read-only mirror of the gang layer's greedy DCN split (one
        contiguous sub-box per slice, each a chips/pod multiple),
        conservative: only each slice's LARGEST box is offered."""
        cpp = max(1, cpp)
        remaining = total
        for sid, ss in sorted(rows, key=lambda kv:
                              -kv[1].blocked_free_chips):
            vol = min(remaining, (boxes[sid] // cpp) * cpp)
            remaining -= vol
            if remaining <= 0:
                return True
        return remaining <= 0

    def _expire_stranded_locked(self, now: float) -> None:
        """Retire ledger entries whose demand left the queue (batching
        on: the first-admit stamps are the liveness oracle) or went
        TTL-stale (no batch queue to consult) — a stranded row must
        never outlive the demand it names."""
        cycle = getattr(self._ext, "cycle", None)
        for label in list(self._stranded):
            demands = self._stranded[label]
            for key in list(demands):
                rec = demands[key]
                dead = now - rec["ts"] > STRANDED_TTL_SECONDS
                if not dead and cycle is not None:
                    dead = not cycle.is_pending(rec["pod"])
                if dead:
                    del demands[key]
                    self._classified_at.pop(key, None)
            if not demands:
                del self._stranded[label]

    def _stranded_rollup_locked(self) -> dict[str, Any]:
        by_shape = []
        for label in sorted(self._stranded):
            demands = list(self._stranded[label].values())
            reasons: dict[str, int] = {}
            for rec in demands:
                reasons[rec["reason"]] = reasons.get(rec["reason"], 0) + 1
            by_shape.append({
                "shape": label,
                "demands": len(demands),
                "chips_requested": sum(r["chips"] for r in demands),
                "reasons": reasons,
            })
        return {
            "demands": sum(r["demands"] for r in by_shape),
            "chips_requested": sum(r["chips_requested"]
                                   for r in by_shape),
            "recoverable_chips": self._recoverable_last,
            "by_shape": by_shape,
        }

    def stranded_summary(self) -> dict[str, Any]:
        """The /statusz stranded ledger ("3×64-chip gangs stranded by
        fragmentation, 412 chips recoverable by repack")."""
        now = self._ext.clock.monotonic()
        with self._lock:
            self._expire_stranded_locked(now)
            return self._stranded_rollup_locked()

    def stranded_by_reason(self) -> dict[str, tuple[int, int]]:
        """Live stranded ledger rolled up by root cause:
        reason -> (demands, chips_requested). The per-reason gauges
        (and the fragmentation ticket alert) read this."""
        now = self._ext.clock.monotonic()
        out: dict[str, tuple[int, int]] = {}
        with self._lock:
            self._expire_stranded_locked(now)
            for demands in self._stranded.values():
                for rec in demands.values():
                    d, c = out.get(rec["reason"], (0, 0))
                    out[rec["reason"]] = (d + 1, c + rec["chips"])
        return out

    def unschedulable_counts(self) -> dict[str, int]:
        """Cumulative failed-plan classifications by reason (the
        ``tpukube_unschedulable_pods{reason}`` source)."""
        return dict(self._unschedulable)

    # -- what-if probes ------------------------------------------------------
    def probe(self, count: Optional[int] = None,
              shape: Optional[tuple[int, int, int]] = None,
              chips_per_pod: int = 1) -> dict[str, Any]:
        """Read-only fit dry-run against the current observer snapshot:
        the real vectorized sweep per slice, plus the greedy DCN-split
        fallback — the answer a defragmenter/autoscaler acts on."""
        if (count is None) == (shape is None):
            raise ValueError("probe wants exactly one of count/shape")
        total = count if count is not None \
            else shape[0] * shape[1] * shape[2]
        if total < 1:
            raise ValueError("probe wants a positive chip count")
        snap = self._ext.snapshots.observe()
        rows = sorted(snap.slices.items())
        slices: dict[str, dict[str, Any]] = {}
        boxes: dict[str, int] = {}
        fits_in: Optional[str] = None
        for sid, ss in rows:
            box = slicefit.largest_free_box_in(ss.blocked_sweep())
            boxes[sid] = box
            fit = slicefit.find_slice_in(
                ss.blocked_sweep(), count=count, shape=shape,
                broken=ss.broken,
            ) is not None
            slices[sid] = {
                "blocked_free_chips": ss.blocked_free_chips,
                "largest_free_box": box,
                "fits": fit,
            }
            if fit and fits_in is None:
                fits_in = sid
        # the DCN fallback dry-run (count asks only — a shape ask is a
        # single-slice contract, exactly as the gang layer treats it)
        dcn: dict[str, Any] = {"fits": False, "parts": {}}
        if shape is None:
            cpp = max(1, chips_per_pod)
            remaining = total
            parts: dict[str, int] = {}
            for sid, ss in sorted(rows, key=lambda kv:
                                  -kv[1].blocked_free_chips):
                vol = min(remaining, (boxes[sid] // cpp) * cpp)
                if vol > 0:
                    parts[sid] = vol
                    remaining -= vol
                if remaining <= 0:
                    break
            if remaining <= 0:
                dcn = {"fits": True, "parts": parts}
        return {
            "requested": {
                "count": count,
                "shape": list(shape) if shape else None,
                "chips": total,
            },
            "free_chips": sum(ss.blocked_free_chips for _, ss in rows),
            "largest_free_box": max(boxes.values(), default=0),
            "fits": fits_in is not None,
            "slice": fits_in,
            "slices": slices,
            "dcn": dcn,
            "epoch": list(snap.key),
        }

    # -- documents -----------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "enabled": True,
            "samples": self.samples_taken,
            "sample_seconds": round(self.sample_seconds, 6),
            "ring": len(self.ring),
            "ring_capacity": self.ring_capacity,
            "interval_seconds": self._interval,
            "classified": self.classified,
            "unschedulable": self.unschedulable_counts(),
        }
        if self.sink is not None:
            bytes_, rotations = self.sink.stats()
            out["sink"] = {"path": self.sink.path, "bytes": bytes_,
                           "rotations": rotations}
        return out

    def capacity_doc(self, since: Optional[float] = None) -> dict[str, Any]:
        """The /capacity answer: ring samples + forensics rollup +
        recorder stats in one JSON document."""
        return {
            "samples": self.samples(since),
            "stranded": self.stranded_summary(),
            "unschedulable": self.unschedulable_counts(),
            "stats": self.stats(),
        }

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()


# -- federation & rendering (shared by the router and the CLI) ---------------
def merge_capacity_docs(per_replica: list[tuple[str, Optional[dict]]],
                        ) -> dict[str, Any]:
    """Stitch per-replica /capacity documents into one fleet view.
    Samples are replica-stamped and ordered by (wall ts, replica) —
    the ``events --merge`` idiom; stranded rows and unschedulable
    counts aggregate with per-replica attribution kept. A replica with
    no document (dead, unreachable, or capacity-off) lands in
    ``dead_replicas`` so a merged answer can degrade loudly but never
    serve a stale fleet picture as fresh."""
    samples: list[dict] = []
    by_shape: dict[str, dict[str, Any]] = {}
    unschedulable: dict[str, int] = {}
    stats: dict[str, Any] = {}
    dead: list[str] = []
    recoverable = 0
    for name, doc in per_replica:
        if doc is None:
            dead.append(name)
            continue
        for s in doc.get("samples", ()):
            s = dict(s)
            s.setdefault("replica", name)
            samples.append(s)
        stranded = doc.get("stranded") or {}
        recoverable += int(stranded.get("recoverable_chips", 0))
        for row in stranded.get("by_shape", ()):
            agg = by_shape.setdefault(row["shape"], {
                "shape": row["shape"], "demands": 0,
                "chips_requested": 0, "reasons": {},
                "replicas": {},
            })
            agg["demands"] += row["demands"]
            agg["chips_requested"] += row["chips_requested"]
            for reason, n in (row.get("reasons") or {}).items():
                agg["reasons"][reason] = \
                    agg["reasons"].get(reason, 0) + n
            agg["replicas"][name] = row["demands"]
        for reason, n in (doc.get("unschedulable") or {}).items():
            unschedulable[reason] = unschedulable.get(reason, 0) + n
        stats[name] = doc.get("stats")
    samples.sort(key=lambda s: (float(s.get("ts", 0.0)),
                                str(s.get("replica", ""))))
    shapes = [by_shape[k] for k in sorted(by_shape)]
    return {
        "samples": samples,
        "stranded": {
            "demands": sum(r["demands"] for r in shapes),
            "chips_requested": sum(r["chips_requested"] for r in shapes),
            "recoverable_chips": recoverable,
            "by_shape": shapes,
        },
        "unschedulable": unschedulable,
        "stats": stats,
        "dead_replicas": sorted(dead),
    }


def merge_probe_docs(per_replica: list[tuple[str, Optional[dict]]],
                     requested: dict[str, Any]) -> dict[str, Any]:
    """Stitch per-replica /capacity/probe answers: the demand fits if
    any replica fits it whole; the DCN fallback composes each replica's
    largest offered parts. Dead replicas are named — a probe answer
    missing a shard's view must say so."""
    slices: dict[str, dict[str, Any]] = {}
    fits_in: Optional[tuple[str, str]] = None
    dead: list[str] = []
    free = 0
    largest = 0
    parts: dict[str, int] = {}
    total = int(requested.get("chips") or 0)
    for name, doc in per_replica:
        if doc is None:
            dead.append(name)
            continue
        free += int(doc.get("free_chips", 0))
        largest = max(largest, int(doc.get("largest_free_box", 0)))
        if doc.get("fits") and fits_in is None:
            fits_in = (name, doc.get("slice"))
        for sid, row in (doc.get("slices") or {}).items():
            slices[sid] = {**row, "replica": name}
        for sid, vol in ((doc.get("dcn") or {}).get("parts")
                         or {}).items():
            parts[sid] = vol
    dcn_fits = sum(parts.values()) >= total > 0
    return {
        "requested": requested,
        "free_chips": free,
        "largest_free_box": largest,
        "fits": fits_in is not None,
        "slice": fits_in[1] if fits_in else None,
        "replica": fits_in[0] if fits_in else None,
        "slices": slices,
        "dcn": {"fits": dcn_fits,
                "parts": parts if dcn_fits else {}},
        "dead_replicas": sorted(dead),
    }


def _spark(values: list[float], lo: float = 0.0,
           hi: float = 1.0) -> str:
    span = max(hi - lo, 1e-9)
    out = []
    for v in values:
        idx = int((min(max(v, lo), hi) - lo) / span
                  * (len(_SPARK) - 1))
        out.append(_SPARK[idx])
    return "".join(out)


def format_capacity(doc: dict[str, Any], fmt: str = "sparkline") -> str:
    """Render a /capacity document (solo or merged) for the terminal:
    ``sparkline`` (utilization + queue trends, stranded ledger lines),
    ``csv`` (one row per sample), or ``json`` (verbatim)."""
    if fmt == "json":
        return json.dumps(doc, indent=2, sort_keys=True)
    samples = doc.get("samples") or []
    if fmt == "csv":
        lines = ["ts,replica,utilization,free_chips,blocked_free_chips,"
                 "largest_free_box,queue_depth,queue_oldest_age_s,"
                 "stranded_chips"]
        for s in samples:
            fleet = s.get("fleet") or {}
            queue = s.get("queue") or {}
            stranded = s.get("stranded") or {}
            largest = max(
                (row.get("largest_free_box", 0)
                 for row in (s.get("slices") or {}).values()),
                default=0,
            )
            lines.append(",".join(str(x) for x in (
                s.get("ts"), s.get("replica", ""),
                fleet.get("utilization"), fleet.get("free_chips"),
                fleet.get("blocked_free_chips"), largest,
                queue.get("depth"), queue.get("oldest_age_s"),
                stranded.get("chips_requested", 0),
            )))
        return "\n".join(lines)
    # sparkline (default)
    lines: list[str] = []
    utils = [float((s.get("fleet") or {}).get("utilization") or 0.0)
             for s in samples]
    if utils:
        depth = [float((s.get("queue") or {}).get("depth") or 0)
                 for s in samples]
        lines.append(
            f"utilization  {_spark(utils)}  "
            f"(last {utils[-1]:.1%} over {len(utils)} samples)"
        )
        lines.append(
            f"queue depth  {_spark(depth, 0.0, max(max(depth), 1.0))}  "
            f"(last {int(depth[-1])})"
        )
    else:
        lines.append("no samples recorded")
    stranded = doc.get("stranded") or {}
    for row in stranded.get("by_shape", ()):
        reasons = ", ".join(
            f"{n}x {reason}"
            for reason, n in sorted((row.get("reasons") or {}).items())
        )
        line = (f"stranded: {row['demands']}x {row['shape']}-chip "
                f"demand(s) ({reasons}) — "
                f"{row['chips_requested']} chips requested")
        reps = row.get("replicas")
        if reps:
            line += " [" + ", ".join(
                f"{r}: {n}" for r, n in sorted(reps.items())) + "]"
        lines.append(line)
    if stranded.get("demands"):
        lines.append(
            f"{stranded.get('recoverable_chips', 0)} chips "
            f"recoverable by repack"
        )
    counts = doc.get("unschedulable") or {}
    if counts:
        lines.append("unschedulable plans: " + ", ".join(
            f"{reason}={n}" for reason, n in sorted(counts.items())))
    dead = doc.get("dead_replicas")
    if dead:
        lines.append(
            "WARNING: no capacity answer from replica(s) "
            + ", ".join(dead) + " — fleet view is partial"
        )
    return "\n".join(lines)

"""/statusz JSON introspection documents.

/metrics answers "how much"; /statusz answers "what, exactly, right
now": the ledger/reservation summary, the pending-eviction queue with
per-key ages, watch liveness as a LAST-EVENT TIMESTAMP (a live thread in
reconnect backoff is not a live stream — ADVICE round 5), trace-ring
stats, and the node agent's inventory source. Served by the extender's
aiohttp app and the node agent's MetricsServer.
"""

from __future__ import annotations

import time
from typing import Any, Optional


def device_health_counts(device) -> tuple[int, int]:
    """(healthy, unhealthy) over a device manager's current device list —
    the ONE classification both /metrics and /statusz report (a second
    copy would let the two disagree the day the health enum grows)."""
    healthy = unhealthy = 0
    for _, h in device.device_list():
        if h.value == "Healthy":
            healthy += 1
        else:
            unhealthy += 1
    return healthy, unhealthy


def watch_status(loop) -> dict[str, Any]:
    """One watch/poll loop's liveness document. ``loop`` is any
    apiserver._WatchLoop (or a loop hosted by a PodInformer); None means
    the daemon runs without that loop (sim/dev)."""
    if loop is None:
        return {"configured": False}
    status = getattr(loop, "watch_status", None)
    if status is not None:
        return {"configured": True, **status()}
    return {"configured": True, "name": getattr(loop, "_name", "?")}


def fleet_health(extender) -> dict[str, Any]:
    """Fleet health rolled up per ICI slice: healthy / degraded /
    unhealthy chips (from the node agents' health-summary annotations,
    falling back to the topology annotation's chip health for agents
    that predate the summary) plus the terminating-victim chip count —
    healthy hardware a dying container still physically owns, the third
    state an operator sizing spare capacity must see. ``degraded``
    means the chip is up but touches a downed ICI link
    (codec.chip_health_states — the ONE classification the sampler,
    the annotation, and this rollup share)."""
    from tpukube.core import codec

    state, gang = extender.state, extender.gang
    slices: dict[str, dict[str, Any]] = {}
    for sid in state.slice_ids():
        slices[sid] = {
            "nodes": 0,
            "chips": 0,
            "healthy": 0,
            "degraded": 0,
            "unhealthy": 0,
            # separate dimension, not a fourth chip state: terminating
            # victims' chips are healthy but unplaceable until confirmed
            "terminating": len(gang.terminating_coords(sid)),
            "links_down": len(state.broken_links(sid)),
        }
    for name in state.node_names():
        view = state.node(name)
        if view is None:
            continue
        s = slices.get(view.info.slice_id)
        if s is None:
            continue
        s["nodes"] += 1
        s["chips"] += len(view.info.chips)
        summary = view.health_summary
        if summary is not None:
            for key in ("healthy", "degraded", "unhealthy"):
                s[key] += int(summary.get(key, 0))
        else:
            for st in codec.chip_health_states(view.info).values():
                s[st] += 1
    totals = {
        k: sum(s[k] for s in slices.values())
        for k in ("nodes", "chips", "healthy", "degraded", "unhealthy",
                  "terminating", "links_down")
    }
    return {
        "slices": slices,
        "total": totals,
        "degraded_slices": sorted(
            sid for sid, s in slices.items()
            if s["degraded"] or s["unhealthy"] or s["terminating"]
            or s["links_down"]
        ),
    }


def extender_statusz(
    extender, evictions=None, informer=None, node_refresh=None,
    lifecycle=None, reconcile=None,
) -> dict[str, Any]:
    """The extender daemon's introspection document (served on /statusz
    behind the same auth as /state — it discloses placement)."""
    state = extender.state
    gangs = extender.gang.snapshot()
    now = time.monotonic()
    if evictions is not None:
        pending = evictions.pending_snapshot(now=now)
        oldest = evictions.oldest_age_seconds(now=now)
    else:
        # no executor (sim/dev): the raw queue, ages unknown
        pending = [
            {"pod": k, "state": "queued", "age_seconds": None}
            for k in list(extender.pending_evictions)
        ]
        oldest = None
    out: dict[str, Any] = {
        "component": "extender",
        "time": time.time(),
        "ledger": {
            "nodes": len(state.node_names()),
            "allocations": len(state.allocations()),
            "utilization_percent": round(100.0 * state.utilization(), 2),
        },
        "gangs": {
            "reservations": len(gangs),
            "committed": sum(1 for r in gangs if r.committed),
            "assembling": sum(1 for r in gangs if not r.committed),
            "victims_terminating": extender.gang.terminating_count(),
        },
        "pending_evictions": {
            "depth": len(pending),
            "oldest_age_seconds": oldest,
            "entries": pending,
        },
        # the pod stream feeding lifecycle releases + alloc reconciles:
        # liveness means a CONNECTED stream with a last-event timestamp,
        # not merely a live thread (reconnect backoff windows miss
        # DELETED events silently)
        "pod_watch": watch_status(informer if informer is not None
                                  else lifecycle),
        "node_watch": watch_status(node_refresh),
        "trace": (extender.trace.stats() if extender.trace is not None
                  else {"enabled": False}),
        "fleet": fleet_health(extender),
        # the epoch-cached scheduling snapshot (sched/snapshot.py):
        # cache counters + per-slice fragmentation / largest-free-box —
        # a hit_rate near zero under webhook load means every cycle is
        # rebuilding (a mutation storm, or an epoch bump on a read path)
        "snapshot": extender.snapshots.stats(),
        # bulk cold-start ingestion (ISSUE 15): batch counters, the
        # decode-cache hit rate, and the lazy backlog still awaiting
        # materialization (the background warmer's queue)
        "ingest": ({"enabled": True, **state.ingest_stats()}
                   if getattr(extender, "bulk_ingest", False)
                   else {"enabled": False}),
        # generation-based incremental resync (ISSUE 15): full vs
        # incremental lifecycle reads and the wire-shape bytes moved
        "resync": ({"enabled": True, **lifecycle.resync_stats()}
                   if lifecycle is not None
                   and getattr(extender, "resync_incremental", False)
                   and hasattr(lifecycle, "resync_stats")
                   else {"enabled": False}),
        # durable-state journal (sched/journal.py): WAL position,
        # checkpoint cadence, and the last recovery's stats — a
        # last_recovery in cold-fallback mode means the journal could
        # not produce a trustworthy base and the O(fleet) rebuild ran
        "journal": (extender.journal.stats()
                    if getattr(extender, "journal", None) is not None
                    else {"enabled": False}),
        # batched scheduling cycles (sched/cycle.py): queue depth,
        # batch sizes, and the plan-hit ratio — near zero with batching
        # on means webhooks are re-planning instead of reading plans
        "cycle": (extender.cycle.stats()
                  if getattr(extender, "cycle", None) is not None
                  else {"enabled": False}),
        # multi-tenant serving plane (tpukube/tenancy): per-tenant
        # usage/share/quota, shed and denial counters, and the SLO
        # burn monitor feeding the shedding decision
        "tenants": (extender.tenants.stats()
                    if getattr(extender, "tenants", None) is not None
                    else {"enabled": False}),
        # decision provenance (obs/decisions.py): ring occupancy,
        # sample rate, and the measured record overhead — the data
        # behind /explain and `tpukube-obs explain`
        "decisions": (extender.decisions.stats()
                      if getattr(extender, "decisions", None)
                      is not None else {"enabled": False}),
    }
    events = getattr(extender, "events", None)
    if events is not None:
        out["events"] = {
            **events.stats(),
            "by_reason": events.counts_by_reason(),
            "recent": events.events(limit=20),
        }
    else:
        out["events"] = {"enabled": False}
    # capacity analytics (obs/capacity.py, ISSUE 17): the key itself
    # is CONDITIONAL — off-is-off means the legacy /statusz document
    # stays byte-identical, like the lifecycle/reconcile keys
    capacity = getattr(extender, "capacity", None)
    if capacity is not None:
        out["capacity"] = {
            **capacity.stats(),
            "stranded": capacity.stranded_summary(),
        }
    # fleet elasticity (ISSUE 19): drain choreography + autoscaler
    # loop — both keys conditional like capacity's (off-is-off)
    drain = getattr(extender, "drain", None)
    if drain is not None:
        out["drain"] = drain.statusz()
    autoscaler = getattr(extender, "autoscaler", None)
    if autoscaler is not None:
        out["autoscaler"] = autoscaler.statusz()
    if lifecycle is not None:
        out["lifecycle_releases"] = lifecycle.released
    if reconcile is not None:
        out["reconciles"] = reconcile.reconciled
    return out


def router_statusz(router) -> dict[str, Any]:
    """The sharded control plane's /statusz document (ISSUE 13):
    slice→replica assignment, per-replica summary rows (liveness,
    nodes, allocs, queue depth, snapshot counters), and the two-phase
    rendezvous ledger. Each replica's FULL ``extender_statusz`` stays
    its own listener's document in a real deployment; this is the
    cross-shard rollup the router serves."""
    return {
        "time": time.time(),
        "sharded": True,
        **router.statusz(),
        "pending_evictions": len(router.pending_evictions),
        "rendezvous_counters": {
            "prepared": router.rendezvous_prepared,
            "committed": router.rendezvous_committed,
            "aborted": router.rendezvous_aborted,
        },
    }


def plugin_statusz(
    server, device=None, health=None, kubelet_watch=None, intent_watch=None,
    sampler=None, events=None,
) -> dict[str, Any]:
    """The node agent's introspection document (served by its
    MetricsServer on /statusz). ``sampler`` is the telemetry
    HealthSampler (per-chip states + rolling windows); ``events`` the
    node-local EventJournal."""
    dev = device if device is not None else server._device
    healthy, unhealthy = device_health_counts(dev)
    out: dict[str, Any] = {
        "component": "plugin",
        "time": time.time(),
        "resource": server.resource_name,
        "devices": {"healthy": healthy, "unhealthy": unhealthy},
        # table-fallback nodes run on static HBM/core guesses, not
        # runtime truth — the first thing to check on a weird node
        "inventory_source": dev.inventory_source(),
        "allocations": server.allocation_count,
        "divergences": server.divergences,
        "intents": {
            "depth": server.intents.depth(),
            "pending": sorted(server.intents.snapshot()),
        },
        "intent_watch": watch_status(intent_watch),
    }
    if health is not None:
        out["health_transitions"] = health.transitions
    if kubelet_watch is not None:
        out["kubelet_reregistrations"] = kubelet_watch.reregistrations
    if sampler is not None:
        out["telemetry"] = sampler.telemetry_status()
    if events is not None:
        out["events"] = {
            **events.stats(),
            "by_reason": events.counts_by_reason(),
            "recent": events.events(limit=20),
        }
    return out

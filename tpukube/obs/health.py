"""Per-chip fleet health telemetry (node-agent side).

The PR-1 observability layer made the control plane's DECISIONS visible;
this module makes the HARDWARE visible: a node-agent sampler loop reads
per-chip health/HBM/duty-cycle/ICI-link-error counters from the device
layer (``TpuDeviceManager.telemetry_snapshot``; the sim backend
synthesizes occupancy/duty, real backends report health and link errors
truthfully), tracks rolling windows, detects health-state transitions,
and emits ChipUnhealthy/ChipRecovered/LinkFault/LinkRecovered events
into the structured journal. The compact per-node summary
(``codec.health_summary``) rides the node annotation upstream so the
extender can roll up fleet health per ICI slice on its /statusz.

Chip states here are the three the fleet rollup uses: ``healthy``,
``degraded`` (chip up but touching a downed ICI link), ``unhealthy`` —
one classification, defined in ``codec.chip_health_states``, shared by
sampler, annotation, and rollup so they can never disagree.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Optional

from tpukube.core.types import Health, TopologyCoord

log = logging.getLogger("tpukube.obs.health")

STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_UNHEALTHY = "unhealthy"


@dataclass(frozen=True)
class ChipTelemetry:
    """One chip's sample: identity + instantaneous gauges + cumulative
    counters, as read from the device layer at one poll."""

    device_id: str
    index: int
    coord: TopologyCoord
    health: Health
    hbm_total_bytes: int
    hbm_used_bytes: int
    duty_cycle_percent: float
    ici_link_errors: int  # cumulative counter
    links_down: int  # downed ICI links touching this chip right now

    @property
    def state(self) -> str:
        if self.health is not Health.HEALTHY:
            return STATE_UNHEALTHY
        if self.links_down:
            return STATE_DEGRADED
        return STATE_HEALTHY


class HealthSampler:
    """Polls device telemetry, keeps rolling windows, detects
    transitions, emits journal events.

    Same deterministic-step shape as the other daemon loops
    (start/stop/check_once); ``check_once`` is what tests and the sim
    drive directly. The sampler is read by three consumers — the
    /metrics registry (pull callbacks over ``latest``/counters), the
    /statusz document (``telemetry_status``), and the node annotation
    (``codec.health_summary`` over ``device.node_info()``).
    """

    WINDOW = 32  # samples per chip kept for rolling stats

    def __init__(self, device, poll_seconds: Optional[float] = None,
                 journal=None, on_transition=None):
        self._device = device
        if poll_seconds is None:
            poll_seconds = device._config.health_poll_seconds
        self._poll = poll_seconds
        self._journal = journal
        # called (no args) after any state transition — the daemon hooks
        # its annotation rewrite here, same contract as HealthWatcher
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._latest: dict[str, ChipTelemetry] = {}
        self._states: dict[str, str] = {}
        # device id -> deque[(duty, hbm_used)] rolling window
        self._windows: dict[str, deque] = {}
        self._transition_counts: dict[str, int] = {}
        self.samples = 0       # polls taken (metrics/tests)
        self.transitions = 0   # chip-state flips observed

    # -- loop --------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("health sampler already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpukube-telemetry")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_once()
            except Exception:
                log.exception("telemetry poll failed")

    def _emit(self, reason: str, obj: str, message: str,
              warning: bool = True) -> None:
        if self._journal is None:
            return
        try:
            self._journal.emit(
                reason, obj=obj, message=message,
                type="Warning" if warning else "Normal",
                node=self._device.host,
            )
        except Exception:
            log.exception("event emit failed for %s %s", reason, obj)

    def check_once(self) -> bool:
        """One telemetry poll; True if any chip changed state."""
        samples = self._device.telemetry_snapshot()
        transitioned = False
        with self._lock:
            self.samples += 1
            for t in samples:
                self._latest[t.device_id] = t
                w = self._windows.get(t.device_id)
                if w is None:
                    w = self._windows[t.device_id] = deque(maxlen=self.WINDOW)
                w.append((t.duty_cycle_percent, t.hbm_used_bytes))
                prev = self._states.get(t.device_id)
                state = t.state
                if prev == state:
                    continue
                self._states[t.device_id] = state
                if prev is None:
                    continue  # first sighting is a baseline, not a flip
                transitioned = True
                self.transitions += 1
                self._transition_counts[t.device_id] = (
                    self._transition_counts.get(t.device_id, 0) + 1
                )
                obj = f"chip/{t.device_id}"
                if state == STATE_UNHEALTHY:
                    self._emit("ChipUnhealthy", obj,
                               f"chip at {tuple(t.coord)} went unhealthy")
                elif prev == STATE_UNHEALTHY:
                    self._emit("ChipRecovered", obj,
                               f"chip at {tuple(t.coord)} recovered",
                               warning=False)
                elif state == STATE_DEGRADED:
                    self._emit("LinkFault", obj,
                               f"{t.links_down} downed ICI link(s) at "
                               f"{tuple(t.coord)}")
                else:  # degraded -> healthy
                    self._emit("LinkRecovered", obj,
                               f"ICI links at {tuple(t.coord)} restored",
                               warning=False)
        if transitioned and self._on_transition is not None:
            try:
                self._on_transition()
            except Exception:
                log.exception("telemetry transition hook failed")
        return transitioned

    # -- read side ---------------------------------------------------------
    def latest(self) -> list[ChipTelemetry]:
        """Most recent sample per chip, index order — the /metrics pull
        surface."""
        with self._lock:
            return sorted(self._latest.values(), key=lambda t: t.index)

    def sample(self, device_id: str) -> Optional[ChipTelemetry]:
        """Most recent sample for one chip (the registry's pull
        callbacks close over this)."""
        with self._lock:
            return self._latest.get(device_id)

    def state_counts(self) -> dict[str, int]:
        with self._lock:
            out = {STATE_HEALTHY: 0, STATE_DEGRADED: 0, STATE_UNHEALTHY: 0}
            for s in self._states.values():
                out[s] = out.get(s, 0) + 1
            return out

    def transition_count(self, device_id: str) -> int:
        with self._lock:
            return self._transition_counts.get(device_id, 0)

    def telemetry_status(self) -> dict[str, Any]:
        """The node agent's /statusz telemetry section: per-chip state +
        latest sample + rolling-window means."""
        with self._lock:
            chips = []
            for did in sorted(self._latest, key=lambda d: self._latest[d].index):
                t = self._latest[did]
                w = self._windows.get(did) or ()
                n = len(w) or 1
                chips.append({
                    "device": did,
                    "coord": list(t.coord),
                    "state": self._states.get(did, STATE_HEALTHY),
                    "duty_cycle_percent": t.duty_cycle_percent,
                    "duty_cycle_avg_percent": round(
                        sum(d for d, _ in w) / n, 2),
                    "hbm_used_bytes": t.hbm_used_bytes,
                    "hbm_total_bytes": t.hbm_total_bytes,
                    "ici_link_errors": t.ici_link_errors,
                    "transitions": self._transition_counts.get(did, 0),
                })
            states = {STATE_HEALTHY: 0, STATE_DEGRADED: 0,
                      STATE_UNHEALTHY: 0}
            for s in self._states.values():
                states[s] = states.get(s, 0) + 1
            return {
                "samples": self.samples,
                "window": self.WINDOW,
                "transitions": self.transitions,
                "states": states,
                "chips": chips,
            }

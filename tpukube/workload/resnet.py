"""ResNet-style conv net in pure JAX (bfloat16, NHWC, MXU-shaped).

BASELINE config 2 schedules a "4-pod data-parallel ResNet-50" job; this
module is that workload made real — the conv counterpart of
:mod:`tpukube.workload.llama`. TPU-first choices:

- NHWC layout (the TPU conv layout; XLA tiles the C axis onto the MXU);
- bfloat16 compute, float32 params/accumulators;
- GroupNorm instead of BatchNorm: no cross-replica batch statistics, so
  pure data parallelism needs exactly one gradient psum per step — the
  same collective shape the reference's NCCL DP jobs produce, here
  inserted by GSPMD over the ICI ring the scheduler granted;
- static shapes everywhere; stages unroll in Python (a handful of blocks
  — XLA deduplicates the repeated block bodies at compile time).

No sharding in this file; :func:`make_dp_train_step` declares it with
PartitionSpecs (batch over 'dp', params replicated).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    width: int = 16          # stem channels; stages double it
    stage_blocks: tuple[int, ...] = (1, 1, 1)
    bottleneck: bool = False  # True => 1x1/3x3/1x1 blocks (ResNet-50 style)
    groups: int = 8           # GroupNorm groups (must divide widths)
    image_size: int = 32

    @staticmethod
    def resnet50(num_classes: int = 1000) -> "ResNetConfig":
        """The real flagship shape (for sizing; tests use tiny configs)."""
        return ResNetConfig(
            num_classes=num_classes, width=64,
            stage_blocks=(3, 4, 6, 3), bottleneck=True, groups=32,
            image_size=224,
        )

    def stage_width(self, stage: int) -> int:
        w = self.width * (2 ** stage)
        return w * 4 if self.bottleneck else w


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * (2.0 / fan_in) ** 0.5)


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x, scale, bias, groups, eps=1e-5):
    n, h, w, c = x.shape
    xf = x.astype(jnp.float32).reshape(n, h, w, groups, c // groups)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + eps)
    xf = xf.reshape(n, h, w, c)
    return (xf * scale + bias).astype(x.dtype)


def _block_params(key, cin, cout, cfg: ResNetConfig) -> dict:
    """One residual block's params. Basic: 3x3 -> 3x3. Bottleneck:
    1x1 (cout/4) -> 3x3 (cout/4) -> 1x1 (cout)."""
    keys = jax.random.split(key, 4)
    if cfg.bottleneck:
        mid = cout // 4
        convs = [
            _conv_init(keys[0], 1, 1, cin, mid),
            _conv_init(keys[1], 3, 3, mid, mid),
            _conv_init(keys[2], 1, 1, mid, cout),
        ]
    else:
        convs = [
            _conv_init(keys[0], 3, 3, cin, cout),
            _conv_init(keys[1], 3, 3, cout, cout),
        ]
    p = {
        "convs": convs,
        "norms": [
            (jnp.ones((w.shape[-1],), jnp.float32),
             jnp.zeros((w.shape[-1],), jnp.float32))
            for w in convs
        ],
    }
    if cin != cout:
        p["proj"] = _conv_init(keys[3], 1, 1, cin, cout)
    return p


def init_params(rng: jax.Array, cfg: ResNetConfig) -> dict:
    n_stages = len(cfg.stage_blocks)
    keys = jax.random.split(rng, 2 + n_stages)
    params: dict = {
        "stem": _conv_init(keys[0], 3, 3, 3, cfg.width),
        "stem_norm": (jnp.ones((cfg.width,), jnp.float32),
                      jnp.zeros((cfg.width,), jnp.float32)),
        "stages": [],
    }
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_blocks):
        cout = cfg.stage_width(s)
        bkeys = jax.random.split(keys[1 + s], n_blocks)
        blocks = []
        for b in range(n_blocks):
            blocks.append(_block_params(bkeys[b], cin, cout, cfg))
            cin = cout
        params["stages"].append(blocks)
    params["head"] = (
        jax.random.normal(keys[-1], (cin, cfg.num_classes), jnp.float32)
        * (cin ** -0.5)
    )
    return params


def _apply_block(x, p, cfg: ResNetConfig, stride: int):
    y = x
    n = len(p["convs"])
    for i, (w, (scale, bias)) in enumerate(zip(p["convs"], p["norms"])):
        y = _conv(y, w, stride=stride if i == 0 else 1)
        y = _group_norm(y, scale, bias, cfg.groups)
        if i < n - 1:
            y = jax.nn.relu(y)
    if "proj" in p:
        x = _conv(x, p["proj"], stride=stride)
    elif stride != 1:
        x = x[:, ::stride, ::stride, :]
    return jax.nn.relu(x + y)


def forward(params: dict, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images [N, H, W, 3] (any float dtype) -> logits [N, num_classes].
    Compute in bfloat16, logits in float32."""
    x = images.astype(jnp.bfloat16)
    x = _conv(x, params["stem"])
    x = _group_norm(x, *params["stem_norm"], cfg.groups)
    x = jax.nn.relu(x)
    for s, blocks in enumerate(params["stages"]):
        for b, p in enumerate(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            x = _apply_block(x, p, cfg, stride)
    x = x.mean(axis=(1, 2), dtype=jnp.float32)  # global average pool
    return x @ params["head"]


def loss_fn(params: dict, images: jax.Array, labels: jax.Array,
            cfg: ResNetConfig) -> jax.Array:
    logits = forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()


def make_dp_train_step(cfg: ResNetConfig, mesh, learning_rate: float = 1e-2):
    """Pure data-parallel SGD step over a mesh with a 'dp' axis.

    Batch shards over 'dp', params replicate; GSPMD inserts exactly the
    gradient psum the reference's NCCL allreduce DP jobs perform — config
    2's "no topology hint" scenario as a real jittable step.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicated = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P("dp"))

    @partial(jax.jit,
             in_shardings=(replicated, batch_sharded, batch_sharded),
             out_shardings=(replicated, None),
             donate_argnums=(0,))
    def step(params, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels, cfg)
        params = jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g, params, grads
        )
        return params, loss

    return step

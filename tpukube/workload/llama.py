"""Minimal Llama-style decoder in pure JAX (bfloat16, MXU-shaped).

This is the workload the BASELINE scenarios schedule (configs 4-5 name
"JAX Llama-3-8B/70B" jobs); the framework's job is placing it, and this
module's job is being a real, shardable training step to place. Design
choices are TPU-first:

- all FLOPs are einsums over static shapes (MXU-friendly, no dynamic
  control flow under jit);
- compute dtype is bfloat16 with float32 params/accumulators;
- GQA attention + RoPE + SwiGLU, the Llama-3 block structure;
- no sharding in this file: parallelism is expressed entirely via
  PartitionSpecs in :mod:`tpukube.workload.train`, so the same code runs
  single-chip or SPMD over a mesh (GSPMD inserts the collectives).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 512
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 256
    max_seq: int = 128
    rope_theta: float = 10_000.0
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        """The real flagship shape (for sizing; tests use tiny configs)."""
        return LlamaConfig(
            vocab=128_256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14_336, max_seq=8192, rope_theta=500_000.0,
        )


def init_params(rng: jax.Array, cfg: LlamaConfig) -> dict:
    """float32 param pytree; layers are stacked on a leading axis so the
    decoder is one lax.scan (one compiled block, layer-count-independent
    compile time)."""
    k_embed, k_layers, k_out = jax.random.split(rng, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(jnp.float32)

    L, D, H, KV, HD, F = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                          cfg.n_kv_heads, cfg.head_dim, cfg.d_ff)
    ks = jax.random.split(k_layers, 7)

    def stack(key, shape, fan_in):
        return dense(key, (L, *shape), fan_in)

    return {
        "embed": dense(k_embed, (cfg.vocab, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), jnp.float32),
            "wq": stack(ks[0], (D, H * HD), D),
            "wk": stack(ks[1], (D, KV * HD), D),
            "wv": stack(ks[2], (D, KV * HD), D),
            "wo": stack(ks[3], (H * HD, D), H * HD),
            "mlp_norm": jnp.ones((L, D), jnp.float32),
            "w_gate": stack(ks[4], (D, F), D),
            "w_up": stack(ks[5], (D, F), D),
            "w_down": stack(ks[6], (F, D), F),
        },
        "final_norm": jnp.ones((D,), jnp.float32),
        "unembed": dense(k_out, (D, cfg.vocab), D),
    }


def _rmsnorm(x: jax.Array, g: jax.Array, eps: float) -> jax.Array:
    # norm statistics in f32 regardless of compute dtype
    h = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) + eps)
    return (h * scale).astype(x.dtype) * g.astype(x.dtype)


def _rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over (B, S, N, HD)."""
    _, S, _, HD = x.shape
    half = HD // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(S, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _block(h: jax.Array, layer: dict, cfg: LlamaConfig) -> jax.Array:
    """One decoder block over activations (B, S, D) in bfloat16."""
    B, S, D = h.shape
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    x = _rmsnorm(h, layer["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dh->bsh", x, layer["wq"].astype(x.dtype))
    k = jnp.einsum("bsd,dh->bsh", x, layer["wk"].astype(x.dtype))
    v = jnp.einsum("bsd,dh->bsh", x, layer["wv"].astype(x.dtype))
    q = _rope(q.reshape(B, S, H, HD), cfg.rope_theta)
    k = _rope(k.reshape(B, S, KV, HD), cfg.rope_theta)
    v = v.reshape(B, S, KV, HD)
    # GQA: group query heads (g) over kv heads (k)
    q = q.reshape(B, S, KV, H // KV, HD)
    logits = jnp.einsum("bskgd,btkd->bkgst", q, k) * (HD ** -0.5)
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))
    logits = jnp.where(causal[None, None, None], logits, -1e9)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    ctx = ctx.reshape(B, S, H * HD)
    h = h + jnp.einsum("bsh,hd->bsd", ctx, layer["wo"].astype(x.dtype))

    x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
    gate = jnp.einsum("bsd,df->bsf", x, layer["w_gate"].astype(x.dtype))
    up = jnp.einsum("bsd,df->bsf", x, layer["w_up"].astype(x.dtype))
    h = h + jnp.einsum(
        "bsf,fd->bsd", jax.nn.silu(gate) * up, layer["w_down"].astype(x.dtype)
    )
    return h


def forward(params: dict, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab) float32."""
    h = params["embed"].astype(jnp.bfloat16)[tokens]

    def body(h, layer):
        return _block(h, layer, cfg), None

    h, _ = jax.lax.scan(body, h, params["layers"])
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    return jnp.einsum(
        "bsd,dv->bsv", h, params["unembed"].astype(h.dtype)
    ).astype(jnp.float32)


def loss_fn(params: dict, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Next-token cross-entropy (shifted), mean over all positions."""
    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)

"""Allocate-env → jax.sharding.Mesh bridge.

The node agent injects ``TPU_KUBE_CHIP_COORDS`` / ``TPU_KUBE_MESH_DIMS`` /
``TPU_HBM_LIMIT_BYTES`` at Allocate (tpukube.device.tpu, SURVEY.md §4.3) —
the TPU analog of the reference's NVIDIA_VISIBLE_DEVICES + /dev/nvidia*
injection. This module is the consumer side: parse that env and turn the
gang's ICI-contiguous box into a well-aligned logical (dp, tp) device mesh,
so the data-parallel axis and the tensor-parallel axis both ride ICI rings
rather than arbitrary device orderings.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

from tpukube.device.tpu import (
    ENV_GANG_NUM_SLICES,
    ENV_GANG_SLICE_INDEX,
    ENV_GANG_SLICES,
    ENV_HBM_LIMIT,
    ENV_KUBE_CHIP_COORDS,
    ENV_KUBE_DEVICE_IDS,
    ENV_KUBE_HOST,
    ENV_KUBE_MESH_DIMS,
    ENV_KUBE_SLICE,
    ENV_KUBE_TENANT,
    ENV_VISIBLE_DEVICES,
)


@dataclass(frozen=True)
class PodTpuEnv:
    """The Allocate contract as seen from inside the container."""

    visible_chips: tuple[int, ...]
    device_ids: tuple[str, ...]
    coords: tuple[tuple[int, int, int], ...]
    mesh_dims: tuple[int, int, int]
    host: str
    hbm_limit_bytes: int
    slice_id: str = ""
    # serving-plane tenant this allocation is accounted to ("" when the
    # cluster runs without tenancy) — whose HBM quota the MEM_FRACTION
    # limit enforces
    tenant: str = ""
    # DCN-spanning gang context (multislice DP): how many ICI slices the
    # gang covers and which one this pod is in. 1/0 for single-slice gangs.
    gang_num_slices: int = 1
    gang_slice_index: int = 0
    gang_slices: tuple[str, ...] = ()

    @property
    def spans_dcn(self) -> bool:
        return self.gang_num_slices > 1

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "PodTpuEnv":
        e = os.environ if env is None else env
        try:
            coords = tuple(
                tuple(int(v) for v in part.split(","))
                for part in e[ENV_KUBE_CHIP_COORDS].split(";")
            )
            gang_slices = tuple(
                s for s in e.get(ENV_GANG_SLICES, "").split(",") if s
            )
            return PodTpuEnv(
                visible_chips=tuple(
                    int(v) for v in e[ENV_VISIBLE_DEVICES].split(",")
                ),
                device_ids=tuple(e[ENV_KUBE_DEVICE_IDS].split(",")),
                coords=coords,  # type: ignore[arg-type]
                mesh_dims=tuple(int(v) for v in e[ENV_KUBE_MESH_DIMS].split(",")),  # type: ignore[arg-type]
                host=e.get(ENV_KUBE_HOST, ""),
                hbm_limit_bytes=int(e.get(ENV_HBM_LIMIT, "0")),
                slice_id=e.get(ENV_KUBE_SLICE, ""),
                tenant=e.get(ENV_KUBE_TENANT, ""),
                gang_num_slices=int(e.get(ENV_GANG_NUM_SLICES, "1")),
                gang_slice_index=int(e.get(ENV_GANG_SLICE_INDEX, "0")),
                gang_slices=gang_slices,
            )
        except KeyError as k:
            raise RuntimeError(
                f"not running under a tpukube allocation: missing env {k}"
            ) from k


def box_shape(coords: Sequence[tuple[int, int, int]]) -> tuple[int, int, int]:
    """Bounding-box shape of a coord set; raises if the set is not exactly a
    full axis-aligned box (the gang layer guarantees contiguity — this is the
    in-pod assertion of that guarantee)."""
    xs, ys, zs = ({c[a] for c in coords} for a in range(3))
    shape = (len(xs), len(ys), len(zs))
    n = shape[0] * shape[1] * shape[2]
    if n != len(set(coords)):
        raise ValueError(f"coords are not a full box: {sorted(coords)}")
    for vals in (xs, ys, zs):
        lo, hi = min(vals), max(vals)
        if hi - lo + 1 != len(vals):
            raise ValueError(f"coords are not contiguous: {sorted(coords)}")
    return shape


def mesh_axes_from_box(
    shape: tuple[int, int, int], tp: Optional[int] = None
) -> tuple[int, int]:
    """Map a physical box shape to logical (dp, tp) sizes.

    Policy: tp should be an ICI-ring-aligned physical axis so tensor-parallel
    collectives (the latency-critical ones) stay single-hop — pick the
    largest box axis as tp unless pinned; dp takes the rest. This is the
    "prefer sub-slices whose shape factors well" note of SURVEY.md §3 made
    executable.
    """
    n = shape[0] * shape[1] * shape[2]
    if tp is None:
        tp = max(shape)
    if tp <= 0 or n % tp:
        raise ValueError(f"tp={tp} does not divide {n} chips")
    return n // tp, tp


def build_mesh(devices, dp: int, tp: int):
    """Arrange ``devices`` (e.g. jax.devices()) into a Mesh('dp','tp').

    Import of jax is deferred so the control plane never pays for it.
    """
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(devs, ("dp", "tp"))


def build_multislice_mesh(devices, num_slices: int, dp: int, tp: int):
    """Arrange devices into a Mesh('dcn', 'dp', 'tp') for a DCN-spanning
    gang: the leading 'dcn' axis crosses slices (gradient-reduction only —
    shard ONLY the batch over it), 'dp'/'tp' ride ICI within a slice.
    Device order must be slice-major (gang_slice_index-major), which is
    what sorted TPU_KUBE_GANG_SLICES indices give."""
    import numpy as np
    from jax.sharding import Mesh

    n = num_slices * dp * tp
    devs = np.asarray(devices[:n]).reshape(num_slices, dp, tp)
    return Mesh(devs, ("dcn", "dp", "tp"))


def mesh_from_alloc_env(env: Optional[dict] = None, devices=None,
                        tp: Optional[int] = None):
    """One-call consumer: env → (Mesh, PodTpuEnv).

    In a real gang each pod contributes its local chips and the sizes come
    from the gang's box; under the sim/dryrun there is one process, so
    ``devices`` defaults to all of jax.devices().

    DCN-spanning gangs (TPU_KUBE_GANG_NUM_SLICES > 1) get a 3-axis
    Mesh('dcn', 'dp', 'tp'): shard ONLY the batch over 'dcn' (gradient
    reduction is the one collective that should cross slices). The device
    count must divide evenly across slices — per-slice parts of unequal
    size cannot form one regular mesh, so such jobs treat the extra
    chips as spare capacity or build their own mesh.
    """
    import jax

    pe = PodTpuEnv.from_env(env)
    devs = list(jax.devices()) if devices is None else list(devices)
    if pe.spans_dcn:
        n = len(devs)
        ns = pe.gang_num_slices
        if n % ns:
            raise ValueError(
                f"{n} devices do not divide over {ns} slices; a DCN mesh "
                f"needs equal per-slice device counts"
            )
        per = n // ns
        dp, tp_ = mesh_axes_from_box((per, 1, 1), tp)
        return build_multislice_mesh(devs, ns, dp, tp_), pe
    shape = box_shape(pe.coords)
    n = shape[0] * shape[1] * shape[2]
    if len(devs) < n:
        # dryrun case: fewer local devices than gang chips — fold onto what
        # exists. A caller-pinned tp is still honored (mesh_axes_from_box
        # raises if it cannot divide the device count — never silently swap
        # the requested layout for a different one).
        n = len(devs)
        dp, tp_ = mesh_axes_from_box((n, 1, 1), tp)
    else:
        dp, tp_ = mesh_axes_from_box(shape, tp)
    return build_mesh(devs, dp, tp_), pe

"""Reference JAX workload (the scheduled side of the framework).

The framework proper is control-plane (SURVEY.md §3 scope note): it places
pods and injects `TPU_KUBE_*` env at Allocate. This package is the other half
of that contract — a minimal Llama-style JAX training job that consumes the
injected env to build its `jax.sharding.Mesh`, proving the placement →
in-pod-parallelism handoff end to end (BASELINE north_star: "gang-scheduled
JAX pods land on a contiguous slice" whose shape the job then uses).
"""

from tpukube.workload.llama import LlamaConfig, init_params, forward, loss_fn
from tpukube.workload.meshenv import PodTpuEnv, mesh_axes_from_box, build_mesh
from tpukube.workload.train import make_train_step, param_specs, init_sharded

__all__ = [
    "LlamaConfig",
    "init_params",
    "forward",
    "loss_fn",
    "PodTpuEnv",
    "mesh_axes_from_box",
    "build_mesh",
    "make_train_step",
    "param_specs",
    "init_sharded",
]

"""SPMD training step over a ('dp', 'tp') mesh via GSPMD partitioning.

Parallelism is declared, not hand-written: params carry Megatron-style
PartitionSpecs (attention heads and MLP hidden sharded over 'tp', row-wise
outputs reduced by XLA-inserted psums), the batch is sharded over 'dp', and
sequence-parallel regions constrain the residual stream's sequence axis onto
'tp' so norm/elementwise work is sharded too (with XLA placing the
all-gather/reduce-scatter pair at region boundaries). This is the TPU-native
answer to the reference jobs' NCCL data-parallelism: same jobs, but the
collectives are XLA's over ICI, shaped by the slice the scheduler granted.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpukube.workload.llama import LlamaConfig, forward, init_params, loss_fn


def param_specs(cfg: LlamaConfig) -> dict:
    """PartitionSpec pytree mirroring init_params' structure.

    Column-parallel (shard output dim over tp): wq/wk/wv, w_gate/w_up, and
    the unembed. Row-parallel (shard input dim, psum the output): wo and
    w_down. Embedding shards vocab over tp (gather + psum is cheap at these
    widths). Norm gains replicate. Layer-stacked leaves keep a leading None
    for the scan axis.
    """
    col, row = P(None, None, "tp"), P(None, "tp", None)
    return {
        "embed": P("tp", None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": col, "wk": col, "wv": col, "wo": row,
            "mlp_norm": P(None, None),
            "w_gate": col, "w_up": col, "w_down": row,
        },
        "final_norm": P(None),
        "unembed": P(None, "tp"),
    }


def _shardings(mesh: Mesh, specs) -> dict:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def init_sharded(rng: jax.Array, cfg: LlamaConfig, mesh: Mesh) -> dict:
    """Initialize params already laid out per param_specs (no replicated
    staging copy — each device materializes only its shard)."""
    shardings = _shardings(mesh, param_specs(cfg))
    return jax.jit(init_params, static_argnums=1,
                   out_shardings=shardings)(rng, cfg)


def make_optimizer(lr: float = 3e-4) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lr))


def make_train_step(cfg: LlamaConfig, mesh: Mesh,
                    opt: Optional[optax.GradientTransformation] = None,
                    remat: bool = True, seq_parallel: bool = True,
                    donate: Optional[bool] = None):
    """Return (step, opt_init) where step(params, opt_state, tokens) ->
    (params, opt_state, loss) is jitted over the mesh.

    Works over both mesh shapes the framework places gangs for: a
    single-slice Mesh('dp','tp') and a DCN-spanning Mesh('dcn','dp','tp')
    (build_multislice_mesh) — with a 'dcn' axis present, the batch shards
    over ('dcn','dp') so the only cross-slice collective is the gradient
    reduction, exactly the multislice DP contract of the gang env.

    remat applies jax.checkpoint to the loss (per-layer rematerialization via
    the scan body), trading FLOPs for HBM — the standard TPU memory lever.

    donate controls params/opt-state buffer donation. Default: donate on
    every backend EXCEPT the forced-multi-device CPU platform, whose XLA
    runtime mis-aliases donated sharded buffers on repeated step calls
    ("Expected aliased input ... to have the same size" INTERNAL error);
    donation buys nothing on CPU anyway (host RAM, not HBM, and the CPU
    runtime copies defensively). On TPU the donation stays on — it is
    the difference between fitting and OOMing at the HBM boundary.
    """
    opt = opt or make_optimizer()
    pspecs = param_specs(cfg)
    param_sh = _shardings(mesh, pspecs)
    batch_axes = (("dcn", "dp") if "dcn" in mesh.axis_names else "dp")
    batch_sh = NamedSharding(mesh, P(batch_axes, None))

    def compute_loss(params, tokens):
        if seq_parallel:
            # Residual-stream sequence sharding: embed output constrained to
            # (dp, tp, None) so norms/elementwise run sequence-sharded; the
            # attention/MLP einsums pull it back to head/hidden sharding and
            # XLA places the boundary collectives.
            def sp_forward(p, t):
                h = p["embed"].astype(jnp.bfloat16)[t]
                h = jax.lax.with_sharding_constraint(
                    h, P(batch_axes, "tp", None)
                )
                from tpukube.workload.llama import _block, _rmsnorm

                def body(h, layer):
                    h = _block(h, layer, cfg)
                    return jax.lax.with_sharding_constraint(
                        h, P(batch_axes, "tp", None)
                    ), None

                h, _ = jax.lax.scan(body, h, p["layers"])
                h = _rmsnorm(h, p["final_norm"], cfg.norm_eps)
                return jnp.einsum(
                    "bsd,dv->bsv", h, p["unembed"].astype(h.dtype)
                ).astype(jnp.float32)

            logits = sp_forward(params, tokens[:, :-1])
            targets = tokens[:, 1:]
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            return -jnp.mean(ll)
        return loss_fn(params, tokens, cfg)

    if remat:
        compute_loss = jax.checkpoint(compute_loss)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(compute_loss)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if donate is None:
        donate = jax.default_backend() != "cpu"
    jstep = jax.jit(
        step,
        in_shardings=(param_sh, None, batch_sh),
        out_shardings=(param_sh, None, None),
        donate_argnums=((0, 1) if donate else ()),
    )

    def opt_init(params):
        return jax.jit(opt.init)(params)

    return jstep, opt_init

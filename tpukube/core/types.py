"""Shared core types (L0).

TPU-native analog of the reference's ``types/`` package (SURVEY.md §2 C1).
The reference (qiniu-ava/KubeGPU; tree unreadable at survey time, SURVEY.md §0)
carries ``ResourceList``, tree-structured resource names encoding the
PCIe/NVLink topology, and ``NodeInfo``/``PodInfo``/``ContainerInfo`` structs
shared by every layer. Here the topology is an ICI mesh, so tree paths become
:class:`TopologyCoord` mesh coordinates, and a GPU UUID becomes a chip id.

Everything in this module is pure data — no I/O, no gRPC, no JAX — so the
whole scheduler stack above it is testable as functions over values
(SURVEY.md §5: "a cluster is just data").
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from enum import Enum
from typing import Iterator, Mapping, NamedTuple, Optional

# Resource names advertised to the cluster. BASELINE.json's north_star fixes
# the whole-chip name: pods request ``qiniu.com/tpu: 1``. Fractional shares
# are a distinct extended resource (one device-plugin endpoint per resource).
RESOURCE_TPU = "qiniu.com/tpu"
RESOURCE_VTPU = "qiniu.com/vtpu"

# Slice id a node reports when the cluster has a single ICI domain (the
# common case; multi-slice clusters name theirs, e.g. "slice-a").
DEFAULT_SLICE = "slice-0"

# Device-id scheme minted by the node agent (L2/L3):
#   whole chip:       tpu-<index>
#   fractional share: tpu-<index>-frac<k>of<n>
_DEVICE_ID_RE = re.compile(r"^tpu-(\d+)(?:-frac(\d+)of(\d+))?$")


def make_device_id(chip_index: int, frac: Optional[tuple[int, int]] = None) -> str:
    if frac is None:
        return f"tpu-{chip_index}"
    k, n = frac
    return f"tpu-{chip_index}-frac{k}of{n}"


def parse_device_id(device_id: str) -> tuple[int, Optional[tuple[int, int]]]:
    """Return (chip_index, (k, n) | None). Raises ValueError on junk."""
    m = _DEVICE_ID_RE.match(device_id)
    if not m:
        raise ValueError(f"malformed tpu device id: {device_id!r}")
    chip = int(m.group(1))
    if m.group(2) is None:
        return chip, None
    return chip, (int(m.group(2)), int(m.group(3)))


class Health(str, Enum):
    HEALTHY = "Healthy"
    UNHEALTHY = "Unhealthy"


class TopologyCoord(NamedTuple):
    """Position of a chip in the global ICI mesh (x fastest-varying)."""

    x: int
    y: int
    z: int

    def as_list(self) -> list[int]:
        return [self.x, self.y, self.z]

    @staticmethod
    def of(seq) -> "TopologyCoord":
        x, y, z = seq
        return TopologyCoord(int(x), int(y), int(z))


# An ICI link is an unordered pair of adjacent chip coords; the canonical
# form (lexicographically smaller endpoint first) makes pairs reported by
# either endpoint's node agent compare equal.
Link = tuple[TopologyCoord, TopologyCoord]


def canonical_link(a, b) -> Link:
    a, b = TopologyCoord.of(a), TopologyCoord.of(b)
    return (a, b) if a <= b else (b, a)


class ResourceList(dict):
    """name -> integer quantity, with the arithmetic schedulers need.

    The reference's ResourceList maps hierarchical resource names to
    quantities; ours maps flat extended-resource names (topology travels in
    :mod:`tpukube.core.codec` annotations instead of in the name).
    """

    def __init__(self, items: Optional[Mapping[str, int]] = None, **kw: int):
        super().__init__()
        for src in (items or {}), kw:
            for k, v in src.items():
                self[k] = int(v)

    def fits(self, capacity: "ResourceList") -> bool:
        """True if every requested quantity is available in ``capacity``."""
        return all(capacity.get(k, 0) >= v for k, v in self.items())

    def plus(self, other: Mapping[str, int]) -> "ResourceList":
        out = ResourceList(self)
        for k, v in other.items():
            out[k] = out.get(k, 0) + int(v)
        return out

    def minus(self, other: Mapping[str, int]) -> "ResourceList":
        out = ResourceList(self)
        for k, v in other.items():
            out[k] = out.get(k, 0) - int(v)
        return out

    def nonneg(self) -> bool:
        return all(v >= 0 for v in self.values())


@dataclass
class ChipInfo:
    """One physical TPU chip as seen by the node agent.

    The reference's per-GPU record carries UUID, memory, and PCIe/NVLink
    neighbor info (via NVML, SURVEY.md §2 C2/C3); the TPU analog carries the
    chip's global mesh coordinate and HBM size. ICI links are implied by mesh
    adjacency (MeshSpec.neighbors) rather than enumerated per-pair.
    """

    chip_id: str  # stable id, e.g. "chip-0" or a real serial
    index: int  # node-local index (device-id minting)
    coord: TopologyCoord  # global mesh coordinate
    hbm_bytes: int
    num_cores: int = 2  # TensorCores per chip (2 on v4/v5p, 1 on v5e)
    health: Health = Health.HEALTHY

    def device_id(self) -> str:
        return make_device_id(self.index)


@dataclass
class VtpuShare:
    """A minted fractional share of a physical chip (SURVEY.md §2 C6).

    Enforcement is cooperative on real TPUs: the HBM quota is exported as env
    (TPU_HBM_LIMIT_BYTES / XLA client mem fraction) for the in-pod JAX
    runtime; the sim-mode C++ audit shim gives hard enforcement in tests.
    """

    chip_index: int
    k: int  # share index, 0-based
    n: int  # shares per chip
    hbm_quota_bytes: int

    def device_id(self) -> str:
        return make_device_id(self.chip_index, (self.k, self.n))


@dataclass
class NodeInfo:
    """Everything the scheduler needs to know about one node's TPUs.

    Travels cluster-ward as the ``tpu.qiniu.com/node-topology`` annotation
    (SURVEY.md §2 C8) because extender webhooks only see core object fields.
    """

    name: str
    chips: list[ChipInfo] = field(default_factory=list)
    shares_per_chip: int = 1  # >1 => vTPU minting enabled on this node
    capacity: ResourceList = field(default_factory=ResourceList)
    annotations: dict[str, str] = field(default_factory=dict)
    # Downed ICI links with at least one endpoint on this node (canonical
    # pairs). The health watch reports them like chip faults; the scheduler
    # keeps gang slices off degraded links (SURVEY.md §6 fault injection).
    bad_links: list[Link] = field(default_factory=list)
    # Which ICI domain (pod slice) this node belongs to. A cluster may hold
    # several slices connected only over DCN; chip coords are meaningful
    # within one slice, so every coord the scheduler touches is implicitly
    # (slice_id, coord). Gangs are ICI-contiguous and thus slice-confined.
    slice_id: str = DEFAULT_SLICE
    # Where the chip inventory came from ("sim", "pjrt", "table (<why>)");
    # surfaced in the node annotation so operators can spot nodes running
    # on the static generation table instead of runtime introspection.
    source: str = ""

    def healthy_chips(self) -> list[ChipInfo]:
        return [c for c in self.chips if c.health is Health.HEALTHY]

    def chip_by_index(self, index: int) -> ChipInfo:
        for c in self.chips:
            if c.index == index:
                return c
        raise KeyError(f"{self.name}: no chip with index {index}")


@dataclass
class PodGroup:
    """Gang-scheduling group identity (SURVEY.md §2 C10).

    ``shape`` optionally pins the requested sub-slice geometry (e.g. (4,4,1)
    for a 16-chip 2D-friendly slice); None means "any contiguous box of the
    right size" (SURVEY.md §6, long-context note: shaped slices are how
    sequence-parallel jobs ask for meshes that factor well).
    """

    name: str
    min_member: int
    shape: Optional[tuple[int, int, int]] = None
    # Opt-in for data-parallel jobs whose gradient reduction tolerates DCN
    # hops: when no single ICI slice fits, the gang may split into
    # contiguous per-slice sub-boxes (multislice training). Incompatible
    # with a shape hint (a shape names one box).
    allow_dcn: bool = False

    def __post_init__(self) -> None:
        if self.allow_dcn and self.shape is not None:
            raise ValueError(
                f"pod group {self.name!r}: allow_dcn is incompatible with a "
                f"shape hint (a shape names one contiguous box)"
            )


@dataclass
class ContainerInfo:
    name: str
    requests: ResourceList = field(default_factory=ResourceList)


@dataclass
class PodInfo:
    """The slice of a k8s Pod this framework reasons about."""

    name: str
    namespace: str = "default"
    uid: str = ""
    containers: list[ContainerInfo] = field(default_factory=list)
    priority: int = 0
    group: Optional[PodGroup] = None
    node_name: str = ""
    annotations: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"

    def requests(self) -> ResourceList:
        total = ResourceList()
        for c in self.containers:
            total = total.plus(c.requests)
        return total

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class AllocResult:
    """Outcome of placing one pod: which devices on which node, plus the env
    the container must receive so the in-pod JAX runtime forms the intended
    mesh (SURVEY.md §4.3: the TPU analog of NVIDIA_VISIBLE_DEVICES +
    /dev/nvidia* injection is env-plumbing for libtpu/XLA)."""

    pod_key: str
    node_name: str
    device_ids: list[str] = field(default_factory=list)
    coords: list[TopologyCoord] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    # pod priority, persisted in the annotation so a restarted extender
    # rebuilds preemption protection (not just occupancy)
    priority: int = 0
    # pod UID at bind time ("" for pre-UID annotations). Pod names recur
    # — controllers recreate StatefulSet members under the same name — so
    # the UID is what lets the lifecycle release loop and the restart
    # rebuild tell THIS incarnation's allocation from a stale one
    uid: str = ""

    def chip_indices(self) -> list[int]:
        return [parse_device_id(d)[0] for d in self.device_ids]


def iter_pod_device_requests(pod: PodInfo) -> Iterator[tuple[str, int]]:
    """Yield (resource_name, count) for the TPU-flavored requests of a pod."""
    req = pod.requests()
    for name in (RESOURCE_TPU, RESOURCE_VTPU):
        n = req.get(name, 0)
        if n:
            yield name, n

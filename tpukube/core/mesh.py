"""ICI mesh geometry (L0).

The reference models GPU topology as a tree (NVLink domain / PCIe switch /
NUMA levels, encoded in hierarchical resource names — SURVEY.md §2 C1/C7).
A TPU pod slice is not a tree: it is an axis-aligned 3D mesh/torus of chips
(v4/v5p: 3D torus; v5e/v6e: 2D), with hosts owning fixed sub-blocks of
coordinates (4 chips per host on v4/v5p). So the core geometric object here
is :class:`MeshSpec`: global dims + per-host block, from which chip->host
mapping, adjacency, and sub-slice containment all derive.

Pure geometry, no I/O. The slicefit allocator (SURVEY.md §2 C7) and the
extender scorer (C9) are functions over this.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

from tpukube.core.types import TopologyCoord


@dataclass(frozen=True)
class MeshSpec:
    """Shape of the global chip mesh and its partition into hosts.

    dims:       chips along (x, y, z). 2D topologies use z=1.
    host_block: chips per host along each axis; must divide dims elementwise.
                v5p default is (2, 2, 1): 4 chips per host.
    torus:      per-axis wraparound. Real v5p slices >= full-dim are tori;
                sub-slices are plain meshes. Affects neighbor enumeration and
                (optionally) wrapped sub-box search.
    """

    dims: tuple[int, int, int]
    host_block: tuple[int, int, int] = (2, 2, 1)
    torus: tuple[bool, bool, bool] = (False, False, False)

    def __post_init__(self) -> None:
        if len(self.dims) != 3 or len(self.host_block) != 3:
            raise ValueError("dims and host_block must be 3-tuples")
        for d, h in zip(self.dims, self.host_block):
            if d <= 0 or h <= 0:
                raise ValueError(f"non-positive mesh dimension: {self}")
            if d % h != 0:
                raise ValueError(
                    f"host_block {self.host_block} does not divide dims {self.dims}"
                )

    # -- basic counts ------------------------------------------------------
    @property
    def num_chips(self) -> int:
        x, y, z = self.dims
        return x * y * z

    @property
    def chips_per_host(self) -> int:
        a, b, c = self.host_block
        return a * b * c

    @property
    def host_grid(self) -> tuple[int, int, int]:
        return tuple(d // h for d, h in zip(self.dims, self.host_block))  # type: ignore[return-value]

    @property
    def num_hosts(self) -> int:
        a, b, c = self.host_grid
        return a * b * c

    # -- coordinate enumeration -------------------------------------------
    def contains(self, c: TopologyCoord) -> bool:
        return all(0 <= v < d for v, d in zip(c, self.dims))

    def all_coords(self) -> Iterator[TopologyCoord]:
        X, Y, Z = self.dims
        for z in range(Z):
            for y in range(Y):
                for x in range(X):
                    yield TopologyCoord(x, y, z)

    def linearize(self, c: TopologyCoord) -> int:
        """Row-major (x fastest) chip index within the global mesh."""
        X, Y, _ = self.dims
        return c.x + X * (c.y + Y * c.z)

    def delinearize(self, i: int) -> TopologyCoord:
        X, Y, Z = self.dims
        if not 0 <= i < self.num_chips:
            raise ValueError(f"chip index {i} out of range for {self.dims}")
        return TopologyCoord(i % X, (i // X) % Y, i // (X * Y))

    # -- host partition ----------------------------------------------------
    def host_of(self, c: TopologyCoord) -> str:
        """Stable host name owning coordinate ``c`` ("host-i-j-k")."""
        # memoized: the scheduler asks this for every reservation coord on
        # every node of every webhook (hot; the cache lives in __dict__ and
        # is invisible to the frozen dataclass' eq/hash)
        cache = self.__dict__.get("_host_of_cache")
        if cache is None:
            cache = {}
            object.__setattr__(self, "_host_of_cache", cache)
        h = cache.get(c)
        if h is None:
            if not self.contains(c):
                raise ValueError(f"coord {c} outside mesh {self.dims}")
            i, j, k = (v // b for v, b in zip(c, self.host_block))
            h = cache[c] = f"host-{i}-{j}-{k}"
        return h

    def host_origin(self, host: str) -> TopologyCoord:
        try:
            prefix, i, j, k = host.split("-")
            if prefix != "host":
                raise ValueError(host)
            grid = (int(i), int(j), int(k))
        except ValueError as e:
            raise ValueError(f"malformed host name {host!r}") from e
        ga, gb, gc = self.host_grid
        if not (0 <= grid[0] < ga and 0 <= grid[1] < gb and 0 <= grid[2] < gc):
            raise ValueError(f"host {host!r} outside host grid {self.host_grid}")
        return TopologyCoord(*(g * h for g, h in zip(grid, self.host_block)))

    def coords_of_host(self, host: str) -> list[TopologyCoord]:
        ox, oy, oz = self.host_origin(host)
        hx, hy, hz = self.host_block
        return [
            TopologyCoord(ox + dx, oy + dy, oz + dz)
            for dz in range(hz)
            for dy in range(hy)
            for dx in range(hx)
        ]

    def all_hosts(self) -> list[str]:
        ga, gb, gc = self.host_grid
        return [
            f"host-{i}-{j}-{k}"
            for k in range(gc)
            for j in range(gb)
            for i in range(ga)
        ]

    # -- adjacency ---------------------------------------------------------
    def neighbors(self, c: TopologyCoord) -> list[TopologyCoord]:
        """ICI neighbors of a chip (±1 per axis, honoring per-axis torus).

        This replaces the reference's per-pair NVLink queries
        (nvmlDeviceGetTopologyCommonAncestor, SURVEY.md §2 C2): on a TPU the
        link table IS mesh adjacency.
        """
        out: list[TopologyCoord] = []
        for axis in range(3):
            d = self.dims[axis]
            if d == 1:
                continue
            for step in (-1, 1):
                v = list(c)
                v[axis] += step
                if not 0 <= v[axis] < d:
                    if not self.torus[axis]:
                        continue
                    v[axis] %= d
                nb = TopologyCoord(*v)
                if nb != c and nb not in out:
                    out.append(nb)
        return out

    # -- (de)serialization -------------------------------------------------
    def to_json(self) -> dict:
        return {
            "dims": list(self.dims),
            "host_block": list(self.host_block),
            "torus": list(self.torus),
        }

    @staticmethod
    def from_json(obj: dict) -> "MeshSpec":
        return MeshSpec(
            dims=tuple(obj["dims"]),
            host_block=tuple(obj.get("host_block", (2, 2, 1))),
            torus=tuple(obj.get("torus", (False, False, False))),
        )


@dataclass(frozen=True)
class Box:
    """Axis-aligned sub-box [origin, origin+shape) of a mesh — the unit of
    gang placement (a contiguous sub-slice)."""

    origin: TopologyCoord
    shape: tuple[int, int, int]

    def __post_init__(self) -> None:
        if any(s <= 0 for s in self.shape):
            raise ValueError(f"non-positive box shape {self.shape}")

    @property
    def size(self) -> int:
        a, b, c = self.shape
        return a * b * c

    def coords(self) -> Iterator[TopologyCoord]:
        ox, oy, oz = self.origin
        sx, sy, sz = self.shape
        for z in range(sz):
            for y in range(sy):
                for x in range(sx):
                    yield TopologyCoord(ox + x, oy + y, oz + z)

    def contains(self, c: TopologyCoord) -> bool:
        return all(o <= v < o + s for v, o, s in zip(c, self.origin, self.shape))

    def fits_in(self, mesh: MeshSpec) -> bool:
        return all(
            0 <= o and o + s <= d
            for o, s, d in zip(self.origin, self.shape, mesh.dims)
        )

    def to_json(self) -> dict:
        return {"origin": list(self.origin), "shape": list(self.shape)}

    @staticmethod
    def from_json(obj: dict) -> "Box":
        return Box(TopologyCoord.of(obj["origin"]), tuple(obj["shape"]))


def surface(shape: tuple[int, int, int]) -> int:
    """Surface area of a box shape — the compactness measure used both for
    shape ranking here and box scoring in slicefit (lower = more compact =
    better ICI bisection for the job)."""
    a, b, c = shape
    return 2 * (a * b + b * c + a * c)


def factor_shapes(n: int, mesh_dims: tuple[int, int, int]) -> list[tuple[int, int, int]]:
    """All 3D box shapes of volume n that could fit in ``mesh_dims``.

    Used by slicefit when a gang requests a count without pinning a shape:
    candidate shapes are ranked elsewhere (prefer compact, well-factoring
    boxes — SURVEY.md §9.3). Deterministic order: sorted by descending
    "compactness" (minimize surface area), then lexicographically.
    """
    shapes: set[tuple[int, int, int]] = set()
    X, Y, Z = mesh_dims
    for a in range(1, min(n, X) + 1):
        if n % a:
            continue
        rem = n // a
        for b in range(1, min(rem, Y) + 1):
            if rem % b:
                continue
            c = rem // b
            if c <= Z:
                shapes.add((a, b, c))

    return sorted(shapes, key=lambda s: (surface(s), s))

"""Configuration (SURVEY.md §6 "Config / flag system").

The reference configures its daemons with Go flag/pflag + the kube-scheduler
policy/extender config file. Here one dataclass covers both daemons and the
sim harness, loadable from defaults < YAML file < environment (later wins).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields, replace
from typing import Optional

import yaml

from tpukube.core.mesh import MeshSpec

# Default HBM per chip: TPU v5p has 95 GiB HBM2e per chip.
DEFAULT_HBM_BYTES = 95 * 1024**3

ENV_PREFIX = "TPUKUBE_"


@dataclass(frozen=True)
class TpuKubeConfig:
    # resources
    resource_tpu: str = "qiniu.com/tpu"
    resource_vtpu: str = "qiniu.com/vtpu"
    shares_per_chip: int = 1  # >1 enables vTPU minting (e.g. 2 or 4)

    # node agent / device plugin
    device_plugin_dir: str = "/var/lib/kubelet/device-plugins"
    kubelet_socket: str = "kubelet.sock"  # within device_plugin_dir
    plugin_socket: str = "tpukube.sock"  # within device_plugin_dir
    health_poll_seconds: float = 5.0

    # scheduler extender
    extender_host: str = "0.0.0.0"
    extender_port: int = 12345
    score_mode: str = "topology"  # topology | binpack | spread
    reservation_ttl_seconds: float = 30.0
    # decision trace (SURVEY.md §6): in-memory ring size (0 disables) and
    # optional JSONL sink for post-mortem replay (tpukubectl replay).
    # Events retain verbatim webhook bodies (the full node list), so the
    # default ring is kept small; raise it (or set trace_path) on clusters
    # where post-mortem replay depth matters more than extender RSS.
    trace_capacity: int = 4096
    trace_path: str = ""
    # JSONL sink size cap: at the cap the file rotates once to
    # <trace_path>.1 (0 = unlimited). Default 256 MiB — an incident
    # capture left on overnight must not fill the node's disk.
    trace_sink_max_bytes: int = 256 * 1024**2
    # structured event journal (obs/events.py): bounded ring of typed
    # "why did that happen" events (GangCommitted, ChipUnhealthy, ...)
    # served on /statusz + /events; events_path streams them to JSONL
    # for `tpukube-obs events`, size-capped like the trace sink.
    # events_capacity 0 disables the journal.
    events_capacity: int = 4096
    events_path: str = ""
    events_sink_max_bytes: int = 64 * 1024**2
    # dynamic lock-order detector (tpukube.analysis.lockgraph): when
    # true, threading.Lock/RLock created by tpukube code are wrapped to
    # record acquisition-order edges; tpukube-sim attaches the resulting
    # lock graph (edges + deadlock cycles) to its result JSON. Off by
    # default: nothing is patched and lock creation is untouched —
    # tests/test_lint.py asserts the zero-overhead default.
    lock_monitor: bool = False

    # unified retry policy (core/retry.py): jittered exponential
    # backoff for every control-plane seam a Retrier is wired into
    # (apiserver requests, eviction GET-confirms, kubelet
    # registration). The knobs only shape retries where a Retrier
    # exists — nothing new retries by default at a seam that did not
    # retry before this policy existed.
    retry_max_attempts: int = 5
    retry_base_delay_seconds: float = 0.1
    retry_max_delay_seconds: float = 5.0
    retry_jitter: float = 0.5  # fraction of each delay randomized away
    retry_deadline_seconds: float = 30.0  # overall wall budget (0 = none)
    # per-attempt transport-timeout cap: one hung attempt must not eat
    # the whole overall deadline (0 = keep the transport's own default)
    retry_attempt_timeout_seconds: float = 0.0
    # apiserver circuit breaker (core/retry.py CircuitBreaker):
    # failure_threshold consecutive transport/5xx failures open the
    # circuit; requests then fail fast for reset_seconds before
    # half-open probing. 0 DISABLES the breaker (the default — legacy
    # behavior), and with it the extender's degraded mode.
    circuit_failure_threshold: int = 0
    circuit_reset_seconds: float = 30.0
    circuit_half_open_probes: int = 1
    # chaos harness (tpukube/chaos/): deterministic fault-schedule seed
    # for the sim's chaos scenarios (8/9). 0 = chaos off everywhere;
    # scenario code falls back to its own fixed seed so `tpukube-sim 8`
    # is reproducible out of the box.
    chaos_seed: int = 0
    # snapshot audit sentinel (sched/snapshot.py SnapshotCache): on
    # this fraction of scheduling-path cache hits, rebuild the snapshot
    # from the ledger and RAISE on divergence — the runtime counterpart
    # of tpukube-lint's epoch-discipline pass, catching any mutation
    # seam the static registry misses. 0 (default) disables the audit;
    # 1.0 audits every hit (sim scenarios and the chaos suite run
    # green at 1.0 with zero divergences).
    snapshot_audit_rate: float = 0.0
    # incremental snapshot maintenance (sched/snapshot.py, ISSUE 10):
    # epoch bumps record typed SnapshotDeltas and the cache ADVANCES
    # the cached snapshot O(Δ) instead of rebuilding O(chips) per
    # epoch. Placements are bit-identical either way (parity-tested);
    # false restores the rebuild-every-epoch behavior (the oracle) and
    # keeps the /metrics exposition free of the tpukube_snapshot_delta_*
    # series.
    snapshot_delta_enabled: bool = True
    # Bulk cold-start ingestion (sched/state.py ingest_nodes, ISSUE
    # 15): batched node upserts (handle("upsert_nodes") /
    # upsert_nodes_many) probe-validate payloads, defer the full decode
    # to first touch (lazy NodeViews + a background warmer, the
    # checkpoint restore's contract), seed the per-slice incremental
    # caches from the probe aggregates, and fire ONE
    # epoch/delta/journal seam per batch. Resulting state is identical
    # to per-item upserts (parity-tested); false loops the per-item
    # path under the same decision surface and keeps the exposition
    # free of the tpukube_ingest_* series.
    bulk_ingest_enabled: bool = True
    # Generation-based incremental resync (ISSUE 15): the ledger
    # stamps a generation on every alloc mutation and keeps a bounded
    # per-generation change log; allocs_since(cursor) then serves a
    # churn wave's resync as O(changed-allocs) adds/removes instead of
    # the full ledger (per replica over the process transport). The
    # capacity must exceed the deepest alloc churn between two resync
    # reads (commits + releases of one wave) — a gap degrades to a
    # counted FULL read, never a stale answer. 0 disables the log (the
    # legacy full-read behavior; no tpukube_resync_* series render).
    generation_log_capacity: int = 65536

    # Durable control-plane state (sched/journal.py, ISSUE 11): with
    # journal_enabled the extender appends every ledger/gang mutation
    # to a CRC'd JSONL write-ahead log at journal_path (drain-thread
    # writes — the decision lock never blocks on disk) and captures a
    # full checkpoint every checkpoint_interval_seconds; a restarted
    # extender then recovers O(Δ-since-checkpoint) instead of the
    # O(fleet) rebuild_from_pods cold start. false (the default)
    # constructs nothing: placements, /metrics exposition, and
    # annotations stay byte-identical to the journal-less daemon.
    journal_enabled: bool = False
    journal_path: str = ""
    # WAL size cap: at the cap the file rotates once to <path>.1 and a
    # prompt checkpoint is requested so the live chain stays coverable
    journal_max_bytes: int = 64 * 1024**2
    checkpoint_interval_seconds: float = 60.0
    # fsync policy: "off" flushes each drain batch (a machine crash can
    # lose the last few records — the recovery reconcile absorbs that
    # exactly like a torn tail); "always" fsyncs every batch (zero loss,
    # one fsync per batch on the journal thread). Checkpoints fsync
    # before their atomic rename under either policy.
    journal_fsync: str = "off"

    # Batched scheduling cycles (sched/cycle.py SchedulingCycle): when
    # batch_enabled is true the extender admits pending pods into a
    # scheduling queue, plans placements for a whole batch against ONE
    # epoch-pinned ClusterSnapshot per cycle (kube-scheduler's
    # snapshot-per-cycle model), and answers /filter, /prioritize, and
    # /bind from the batch plan instead of re-planning per webhook.
    # false (the default) preserves the legacy per-pod webhook path
    # bit-identically — nothing batch-related is even constructed.
    batch_enabled: bool = False
    # most pods planned per cycle; pods beyond the cap wait for the
    # next cycle (their own /filter triggers it)
    batch_max_pods: int = 64
    # minimum simulated/wall seconds between full batch replans when
    # the queue is already drained (0 = plan eagerly on every webhook
    # that misses the plan — the latency-first default; kilonode sims
    # raise it to coalesce arrival storms into fewer, bigger cycles)
    cycle_interval_seconds: float = 0.0
    # Answer /filter and /prioritize webhooks FROM the cycle plan
    # (sched/cycle.py, ISSUE 13 satellite): the feasibility answer is
    # the planned node alone instead of the materialized O(nodes)
    # per-node verdict list that was the 10k-node filter p99
    # (BENCH_r06: ~49ms webhook answer vs a 0.25ms/pod planner). The
    # PLACEMENT is unchanged — the scheduler picks from a one-node
    # feasible set exactly the node the full answer's max-score
    # tie-break would pick — but the wire response no longer names
    # every infeasible node's reason, so the default stays off (full
    # answers) and the kilonode scenarios/bench turn it on. Requires
    # batch_enabled (there is no plan to answer from otherwise).
    filter_from_plan: bool = False

    # Slice-partitioned control plane (sched/shard.py, ISSUE 13
    # tentpole): >1 runs N planner replicas behind an in-process
    # ShardRouter — each replica a full Extender owning a disjoint ICI
    # slice set (its own ledger, gang manager, snapshot cache,
    # scheduling queue, and journal segment at <journal_path>.r<i>);
    # the router routes pods by slice affinity and coordinates a
    # two-phase rendezvous (reserve-on-each-replica, then
    # commit-or-abort) for DCN-spanning gangs. 1 (the default) builds
    # no router anywhere — the single-planner path is untouched. The
    # in-process router serves the sim/bench plane; production runs
    # one extender process per replica behind the same routing
    # contract (see README "Sharded control plane").
    planner_replicas: int = 1
    # How the router reaches its planner replicas (ISSUE 14):
    #   inprocess   — replicas are Extender objects in the router's
    #                 process (PR 13's plane: deterministic, one GIL —
    #                 the tier-1 parity oracle).
    #   subprocess  — one planner DAEMON per replica: the router
    #                 spawns `tpukube.cli shard-worker` processes and
    #                 fans webhook bodies out over HTTP (concurrent
    #                 across replicas, ordered per replica), so N
    #                 replicas plan on N cores. Replica death is
    #                 detected by health checks / transport failures
    #                 and handled with crash_replica semantics (warm
    #                 restart via rebuild). bench.shard_scaling's
    #                 process sweep and scenario 14's process mode run
    #                 this; production runs the same worker daemon
    #                 shape under its own supervisor.
    shard_transport: str = "inprocess"
    # Wire codec for the router<->worker `/worker/*` surface (ISSUE 20;
    # sched/wirecodec.py):
    #   json    — the default AND the parity oracle: compact-separator
    #             JSON bodies, byte-for-byte what the plane shipped
    #             before the codec existed.
    #   binary  — versioned TKW1 frames (per-op key tables, interned
    #             strings, zlib/zstd above wire_compress_min_bytes),
    #             negotiated per request via Content-Type/Accept so a
    #             binary router over a JSON-only worker degrades
    #             cleanly to JSON per replica (rolling upgrades,
    #             deploy/README.md). Placements are byte-identical
    #             codec-on vs codec-off — the codec moves bytes, never
    #             decisions. Meaningful only on the subprocess
    #             transport, but binary+inprocess is NOT a config
    #             error: SubprocessTransport pins every worker's own
    #             YAML to inprocess, and the worker must still boot
    #             (the worker side is Accept-driven, not config-driven).
    wire_codec: str = "json"
    # Binary payloads at or above this many encoded bytes are
    # compressed (kept raw if compression doesn't shrink them). Small
    # control ops stay raw — compression overhead would dominate.
    wire_compress_min_bytes: int = 1024

    # Decision provenance (tpukube/obs/decisions.py, ISSUE 12). With
    # decisions_enabled the extender keeps a bounded, sampled,
    # lock-free-on-record ring of per-pod DecisionRecord stage events
    # (admit -> queue wait -> cycle pin -> candidate pruning -> gang /
    # preemption -> tenancy verdict -> bind), serves them on /explain
    # + the /statusz "decisions" section + `tpukube-obs explain`, and
    # turns on cycle phase profiling (tpukube_cycle_phase_seconds).
    # false (the default) constructs NOTHING: no stage is built, no
    # series renders, placements and exposition stay byte-identical.
    decisions_enabled: bool = False
    decisions_capacity: int = 8192
    # fraction of pods sampled into the ring, selected by a
    # deterministic seeded hash of the pod key — 0.01 on a kilonode
    # fleet keeps 1% of pods FULLY explained
    decisions_sample_rate: float = 1.0
    decisions_seed: int = 0
    # optional JSONL sink for `tpukube-obs explain --file` (size-capped
    # like the trace/events sinks)
    decisions_path: str = ""
    decisions_sink_max_bytes: int = 64 * 1024**2

    # Capacity analytics & demand forensics (tpukube/obs/capacity.py,
    # ISSUE 17). With capacity_enabled the extender keeps a bounded
    # flight-recorder ring of periodic fleet samples (per-slice
    # utilization / fragmentation / largest-free-box, queue depth,
    # tenant shares), root-causes every failed/deferred plan into the
    # stranded-demand taxonomy (fragmented / capacity / quota / shed /
    # unhealthy / dcn-ineligible), and serves /capacity +
    # /capacity/probe. Samples ride the scheduling clock (FakeClock-
    # compressible) and the epoch-cached snapshot's observer view.
    # false (the default) constructs NOTHING: no recorder, no series,
    # placements and exposition stay byte-identical.
    capacity_enabled: bool = False
    capacity_sample_interval_seconds: float = 30.0
    # flight-recorder ring depth (samples, not bytes)
    capacity_samples: int = 2048
    # optional JSONL sample sink for `tpukube-obs capacity --merge`
    # (size-capped like the trace/events/decisions sinks)
    capacity_path: str = ""
    capacity_sink_max_bytes: int = 64 * 1024**2

    # Multi-tenant serving plane (tpukube/tenancy, ISSUE 9). With
    # tenancy_enabled the extender attaches a TenantPlane: tenant ids
    # from the tenancy_label pod label (unlabeled pods belong to
    # tenancy_default_tenant), per-tenant quota enforcement at
    # admission, DRF ordering of the batch scheduling queue,
    # tenant-aware preemption victim bias, and SLO-burn shedding of
    # low-priority bursts. false (the default) constructs NOTHING —
    # placements, /metrics exposition, and alloc annotations stay
    # byte-identical to the pre-tenancy behavior.
    tenancy_enabled: bool = False
    tenancy_label: str = "tpu.qiniu.com/tenant"
    tenancy_default_tenant: str = "default"
    # per-tenant caps: "teamA=chips:16,hbm:0.25;teamB=chips:8" —
    # chips are whole-chip equivalents (vTPU shares count 1/n), hbm a
    # fraction of total cluster HBM. Empty = no quotas (fairness and
    # shedding still apply).
    tenancy_quotas: str = ""
    # SLO-aware admission: while any DEFAULT_SLOS burn rate over the
    # sliding window reaches this page threshold (obs/slo.py
    # MULTIWINDOW_ALERTS' page burn), low-priority non-gang admissions
    # from over-share tenants are shed with a TenantAdmissionShed
    # journal event. 0 disables shedding (quotas still enforce).
    tenancy_burn_threshold: float = 14.4
    tenancy_burn_window_seconds: float = 60.0
    # only pods at or below this priority are ever shed (burst-infer
    # traffic; committed training gangs are never shed)
    tenancy_shed_priority_max: int = 0

    # Graceful drain / decommission (tpukube/sched/drain.py, ISSUE
    # 19). With drain_enabled the extender attaches a DrainCoordinator:
    # cordon a node/slice (excluded from every placement sweep while
    # live allocations keep serving), migrate-or-preempt residents
    # through the existing preemption-planner + eviction-executor
    # machinery under a bounded disruption budget, then release and
    # un-ingest (the inverse of ingest_nodes — one epoch/delta/journal
    # seam per batch). false (the default) constructs NOTHING:
    # placements, exposition, and journal bytes stay byte-identical.
    drain_enabled: bool = False
    # most resident evictions per drain tick (the disruption budget's
    # concurrency half — a drain never rips more than this many
    # workloads out of service between two scheduling chances)
    drain_max_concurrent_moves: int = 4
    # most evictions charged to ONE tenant per drain tick (0 = no
    # per-tenant budget; only meaningful with tenancy attribution)
    drain_tenant_budget: int = 0

    # Autoscaler loop (tpukube/sched/autoscale.py, ISSUE 19). With
    # autoscale_enabled the extender attaches an Autoscaler that grows
    # the simulated fleet against queue depth + tenant SLO burn (bulk
    # ingest of provisioned slices) and shrinks it by driving drains
    # when utilization idles below the floor. Requires drain_enabled —
    # scale-down IS a drain. false (the default) constructs nothing.
    autoscale_enabled: bool = False
    # queue depth at/above which a scale-up fires (SLO page burn also
    # triggers one regardless of depth)
    autoscale_up_queue_depth: int = 8
    # fleet utilization below which a scale-down drain is considered
    autoscale_down_utilization: float = 0.25
    # slice-count bounds the loop never crosses
    autoscale_min_slices: int = 1
    autoscale_max_slices: int = 16
    # scheduling-clock seconds between scale actions (either direction)
    autoscale_cooldown_seconds: float = 120.0

    # Which ICI slice this node belongs to (multi-slice clusters name
    # their pod slices; coords are slice-local — SURVEY.md §3 ICI/DCN note)
    slice_id: str = "slice-0"

    # sim topology (used when backend == "sim")
    backend: str = "sim"  # sim | real
    # explicit libtpu.so path for the real backend (Cloud TPU images ship
    # it off the loader path); empty = autodiscover (loader path, then the
    # libtpu Python package)
    libtpu_path: str = ""
    # real-backend health canary: "" = native default (liveness), or
    # client|liveness|off — see native/tpuinfo.h tpuinfo_probe
    probe_mode: str = ""
    # per-axis torus wrap for real nodes when the runtime doesn't report
    # the "wrap" attribute (PJRT exposes only a bounding box); a
    # runtime-reported wrap always wins over this
    real_torus: tuple[bool, bool, bool] = (False, False, False)
    sim_mesh_dims: tuple[int, int, int] = (4, 4, 4)
    sim_host_block: tuple[int, int, int] = (2, 2, 1)
    sim_torus: tuple[bool, bool, bool] = (False, False, False)
    # chip-coord origin of this host's block ("x,y,z"); empty = derive from
    # the host name's host-i-j-k convention. Set it when node names do not
    # follow that convention (e.g. multi-slice sims prefix the slice id).
    sim_host_origin: str = ""
    hbm_bytes_per_chip: int = DEFAULT_HBM_BYTES
    cores_per_chip: int = 2

    def sim_mesh(self) -> MeshSpec:
        return MeshSpec(
            dims=self.sim_mesh_dims,
            host_block=self.sim_host_block,
            torus=self.sim_torus,
        )

    def plugin_socket_path(self) -> str:
        return os.path.join(self.device_plugin_dir, self.plugin_socket)

    def kubelet_socket_path(self) -> str:
        return os.path.join(self.device_plugin_dir, self.kubelet_socket)


_TUPLE_FIELDS = {"sim_mesh_dims", "sim_host_block", "sim_torus", "real_torus"}


def _coerce(name: str, raw, current):
    if name in _TUPLE_FIELDS:
        if isinstance(raw, str):
            raw = [p for p in raw.replace("x", ",").split(",") if p != ""]
        elem = bool if isinstance(current[0], bool) else int
        if elem is bool:
            vals = tuple(
                v if isinstance(v, bool) else str(v).lower() in ("1", "true", "yes")
                for v in raw
            )
        else:
            vals = tuple(int(v) for v in raw)
        if len(vals) != 3:
            raise ValueError(f"config {name}: need 3 values, got {vals!r}")
        return vals
    t = type(current)
    if t is bool:
        return raw if isinstance(raw, bool) else str(raw).lower() in ("1", "true", "yes")
    return t(raw)


def load_config(
    yaml_path: Optional[str] = None, env: Optional[dict[str, str]] = None
) -> TpuKubeConfig:
    """defaults < yaml < env (TPUKUBE_<UPPER_FIELD_NAME>)."""
    cfg = TpuKubeConfig()
    updates: dict = {}
    if yaml_path:
        with open(yaml_path) as f:
            doc = yaml.safe_load(f) or {}
        if not isinstance(doc, dict):
            raise ValueError(f"{yaml_path}: top level must be a mapping")
        known = {f_.name for f_ in fields(cfg)}
        for k, v in doc.items():
            if k not in known:
                raise ValueError(f"{yaml_path}: unknown config key {k!r}")
            updates[k] = v
    env = os.environ if env is None else env
    for f_ in fields(cfg):
        env_key = ENV_PREFIX + f_.name.upper()
        if env_key in env:
            updates[f_.name] = env[env_key]
    for k, v in list(updates.items()):
        updates[k] = _coerce(k, v, getattr(cfg, k))
    cfg = replace(cfg, **updates)
    if cfg.shares_per_chip < 1:
        raise ValueError("shares_per_chip must be >= 1")
    if not 0 < cfg.extender_port < 65536:
        raise ValueError(f"extender_port {cfg.extender_port} out of range")
    if cfg.health_poll_seconds <= 0 or cfg.reservation_ttl_seconds <= 0:
        raise ValueError("poll/ttl intervals must be positive")
    if cfg.score_mode not in ("topology", "binpack", "spread"):
        raise ValueError(f"unknown score_mode {cfg.score_mode!r}")
    if cfg.backend not in ("sim", "real"):
        raise ValueError(f"unknown backend {cfg.backend!r}")
    if cfg.probe_mode not in ("", "client", "liveness", "off"):
        raise ValueError(f"unknown probe_mode {cfg.probe_mode!r}")
    if cfg.sim_host_origin:
        parts = cfg.sim_host_origin.split(",")
        if len(parts) != 3 or not all(p.strip().lstrip("-").isdigit() for p in parts):
            raise ValueError(
                f"sim_host_origin must be 'x,y,z', got {cfg.sim_host_origin!r}"
            )
    if not cfg.slice_id:
        raise ValueError("slice_id must be non-empty")
    if (cfg.trace_sink_max_bytes < 0 or cfg.events_capacity < 0
            or cfg.events_sink_max_bytes < 0):
        raise ValueError(
            "trace_sink_max_bytes, events_capacity, and "
            "events_sink_max_bytes must be >= 0"
        )
    if cfg.retry_max_attempts < 1:
        raise ValueError("retry_max_attempts must be >= 1")
    if cfg.retry_base_delay_seconds <= 0 or cfg.retry_max_delay_seconds <= 0:
        raise ValueError("retry delays must be positive")
    if cfg.retry_max_delay_seconds < cfg.retry_base_delay_seconds:
        raise ValueError(
            "retry_max_delay_seconds must be >= retry_base_delay_seconds"
        )
    if not 0.0 <= cfg.retry_jitter < 1.0:
        raise ValueError("retry_jitter must be in [0, 1)")
    if cfg.retry_deadline_seconds < 0:
        raise ValueError("retry_deadline_seconds must be >= 0 (0 = none)")
    if cfg.retry_attempt_timeout_seconds < 0:
        raise ValueError(
            "retry_attempt_timeout_seconds must be >= 0 (0 = transport "
            "default)"
        )
    if cfg.circuit_failure_threshold < 0:
        raise ValueError(
            "circuit_failure_threshold must be >= 0 (0 = disabled)"
        )
    if cfg.circuit_reset_seconds <= 0:
        raise ValueError("circuit_reset_seconds must be positive")
    if cfg.circuit_half_open_probes < 1:
        raise ValueError("circuit_half_open_probes must be >= 1")
    if cfg.chaos_seed < 0:
        raise ValueError("chaos_seed must be >= 0 (0 = chaos off)")
    if not 0.0 <= cfg.snapshot_audit_rate <= 1.0:
        raise ValueError(
            "snapshot_audit_rate must be in [0, 1] (0 = audit off)"
        )
    if cfg.batch_max_pods < 1:
        raise ValueError("batch_max_pods must be >= 1")
    if cfg.generation_log_capacity < 0:
        raise ValueError(
            "generation_log_capacity must be >= 0 (0 = incremental "
            "resync off)"
        )
    if cfg.journal_enabled and not cfg.journal_path:
        # a journal with nowhere to write would silently provide NO
        # durability — the operator who enabled it believes it is live
        raise ValueError(
            "journal_enabled requires journal_path"
        )
    if cfg.journal_path and not cfg.journal_enabled:
        raise ValueError(
            "journal_path is set but journal_enabled is false — "
            "enable the journal or drop the path"
        )
    if cfg.journal_fsync not in ("off", "always"):
        raise ValueError(
            f"unknown journal_fsync {cfg.journal_fsync!r} "
            f"(off | always)"
        )
    if cfg.journal_max_bytes < 0:
        raise ValueError("journal_max_bytes must be >= 0 (0 = uncapped)")
    if cfg.checkpoint_interval_seconds <= 0:
        raise ValueError("checkpoint_interval_seconds must be positive")
    if cfg.decisions_path and not cfg.decisions_enabled:
        raise ValueError(
            "decisions_path is set but decisions_enabled is false — "
            "enable decision provenance or drop the path"
        )
    if cfg.decisions_enabled and cfg.decisions_capacity < 1:
        raise ValueError("decisions_capacity must be >= 1 when enabled")
    if not 0.0 <= cfg.decisions_sample_rate <= 1.0:
        raise ValueError("decisions_sample_rate must be in [0, 1]")
    if cfg.decisions_seed < 0 or cfg.decisions_sink_max_bytes < 0:
        raise ValueError(
            "decisions_seed and decisions_sink_max_bytes must be >= 0"
        )
    if cfg.capacity_path and not cfg.capacity_enabled:
        raise ValueError(
            "capacity_path is set but capacity_enabled is false — "
            "enable capacity analytics or drop the path"
        )
    if cfg.capacity_enabled and cfg.capacity_samples < 1:
        raise ValueError("capacity_samples must be >= 1 when enabled")
    if cfg.capacity_enabled and cfg.capacity_sample_interval_seconds <= 0:
        raise ValueError(
            "capacity_sample_interval_seconds must be positive"
        )
    if cfg.capacity_sink_max_bytes < 0:
        raise ValueError("capacity_sink_max_bytes must be >= 0")
    if cfg.tenancy_quotas and not cfg.tenancy_enabled:
        # quotas without the plane would be silently unenforced — an
        # operator who wrote caps believes they are live; fail loudly
        raise ValueError(
            "tenancy_quotas is set but tenancy_enabled is false — "
            "enable tenancy or drop the quotas"
        )
    if cfg.tenancy_enabled:
        if not cfg.tenancy_label or not cfg.tenancy_default_tenant:
            raise ValueError(
                "tenancy_label and tenancy_default_tenant must be "
                "non-empty"
            )
        # surface quota-spec mistakes at config load, not at the first
        # webhook (lazy import: tenancy is only pulled in when used;
        # parse_quotas raises ValueError with the offending fragment)
        from tpukube.tenancy import parse_quotas

        parse_quotas(cfg.tenancy_quotas)
    if cfg.tenancy_burn_threshold < 0:
        raise ValueError(
            "tenancy_burn_threshold must be >= 0 (0 = no SLO shedding)"
        )
    if cfg.tenancy_burn_window_seconds <= 0:
        raise ValueError("tenancy_burn_window_seconds must be positive")
    if cfg.cycle_interval_seconds < 0:
        raise ValueError(
            "cycle_interval_seconds must be >= 0 (0 = plan on demand)"
        )
    if cfg.filter_from_plan and not cfg.batch_enabled:
        raise ValueError(
            "filter_from_plan requires batch_enabled — without the "
            "batch planner there is no cycle plan to answer from"
        )
    if cfg.planner_replicas < 1:
        raise ValueError("planner_replicas must be >= 1")
    if cfg.shard_transport not in ("inprocess", "subprocess"):
        raise ValueError(
            f"unknown shard_transport {cfg.shard_transport!r} "
            f"(inprocess | subprocess)"
        )
    if cfg.wire_codec not in ("json", "binary"):
        raise ValueError(
            f"unknown wire_codec {cfg.wire_codec!r} (json | binary)"
        )
    if cfg.wire_compress_min_bytes < 0:
        raise ValueError("wire_compress_min_bytes must be >= 0")
    if cfg.drain_max_concurrent_moves < 1:
        raise ValueError("drain_max_concurrent_moves must be >= 1")
    if cfg.drain_tenant_budget < 0:
        raise ValueError(
            "drain_tenant_budget must be >= 0 (0 = no per-tenant cap)"
        )
    if cfg.autoscale_enabled and not cfg.drain_enabled:
        # scale-down IS a drain: an autoscaler without the drain
        # choreography would silently never shrink — fail loudly (the
        # journal_enabled/journal_path pairing contract)
        raise ValueError(
            "autoscale_enabled requires drain_enabled — scale-down "
            "drives the drain choreography"
        )
    if cfg.autoscale_up_queue_depth < 1:
        raise ValueError("autoscale_up_queue_depth must be >= 1")
    if not 0.0 <= cfg.autoscale_down_utilization <= 1.0:
        raise ValueError(
            "autoscale_down_utilization must be in [0, 1]"
        )
    if cfg.autoscale_min_slices < 1:
        raise ValueError("autoscale_min_slices must be >= 1")
    if cfg.autoscale_max_slices < cfg.autoscale_min_slices:
        raise ValueError(
            "autoscale_max_slices must be >= autoscale_min_slices"
        )
    if cfg.autoscale_cooldown_seconds < 0:
        raise ValueError("autoscale_cooldown_seconds must be >= 0")
    if cfg.planner_replicas > 1 and cfg.tenancy_quotas:
        # each replica's TenantLedger sees only its own slice set, so a
        # cluster-wide chip cap split across N replicas would silently
        # enforce N x the written quota — refuse at load rather than
        # under-enforce (same contract as quotas-without-the-plane)
        raise ValueError(
            "tenancy_quotas with planner_replicas > 1 is not yet "
            "shard-aware (each replica would enforce the full cap "
            "against its own slices) — run quotas unsharded, or drop "
            "them for the sharded plane"
        )
    return cfg

"""Core vocabulary: types, mesh geometry, annotation codec, configuration."""

from tpukube.core.types import (  # noqa: F401
    RESOURCE_TPU,
    RESOURCE_VTPU,
    AllocResult,
    ChipInfo,
    ContainerInfo,
    Health,
    NodeInfo,
    PodGroup,
    PodInfo,
    ResourceList,
    TopologyCoord,
    VtpuShare,
)
from tpukube.core.mesh import MeshSpec  # noqa: F401

"""Annotation codec (L4) — the cluster<->node information channel.

TPU-native analog of the reference's ``kubeinterface/`` (SURVEY.md §2 C8):
kube-scheduler extender webhooks only see core API object fields, so rich
node topology and allocation results must ride Kubernetes annotations. The
node agent writes ``node-topology`` onto its Node; the extender writes
``alloc`` onto bound Pods; jobs declare gangs with ``pod-group`` annotations.

Schema is versioned JSON. Every encode has a decode round-trip test.
"""

from __future__ import annotations

import json
from typing import Optional

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    DEFAULT_SLICE,
    AllocResult,
    ChipInfo,
    Health,
    NodeInfo,
    PodGroup,
    PodInfo,
    TopologyCoord,
    canonical_link,
)

SCHEMA_VERSION = 1

ANNO_PREFIX = "tpu.qiniu.com/"
ANNO_NODE_TOPOLOGY = ANNO_PREFIX + "node-topology"
ANNO_ALLOC = ANNO_PREFIX + "alloc"
ANNO_POD_GROUP = ANNO_PREFIX + "pod-group"
ANNO_POD_GROUP_MIN_MEMBER = ANNO_PREFIX + "pod-group-min-member"
ANNO_POD_GROUP_SHAPE = ANNO_PREFIX + "pod-group-shape"
ANNO_POD_GROUP_ALLOW_DCN = ANNO_PREFIX + "pod-group-allow-dcn"
# Compact per-node health summary (obs/health.py telemetry): refreshed
# alongside node-topology on every health/link transition so the
# extender can roll up fleet health per ICI slice without re-walking
# every chip entry of every annotation.
ANNO_HEALTH_SUMMARY = ANNO_PREFIX + "health-summary"

# Per-key projections of the bind-time gang env (the DCN coordination
# contract TPU_KUBE_GANG_* — device/tpu.py ENV_GANG_*). The alloc
# annotation carries the same env as one JSON blob, but the downward API
# can only project a WHOLE annotation value into one env var — so the
# bind effector also writes each gang env key as its own annotation, and
# deploy/gang-job-example.yaml projects them 1:1 into container env.
GANG_ENV_TO_ANNO = {
    "TPU_KUBE_GANG_NUM_SLICES": ANNO_PREFIX + "gang-num-slices",
    "TPU_KUBE_GANG_SLICES": ANNO_PREFIX + "gang-slices",
    "TPU_KUBE_GANG_SLICE_INDEX": ANNO_PREFIX + "gang-slice-index",
}


def gang_env_annotations(env: dict[str, str]) -> dict[str, str]:
    """The per-key annotation projection of an alloc's gang env ({} for
    non-gang allocs — their pods get no gang annotations at all)."""
    return {
        anno: env[var]
        for var, anno in GANG_ENV_TO_ANNO.items()
        if var in env
    }


class CodecError(ValueError):
    pass


def _check_version(obj, what: str) -> None:
    if not isinstance(obj, dict):
        raise CodecError(f"{what}: payload must be a JSON object")
    v = obj.get("v")
    if v != SCHEMA_VERSION:
        raise CodecError(f"{what}: unsupported schema version {v!r}")


def _field(obj: dict, key: str, what: str):
    try:
        return obj[key]
    except (KeyError, TypeError) as e:
        raise CodecError(f"{what}: missing field {key!r}") from e


# -- node topology ---------------------------------------------------------

def encode_node_topology(node: NodeInfo, mesh: MeshSpec) -> str:
    """Serialize a node's chip inventory + the global mesh it sits in."""
    return json.dumps(
        {
            "v": SCHEMA_VERSION,
            "node": node.name,
            "slice": node.slice_id,
            "mesh": mesh.to_json(),
            "sharesPerChip": node.shares_per_chip,
            "chips": [
                {
                    "id": c.chip_id,
                    "index": c.index,
                    "coord": c.coord.as_list(),
                    "hbm": c.hbm_bytes,
                    "cores": c.num_cores,
                    "health": c.health.value,
                }
                for c in node.chips
            ],
            "badLinks": [
                [a.as_list(), b.as_list()] for a, b in node.bad_links
            ],
            **({"source": node.source} if node.source else {}),
        },
        separators=(",", ":"),
    )


def decode_node_topology(payload: str) -> tuple[NodeInfo, MeshSpec]:
    try:
        obj = json.loads(payload)
    except json.JSONDecodeError as e:
        raise CodecError(f"node-topology: bad JSON: {e}") from e
    _check_version(obj, "node-topology")
    try:
        mesh = MeshSpec.from_json(_field(obj, "mesh", "node-topology"))
    except (KeyError, TypeError, ValueError) as e:
        if isinstance(e, CodecError):
            raise
        raise CodecError(f"node-topology: malformed mesh: {e}") from e
    raw_chips = _field(obj, "chips", "node-topology")
    if not isinstance(raw_chips, list):
        raise CodecError("node-topology: 'chips' must be a list")
    try:
        chips = [
            ChipInfo(
                chip_id=c["id"],
                index=int(c["index"]),
                coord=TopologyCoord.of(c["coord"]),
                hbm_bytes=int(c["hbm"]),
                num_cores=int(c.get("cores", 2)),
                health=Health(c.get("health", "Healthy")),
            )
            for c in raw_chips
        ]
    except (KeyError, TypeError, ValueError) as e:
        raise CodecError(f"node-topology: malformed chip entry: {e}") from e
    try:
        shares = int(obj.get("sharesPerChip", 1))
    except (TypeError, ValueError) as e:
        raise CodecError(f"node-topology: bad sharesPerChip: {e}") from e
    if shares < 1:
        raise CodecError(f"node-topology: sharesPerChip must be >= 1, got {shares}")
    raw_links = obj.get("badLinks", [])
    if not isinstance(raw_links, list):
        raise CodecError("node-topology: 'badLinks' must be a list")
    try:
        bad_links = [canonical_link(a, b) for a, b in raw_links]
    except (TypeError, ValueError) as e:
        raise CodecError(f"node-topology: malformed badLinks entry: {e}") from e
    # A stale/buggy annotation carrying an out-of-mesh or non-adjacent pair
    # would otherwise flow silently into link-containment checks and veto
    # placements with no diagnostic; the C side (tpuinfo_inject_link_fault)
    # enforces adjacency, so enforce it here too (torus-aware).
    for a, b in bad_links:
        if not (mesh.contains(a) and mesh.contains(b)):
            raise CodecError(
                f"node-topology: badLinks endpoint outside mesh "
                f"{mesh.dims}: {[a.as_list(), b.as_list()]}"
            )
        if b not in mesh.neighbors(a):
            raise CodecError(
                f"node-topology: badLinks pair not ICI-adjacent: "
                f"{[a.as_list(), b.as_list()]}"
            )
    slice_id = obj.get("slice", DEFAULT_SLICE)
    if not isinstance(slice_id, str) or not slice_id:
        raise CodecError(f"node-topology: bad slice id {slice_id!r}")
    node = NodeInfo(
        name=_field(obj, "node", "node-topology"),
        chips=chips,
        shares_per_chip=shares,
        bad_links=bad_links,
        slice_id=slice_id,
        source=str(obj.get("source", "")),
    )
    return node, mesh


def annotate_node(node: NodeInfo, mesh: MeshSpec) -> dict[str, str]:
    return {
        ANNO_NODE_TOPOLOGY: encode_node_topology(node, mesh),
        ANNO_HEALTH_SUMMARY: encode_health_summary(health_summary(node)),
    }


# -- per-node health summary -------------------------------------------------

def chip_health_states(node: NodeInfo) -> dict[str, str]:
    """device id -> "healthy" | "degraded" | "unhealthy" for a node's
    whole chips. Degraded = the chip itself is up but touches a downed
    ICI link (its gang traffic rides a reduced path) — the state the
    fleet rollup and the telemetry sampler must agree on, so it is
    defined exactly once, here."""
    bad_ends = {c for link in node.bad_links for c in link}
    out: dict[str, str] = {}
    for chip in node.chips:
        if chip.health is not Health.HEALTHY:
            out[chip.device_id()] = "unhealthy"
        elif chip.coord in bad_ends:
            out[chip.device_id()] = "degraded"
        else:
            out[chip.device_id()] = "healthy"
    return out


def health_summary(node: NodeInfo) -> dict:
    """The compact summary document the node agent pushes upstream."""
    states = chip_health_states(node)
    return {
        "v": SCHEMA_VERSION,
        "node": node.name,
        "slice": node.slice_id,
        "healthy": sum(1 for s in states.values() if s == "healthy"),
        "degraded": sum(1 for s in states.values() if s == "degraded"),
        "unhealthy": sum(1 for s in states.values() if s == "unhealthy"),
        "badLinks": len(node.bad_links),
        "chips": states,
    }


def encode_health_summary(summary: dict) -> str:
    return json.dumps(summary, separators=(",", ":"), sort_keys=True)


def decode_health_summary(payload: str) -> dict:
    try:
        obj = json.loads(payload)
    except json.JSONDecodeError as e:
        raise CodecError(f"health-summary: bad JSON: {e}") from e
    _check_version(obj, "health-summary")
    for key in ("healthy", "degraded", "unhealthy"):
        if not isinstance(obj.get(key), int):
            raise CodecError(f"health-summary: missing/bad count {key!r}")
    return obj


def node_from_annotations(
    name: str, annotations: dict[str, str]
) -> Optional[tuple[NodeInfo, MeshSpec]]:
    payload = annotations.get(ANNO_NODE_TOPOLOGY)
    if payload is None:
        return None
    node, mesh = decode_node_topology(payload)
    if node.name != name:
        raise CodecError(
            f"node-topology annotation names {node.name!r} but lives on {name!r}"
        )
    node.annotations = dict(annotations)
    return node, mesh


# -- allocation result -----------------------------------------------------

def alloc_obj(alloc: AllocResult) -> dict:
    """The alloc payload's object form (see ``alloc_from_obj``)."""
    obj = {
        "v": SCHEMA_VERSION,
        "pod": alloc.pod_key,
        "node": alloc.node_name,
        "devices": alloc.device_ids,
        "coords": [c.as_list() for c in alloc.coords],
        "env": alloc.env,
        "priority": alloc.priority,
    }
    if alloc.uid:
        # optional, not a schema bump: pre-UID decoders ignore it, and
        # pre-UID payloads decode to uid="" (name-only semantics)
        obj["uid"] = alloc.uid
    return obj


def encode_alloc(alloc: AllocResult) -> str:
    return json.dumps(alloc_obj(alloc), separators=(",", ":"))


def decode_alloc(payload: str) -> AllocResult:
    try:
        obj = json.loads(payload)
    except json.JSONDecodeError as e:
        raise CodecError(f"alloc: bad JSON: {e}") from e
    _check_version(obj, "alloc")
    return alloc_from_obj(obj)


def alloc_from_obj(obj: dict) -> AllocResult:
    """An AllocResult from the alloc payload's PARSED object form —
    the checkpoint (sched/journal.py) stores allocs as plain objects so
    a warm restore skips ten thousand per-string ``json.loads`` calls;
    the wire decoder above shares this construction."""
    try:
        return AllocResult(
            pod_key=_field(obj, "pod", "alloc"),
            node_name=_field(obj, "node", "alloc"),
            device_ids=list(_field(obj, "devices", "alloc")),
            coords=[TopologyCoord.of(c) for c in obj.get("coords", [])],
            env=dict(obj.get("env", {})),
            priority=int(obj.get("priority", 0)),
            uid=str(obj.get("uid", "")),
        )
    except CodecError:
        raise
    except (TypeError, ValueError) as e:
        raise CodecError(f"alloc: malformed payload: {e}") from e


# -- pod group -------------------------------------------------------------

def pod_group_annotations(group: PodGroup) -> dict[str, str]:
    out = {
        ANNO_POD_GROUP: group.name,
        ANNO_POD_GROUP_MIN_MEMBER: str(group.min_member),
    }
    if group.shape is not None:
        out[ANNO_POD_GROUP_SHAPE] = "x".join(str(s) for s in group.shape)
    if group.allow_dcn:
        out[ANNO_POD_GROUP_ALLOW_DCN] = "true"
    return out


def pod_group_from_annotations(annotations: dict[str, str]) -> Optional[PodGroup]:
    name = annotations.get(ANNO_POD_GROUP)
    if not name:
        return None
    try:
        min_member = int(annotations.get(ANNO_POD_GROUP_MIN_MEMBER, "1"))
    except ValueError as e:
        raise CodecError("pod-group-min-member not an int") from e
    if min_member < 1:
        raise CodecError(f"pod-group-min-member must be >= 1, got {min_member}")
    shape_s = annotations.get(ANNO_POD_GROUP_SHAPE)
    shape = None
    if shape_s:
        parts = shape_s.split("x")
        if len(parts) not in (1, 2, 3) or not all(p.isdigit() for p in parts):
            raise CodecError(f"bad pod-group-shape {shape_s!r}")
        vals = [int(p) for p in parts] + [1, 1]
        shape = (vals[0], vals[1], vals[2])
        if any(v < 1 for v in shape):
            raise CodecError(f"pod-group-shape dims must be >= 1: {shape_s!r}")
    allow_dcn = annotations.get(ANNO_POD_GROUP_ALLOW_DCN, "").lower() in (
        "1", "true", "yes"
    )
    if allow_dcn and shape is not None:
        raise CodecError(
            "pod-group-allow-dcn is incompatible with pod-group-shape "
            "(a shape names one contiguous box)"
        )
    return PodGroup(
        name=name, min_member=min_member, shape=shape, allow_dcn=allow_dcn
    )


def attach_group(pod: PodInfo) -> PodInfo:
    """Populate pod.group from its annotations (idempotent)."""
    if pod.group is None:
        pod.group = pod_group_from_annotations(pod.annotations)
    return pod

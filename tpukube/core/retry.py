"""Unified retry / backoff / circuit-breaker policy (ISSUE 4 tentpole).

Every control-plane seam used to handle failure ad hoc: RestApiServer
raised on the first error, the eviction executor's GET confirms relied
on the next poll, informer reconnects waited exactly one poll interval
however long the apiserver had been down, and the plugin's kubelet
registration carried a bare "the watcher will retry" note. This module
is the one policy object they all route through:

  * :class:`RetryPolicy`   — jittered exponential backoff with a
    max-attempt cap, a per-attempt timeout hint, and an overall
    deadline (the retry loop's wall budget).
  * :class:`Backoff`       — the policy's delay sequence as a stateful
    object, for reconnect loops that back off across iterations rather
    than inside one call (informer reconnects).
  * :class:`Retrier`       — executes a callable under a policy,
    counting attempts/retries/exhaustions for /metrics and emitting
    ``RetryExhausted`` into an event journal when it gives up.
  * :class:`CircuitBreaker` — consecutive-failure trip wire with
    half-open probing. While open, callers fail fast instead of
    stacking timeouts; the extender's degraded mode keys off this
    (fail filter requests safe while the apiserver circuit is open).

Everything time- and randomness-dependent is injectable (``clock``,
``sleep``, ``rng``) so tests and the chaos scenarios are deterministic.
Defaults preserve pre-ISSUE-4 behavior: a Retrier is only consulted
where one is wired, and a CircuitBreaker with ``failure_threshold=0``
never trips (config ships circuits disabled).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

log = logging.getLogger("tpukube.retry")

#: breaker states, exported as the tpukube_circuit_state gauge
CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff: attempt ``n`` (1-based) failing
    sleeps ``min(max_delay, base_delay * 2**(n-1))`` scaled down by up
    to ``jitter`` (full-jitter style: a fleet of retriers must not
    re-dogpile the apiserver in lockstep). ``deadline`` caps the whole
    call's wall budget (0 = unbounded); ``attempt_timeout`` is the
    per-attempt budget hint callers pass to their transport (0 = use
    the transport's own default)."""

    max_attempts: int = 5
    base_delay: float = 0.1
    max_delay: float = 5.0
    jitter: float = 0.5
    deadline: float = 30.0
    attempt_timeout: float = 0.0

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Sleep before retry number ``attempt`` (1-based failures)."""
        d = min(self.max_delay, self.base_delay * (2 ** max(0, attempt - 1)))
        if self.jitter > 0:
            d *= 1.0 - self.jitter * rng.random()
        return d


def policy_from_config(cfg) -> RetryPolicy:
    """The one translation from TpuKubeConfig retry_* knobs."""
    return RetryPolicy(
        max_attempts=cfg.retry_max_attempts,
        base_delay=cfg.retry_base_delay_seconds,
        max_delay=cfg.retry_max_delay_seconds,
        jitter=cfg.retry_jitter,
        deadline=cfg.retry_deadline_seconds,
        attempt_timeout=cfg.retry_attempt_timeout_seconds,
    )


class Backoff:
    """The policy's delay sequence as reusable state, for loops that
    back off BETWEEN iterations (informer reconnects): ``next()``
    returns the delay for one more consecutive failure, ``reset()``
    re-arms after success. Thread-compatible (each loop owns one)."""

    def __init__(self, base: float, cap: float, jitter: float = 0.5,
                 rng: Optional[random.Random] = None) -> None:
        self._policy = RetryPolicy(base_delay=base, max_delay=cap,
                                   jitter=jitter)
        self._rng = rng or random.Random()
        self.failures = 0

    def next(self) -> float:
        self.failures += 1
        return self._policy.delay(self.failures, self._rng)

    def reset(self) -> None:
        self.failures = 0


class RetryStats:
    """Thread-safe counters one Retrier exports on /metrics."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._attempts = 0
        self._retries = 0
        self._exhausted = 0

    def note(self, attempts: int, exhausted: bool) -> None:
        with self._lock:
            self._attempts += attempts
            self._retries += attempts - 1
            if exhausted:
                self._exhausted += 1

    @property
    def attempts(self) -> int:
        with self._lock:
            return self._attempts

    @property
    def retries(self) -> int:
        with self._lock:
            return self._retries

    @property
    def exhausted(self) -> int:
        with self._lock:
            return self._exhausted


def _default_retryable(exc: BaseException) -> bool:
    """Fallback classifier: retry ordinary failures, never programming
    errors or interpreter-level signals (KeyboardInterrupt/SystemExit
    must propagate immediately). Callers with richer error taxonomies
    pass their own predicate."""
    if not isinstance(exc, Exception):
        return False
    return not isinstance(exc, (TypeError, KeyError, AttributeError))


class Retrier:
    """Executes callables under one RetryPolicy, with optional circuit
    integration: every attempt consults ``circuit`` first (an open
    circuit raises :class:`CircuitOpenError` without calling the
    target) and reports its outcome back to the breaker."""

    def __init__(
        self,
        policy: RetryPolicy,
        name: str,
        retryable: Callable[[BaseException], bool] = _default_retryable,
        circuit: Optional["CircuitBreaker"] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        journal=None,
    ) -> None:
        self.policy = policy
        self.name = name
        self.stats = RetryStats()
        self._retryable = retryable
        self._circuit = circuit
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()
        self.journal = journal
        # attempts consumed by the most recent call() — single-threaded
        # callers (the kubelet session watcher) read this to learn
        # whether success needed a retry
        self.last_attempts = 0

    def _emit_exhausted(self, err: BaseException, attempts: int) -> None:
        if self.journal is None:
            return
        try:
            self.journal.emit(
                "RetryExhausted", obj=f"retry/{self.name}",
                message=f"gave up after {attempts} attempt(s): {err}",
                type="Warning",
            )
        except Exception:
            log.exception("event emit failed: RetryExhausted")

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` until success, a non-retryable error, attempt
        exhaustion, or the deadline. Raises the last error."""
        start = self._clock()
        attempt = 0
        while True:
            attempt += 1
            if self._circuit is not None:
                self._circuit.before_call()  # CircuitOpenError when open
            try:
                out = fn()
            except CircuitOpenError:
                # the breaker tripped between before_call and a nested
                # guard: not a target failure, never retried here
                self.last_attempts = attempt
                self.stats.note(attempt, exhausted=False)
                raise
            except BaseException as e:
                retryable = self._retryable(e)
                if self._circuit is not None:
                    if not isinstance(e, Exception):
                        # interrupted, not answered: release any
                        # half-open probe slot without judging
                        self._circuit.abort_probe()
                    elif retryable:
                        self._circuit.on_failure()
                    else:
                        # a non-transient answer (404/409/429-shaped)
                        # means the dependency is HEALTHY — it must
                        # not trip the breaker into degraded mode
                        self._circuit.on_success()
                delay = self.policy.delay(attempt, self._rng)
                over_deadline = (
                    self.policy.deadline > 0
                    and self._clock() - start + delay > self.policy.deadline
                )
                if (not retryable or attempt >= self.policy.max_attempts
                        or over_deadline):
                    self.last_attempts = attempt
                    self.stats.note(attempt, exhausted=retryable)
                    if retryable:
                        why = ("deadline" if over_deadline else
                               "max attempts")
                        log.warning("%s: giving up after %d attempt(s) "
                                    "(%s): %s", self.name, attempt, why, e)
                        self._emit_exhausted(e, attempt)
                    raise
                log.info("%s: attempt %d failed (%s); retrying in %.3fs",
                         self.name, attempt, e, delay)
                self._sleep(delay)
                continue
            if self._circuit is not None:
                self._circuit.on_success()
            self.last_attempts = attempt
            self.stats.note(attempt, exhausted=False)
            return out


class CircuitOpenError(RuntimeError):
    """Raised by a breaker guard while the circuit is open: the caller
    fails fast instead of stacking timeouts onto a dead dependency."""


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    Closed -> ``failure_threshold`` consecutive failures -> open.
    Open   -> after ``reset_seconds`` -> half-open, admitting up to
    ``half_open_probes`` in-flight probe calls. A probe success closes
    the circuit (and resets the failure count); a probe failure
    re-opens it for another ``reset_seconds``.

    ``failure_threshold=0`` disables the breaker entirely (every guard
    is a no-op) — the config default, preserving legacy behavior.
    Transitions are journaled as ``CircuitOpen`` / ``CircuitClosed``.
    """

    def __init__(self, failure_threshold: int, reset_seconds: float,
                 name: str, half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 journal=None) -> None:
        self.failure_threshold = failure_threshold
        self.reset_seconds = reset_seconds
        self.half_open_probes = max(1, half_open_probes)
        self.name = name
        self.journal = journal
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0       # consecutive, while closed
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.opens = 0           # cumulative trips (metrics)

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def state(self) -> str:
        with self._lock:
            return self._effective_state_locked()

    def state_code(self) -> int:
        """0 closed / 1 half-open / 2 open (the metrics gauge)."""
        return _STATE_CODE[self.state()]

    def is_open(self) -> bool:
        """True only while calls are being refused (open, before the
        reset window elapses) — the degraded-mode gate."""
        return self.state() == OPEN

    def _effective_state_locked(self) -> str:
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.reset_seconds):
            self._state = HALF_OPEN
            self._probes_in_flight = 0
        return self._state

    def _emit(self, reason: str, message: str, warning: bool) -> None:
        if self.journal is None:
            return
        try:
            self.journal.emit(
                reason, obj=f"circuit/{self.name}", message=message,
                type="Warning" if warning else "Normal",
            )
        except Exception:
            log.exception("event emit failed: %s", reason)

    def before_call(self) -> None:
        """Admission guard: raises CircuitOpenError while open; in
        half-open, admits only the probe budget."""
        if not self.enabled:
            return
        with self._lock:
            state = self._effective_state_locked()
            if state == CLOSED:
                return
            if state == HALF_OPEN:
                if self._probes_in_flight < self.half_open_probes:
                    self._probes_in_flight += 1
                    return
                raise CircuitOpenError(
                    f"circuit {self.name}: half-open, probe budget "
                    f"({self.half_open_probes}) in flight"
                )
            remaining = self.reset_seconds - (
                self._clock() - self._opened_at
            )
            raise CircuitOpenError(
                f"circuit {self.name}: open for another "
                f"{max(0.0, remaining):.1f}s"
            )

    def abort_probe(self) -> None:
        """Release a half-open probe slot without judging the outcome
        (the probed call was interrupted — KeyboardInterrupt, nested
        open circuit — not answered). Without this, an aborted probe
        would pin the breaker half-open with its budget consumed
        forever."""
        if not self.enabled:
            return
        with self._lock:
            if (self._effective_state_locked() == HALF_OPEN
                    and self._probes_in_flight > 0):
                self._probes_in_flight -= 1

    def on_success(self) -> None:
        if not self.enabled:
            return
        closed_now = False
        with self._lock:
            state = self._effective_state_locked()
            if state == HALF_OPEN:
                self._state = CLOSED
                self._probes_in_flight = 0
                closed_now = True
            self._failures = 0
        if closed_now:
            log.warning("circuit %s: probe succeeded; closed", self.name)
            self._emit("CircuitClosed",
                       "half-open probe succeeded; traffic restored",
                       warning=False)

    def on_failure(self) -> None:
        if not self.enabled:
            return
        opened_now = False
        with self._lock:
            state = self._effective_state_locked()
            if state == HALF_OPEN:
                # the probe failed: re-open for a fresh reset window
                self._state = OPEN
                self._opened_at = self._clock()
                self._probes_in_flight = 0
                self.opens += 1
                opened_now = True
            elif state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._state = OPEN
                    self._opened_at = self._clock()
                    self.opens += 1
                    opened_now = True
        if opened_now:
            log.error("circuit %s: opened (threshold %d); failing fast "
                      "for %.1fs", self.name, self.failure_threshold,
                      self.reset_seconds)
            self._emit(
                "CircuitOpen",
                f"tripped after {self.failure_threshold} consecutive "
                f"failure(s); failing fast for {self.reset_seconds:g}s",
                warning=True,
            )

    def call(self, fn: Callable[[], Any]) -> Any:
        """Guarded single call (no retries): admission, then outcome
        bookkeeping."""
        self.before_call()
        try:
            out = fn()
        except CircuitOpenError:
            # raised by a NESTED guard: our own admitted slot (possibly
            # a probe) was never answered — release it
            self.abort_probe()
            raise
        except Exception:
            self.on_failure()
            raise
        except BaseException:
            self.abort_probe()
            raise
        self.on_success()
        return out

"""Injectable clocks (ISSUE 8 — the discrete-event sim half).

Every time-dependent control-plane mechanism — gang reservation TTLs,
the extender's pending-webhook pruning, eviction-confirm ages,
retry/backoff sleeps (``core/retry.py`` already takes ``clock``/
``sleep``) — reads time through one of these objects instead of the
``time`` module, so the sim harness can compress hours of simulated
churn into seconds of wall time:

  * :class:`SystemClock` — the production clock: thin pass-throughs to
    ``time.monotonic``/``time.time``/``time.sleep``. The default
    everywhere, so nothing changes for the daemons.
  * :class:`FakeClock`  — a discrete-event clock: ``sleep``/``advance``
    move simulated time forward instantly, firing any timers scheduled
    with :meth:`schedule` in deadline order (each callback observes
    ``monotonic()`` == its own deadline, the discrete-event contract).

Only *scheduling-semantic* time goes through the clock (TTL expiry,
age gauges, backoff delays). Latency MEASUREMENT stays on the real
``time.perf_counter``/``time.monotonic`` — a fake-clock run must still
report how long the scheduler actually took, not zero.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Any, Callable


class SystemClock:
    """The real clock. One shared instance (:data:`SYSTEM`) is enough —
    it holds no state."""

    def monotonic(self) -> float:
        return time.monotonic()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


#: process-wide default; ``clock=None`` parameters resolve to this
SYSTEM = SystemClock()


class FakeClock:
    """Discrete-event fake clock for the sim harness.

    ``sleep(s)`` and ``advance(s)`` move simulated time forward and run
    every timer whose deadline falls inside the window, in deadline
    order (FIFO among equal deadlines). Timer callbacks run on the
    advancing thread with the clock set to their own deadline — a
    callback scheduling another timer inside the window is honored in
    the same advance. Thread-safe: the sim's effector threads may read
    ``monotonic()`` while a scenario thread advances.

    ``epoch`` anchors ``time()`` (wall clock) so journal/statusz
    timestamps stay plausible; ``monotonic()`` starts at 0.0 like a
    freshly booted process.
    """

    def __init__(self, epoch: float = 1_700_000_000.0) -> None:
        self._lock = threading.RLock()
        self._now = 0.0
        self._epoch = epoch
        self._seq = itertools.count()  # FIFO tie-break among deadlines
        self._timers: list[tuple[float, int, Callable[[], Any]]] = []

    def monotonic(self) -> float:
        with self._lock:
            return self._now

    def time(self) -> float:
        with self._lock:
            return self._epoch + self._now

    def sleep(self, seconds: float) -> None:
        """A fake sleep IS an advance: the sleeper's wait elapses
        instantly in wall time while every timer due in the window
        fires exactly as it would have during a real sleep."""
        self.advance(seconds)

    def schedule(self, delay: float, fn: Callable[[], Any]) -> None:
        """Run ``fn`` once ``delay`` seconds of simulated time elapse
        (fires during the ``advance``/``sleep`` that crosses it)."""
        with self._lock:
            heapq.heappush(
                self._timers,
                (self._now + max(0.0, delay), next(self._seq), fn),
            )

    def pending_timers(self) -> int:
        with self._lock:
            return len(self._timers)

    def advance(self, seconds: float) -> None:
        """Advance simulated time by ``seconds`` (>= 0), firing due
        timers in deadline order. Callbacks run OUTSIDE the clock's
        internal lock (they may read the clock or schedule more work)."""
        if seconds < 0:
            raise ValueError(f"cannot advance time backwards ({seconds})")
        with self._lock:
            target = self._now + seconds
        while True:
            fn = None
            with self._lock:
                if self._timers and self._timers[0][0] <= target:
                    deadline, _, fn = heapq.heappop(self._timers)
                    # the discrete-event contract: the callback observes
                    # the clock AT its own deadline
                    self._now = max(self._now, deadline)
                else:
                    self._now = target
            if fn is None:
                return
            fn()

"""TPU device manager (L2) — the reference's ``device/nvidia/`` analog.

SURVEY.md §2 C3: the reference's Device impl discovers GPUs via NVML, builds
the topology tree, translates container requests into tree resources, and
does node-local allocation bookkeeping. The TPU version discovers chips via
libtpuinfo (C++/ctypes), models the ICI mesh, and mints device ids:

  whole chips      -> ``qiniu.com/tpu``   ids ``tpu-<i>``
  fractional vTPUs -> ``qiniu.com/vtpu``  ids ``tpu-<i>-frac<k>of<n>``

Sharing policy: ``shares_per_chip`` is a node-level mode switch. A node
either advertises whole chips (shares_per_chip == 1) or vTPU shares (> 1),
never both — advertising both would let the kubelet double-book a chip,
since extended-resource accounting is per-resource. This mirrors the
GPU-world practice of dedicating node pools to fractional sharing.

Allocation responses carry env only (no /dev device nodes: TPU runtimes in
pods reach chips through the platform's own device plumbing; what they need
from us is which chips are theirs and how much HBM they may map —
SURVEY.md §4.3).
"""

from __future__ import annotations

import threading
from typing import Optional

from tpukube.core.config import TpuKubeConfig
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    ChipInfo,
    Health,
    NodeInfo,
    TopologyCoord,
    VtpuShare,
    canonical_link,
    make_device_id,
    parse_device_id,
)
from tpukube.native import TpuInfo, sim_spec

# Env exported to allocated containers. TPU_VISIBLE_DEVICES is the real
# libtpu env gating chip visibility; the TPU_KUBE_* keys carry mesh context
# so the in-pod JAX job can build its jax.sharding.Mesh; the HBM keys are
# the cooperative quota channel (XLA client respects MEM_FRACTION).
ENV_VISIBLE_DEVICES = "TPU_VISIBLE_DEVICES"
ENV_KUBE_DEVICE_IDS = "TPU_KUBE_DEVICE_IDS"
ENV_KUBE_CHIP_COORDS = "TPU_KUBE_CHIP_COORDS"
ENV_KUBE_MESH_DIMS = "TPU_KUBE_MESH_DIMS"
ENV_KUBE_HOST = "TPU_KUBE_HOST"
ENV_KUBE_SLICE = "TPU_KUBE_SLICE_ID"  # ICI domain (multi-slice clusters)
# Gang slice context for DCN-spanning gangs. PRODUCED by the extender in
# the alloc annotation (the device plugin's Allocate only sees device ids);
# consumed by tpukube.workload.meshenv. Defined here so producer and
# consumer share one set of names.
ENV_GANG_NUM_SLICES = "TPU_KUBE_GANG_NUM_SLICES"
ENV_GANG_SLICES = "TPU_KUBE_GANG_SLICES"
ENV_GANG_SLICE_INDEX = "TPU_KUBE_GANG_SLICE_INDEX"
# Tenant identity for the multi-tenant serving plane (tpukube/tenancy).
# PRODUCED by the extender in the alloc annotation when tenancy is on
# (like the gang env: the device plugin's Allocate sees only device
# ids, so tenant attribution must ride the annotation); consumed by
# the TenantLedger for restart-survivable per-tenant fractional
# accounting and by tpukube.workload.meshenv so the in-pod runtime
# knows whose HBM quota its XLA_PYTHON_CLIENT_MEM_FRACTION enforces.
ENV_KUBE_TENANT = "TPU_KUBE_TENANT"
ENV_HBM_LIMIT = "TPU_HBM_LIMIT_BYTES"
ENV_MEM_FRACTION = "XLA_PYTHON_CLIENT_MEM_FRACTION"
# vTPU TensorCore partition (BASELINE: "partitions TPU HBM and TensorCores"):
# when shares divide a chip's cores evenly, each share owns dedicated
# core(s) — "chip:coreA+coreB;chip:core" per allocated chip. Cooperative,
# like the HBM limit (see README trust model).
ENV_KUBE_CORE_IDS = "TPU_KUBE_CORE_IDS"


class DeviceError(RuntimeError):
    pass


class TpuDeviceManager:
    """Owns the node's libtpuinfo session and all device-id minting."""

    def __init__(
        self,
        config: TpuKubeConfig,
        host: Optional[str] = None,
        libtpu_path: Optional[str] = None,
    ):
        self._config = config
        self._lock = threading.Lock()
        self._host = host or "host-0-0-0"
        # telemetry state (telemetry_snapshot): sample tick for the sim
        # synthesis + per-chip cumulative ICI link-error counters
        self._telemetry_ticks = 0
        self._link_error_counts: dict[int, int] = {}
        if config.backend == "sim":
            origin = None
            if config.sim_host_origin:
                x, y, z = config.sim_host_origin.split(",")
                origin = (int(x), int(y), int(z))
            spec = sim_spec(
                config.sim_mesh(),
                self._host,
                config.hbm_bytes_per_chip,
                config.cores_per_chip,
                origin=origin,
            )
            self._ti = TpuInfo("sim", spec)
        else:
            libtpu_path = libtpu_path or config.libtpu_path
            spec = ""
            if libtpu_path:
                spec += f"libtpu={libtpu_path}\n"
            if config.probe_mode:
                spec += f"probe={config.probe_mode}\n"
            self._ti = TpuInfo("real", spec or None)
        self._mesh = self._ti.mesh()
        if (
            config.backend == "real"
            and any(config.real_torus)
            and not any(self._mesh.torus)
        ):
            # the runtime reported no wrap flags (bounding-box mesh);
            # operator config supplies the real geometry
            self._mesh = MeshSpec(
                dims=self._mesh.dims,
                host_block=self._mesh.host_block,
                torus=config.real_torus,
            )

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._ti.close()

    def __enter__(self) -> "TpuDeviceManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- discovery ---------------------------------------------------------
    @property
    def mesh(self) -> MeshSpec:
        return self._mesh

    @property
    def host(self) -> str:
        return self._host

    @property
    def resource_name(self) -> str:
        """The one extended resource this node advertises (see module doc)."""
        if self._config.shares_per_chip > 1:
            return self._config.resource_vtpu
        return self._config.resource_tpu

    def chips(self) -> list[ChipInfo]:
        return self._ti.chips()

    def node_info(self) -> NodeInfo:
        chips = self.chips()
        mine = {c.coord for c in chips}
        # a node agent reports only the downed links it can see: those with
        # at least one endpoint on this host (the far host reports its side;
        # the scheduler dedupes on the canonical pair)
        bad_links = [
            (a, b) for a, b in self._ti.link_faults()
            if a in mine or b in mine
        ]
        return NodeInfo(
            name=self._host,
            chips=chips,
            shares_per_chip=self._config.shares_per_chip,
            bad_links=bad_links,
            slice_id=self._config.slice_id,
            source=self._ti.source(),
        )

    def inventory_source(self) -> str:
        """Where the inventory came from: "sim", "pjrt", or "table (...)"."""
        return self._ti.source()

    def link_fault_snapshot(self) -> list:
        """Downed ICI links visible to this node (node_info's badLinks),
        canonical pairs, sorted — the health watcher diffs this so link
        faults re-annotate the Node just like chip faults. Delegates to
        node_info() so the visibility rule lives in exactly one place."""
        return sorted(self.node_info().bad_links)

    def probe(self) -> bool:
        """Run the backend's health canary (no-op True on sim); chips()
        and health_snapshot() reflect the outcome."""
        return self._ti.probe()

    def shares_of(self, chip: ChipInfo) -> list[VtpuShare]:
        n = self._config.shares_per_chip
        quota = chip.hbm_bytes // n
        return [VtpuShare(chip.index, k, n, quota) for k in range(n)]

    def device_list(self) -> list[tuple[str, Health]]:
        """(device_id, health) pairs advertised on ListAndWatch."""
        out: list[tuple[str, Health]] = []
        for chip in self.chips():
            if self._config.shares_per_chip > 1:
                out.extend((s.device_id(), chip.health) for s in self.shares_of(chip))
            else:
                out.append((chip.device_id(), chip.health))
        return out

    def health_snapshot(self) -> dict[str, Health]:
        return dict(self.device_list())

    # -- allocation --------------------------------------------------------
    def allocate_env(self, device_ids: list[str]) -> dict[str, str]:
        """Build the container env for an Allocate of ``device_ids``.

        Whole-chip and fractional ids cannot mix (they are different
        resources; the kubelet never mixes them in one request — rejecting
        here guards against a confused caller).
        """
        with self._lock:
            if not device_ids:
                raise DeviceError("empty device list")
            by_index = {c.index: c for c in self.chips()}

            def chip_at(index: int) -> ChipInfo:
                if index not in by_index:
                    raise DeviceError(f"unknown chip index {index} on {self._host}")
                return by_index[index]

            shares_mode = self._config.shares_per_chip > 1
            chip_indices: list[int] = []
            shares_per_chip_alloc: dict[int, int] = {}
            share_ks: dict[int, list[int]] = {}  # chip index -> share ks
            hbm_limit = 0
            seen: set[str] = set()
            for did in device_ids:
                if did in seen:
                    raise DeviceError(f"duplicate device id {did}")
                seen.add(did)
                try:
                    index, frac = parse_device_id(did)
                except ValueError as e:
                    raise DeviceError(str(e)) from e
                chip = chip_at(index)
                if chip.health is not Health.HEALTHY:
                    raise DeviceError(f"device {did} is unhealthy")
                if shares_mode:
                    if frac is None:
                        raise DeviceError(
                            f"{did}: node is in vTPU mode; whole-chip id rejected"
                        )
                    k, n = frac
                    if n != self._config.shares_per_chip or not 0 <= k < n:
                        raise DeviceError(f"{did}: share does not match node config")
                    hbm_limit += chip.hbm_bytes // n
                    shares_per_chip_alloc[index] = shares_per_chip_alloc.get(index, 0) + 1
                    share_ks.setdefault(index, []).append(k)
                else:
                    if frac is not None:
                        raise DeviceError(
                            f"{did}: node is in whole-chip mode; vTPU id rejected"
                        )
                    hbm_limit += chip.hbm_bytes
                if index not in chip_indices:
                    chip_indices.append(index)

            chip_indices.sort()
            coords = [chip_at(i).coord for i in chip_indices]
            env = {
                ENV_VISIBLE_DEVICES: ",".join(str(i) for i in chip_indices),
                ENV_KUBE_DEVICE_IDS: ",".join(sorted(seen)),
                ENV_KUBE_CHIP_COORDS: ";".join(
                    ",".join(str(v) for v in c) for c in coords
                ),
                ENV_KUBE_MESH_DIMS: ",".join(str(d) for d in self._mesh.dims),
                ENV_KUBE_HOST: self._host,
                ENV_KUBE_SLICE: self._config.slice_id,
                ENV_HBM_LIMIT: str(hbm_limit),
            }
            if shares_mode:
                # Cooperative enforcement for the in-pod XLA client. XLA
                # applies MEM_FRACTION per visible device, so the safe cap
                # is the MOST-constrained chip's share fraction — with
                # uneven shares per chip a pooled average would over-grant
                # the chip holding fewer shares.
                n = self._config.shares_per_chip
                min_shares = min(shares_per_chip_alloc.values())
                env[ENV_MEM_FRACTION] = f"{min_shares / n:.4f}"
                # TensorCore partition: when shares divide a chip's cores
                # evenly, share k owns cores [k*cps, (k+1)*cps). With more
                # shares than cores the cores are time-shared and no core
                # assignment is emitted (HBM-only partitioning).
                parts = []
                for index in chip_indices:
                    cores = chip_at(index).num_cores
                    if cores % n != 0:
                        parts = []
                        break
                    cps = cores // n
                    owned = sorted(
                        c
                        for k in share_ks[index]
                        for c in range(k * cps, (k + 1) * cps)
                    )
                    parts.append(f"{index}:{'+'.join(map(str, owned))}")
                if parts:
                    env[ENV_KUBE_CORE_IDS] = ";".join(parts)
            return env

    def preferred_allocation(
        self,
        available: list[str],
        required: list[str],
        size: int,
    ) -> list[str]:
        """Pick ``size`` devices maximizing ICI adjacency within this host.

        The reference's GetPreferredAllocation picks NVLink-connected GPU
        sets; here we greedily grow a connected set in mesh-neighbor space
        starting from the required ids (SURVEY.md §2 C4).
        """
        if size < len(required):
            raise DeviceError("allocation_size smaller than must-include set")
        if size > len(available):
            raise DeviceError("allocation_size larger than available set")
        avail = list(dict.fromkeys(available))
        for r in required:
            if r not in avail:
                raise DeviceError(f"must-include id {r} not in available set")

        by_index = {c.index: c for c in self.chips()}
        coords = {}
        chip_of = {}
        for did in avail:
            try:
                index, _ = parse_device_id(did)
            except ValueError as e:
                raise DeviceError(str(e)) from e
            if index not in by_index:
                raise DeviceError(f"unknown chip index {index} on {self._host}")
            chip = by_index[index]
            if chip.health is not Health.HEALTHY:
                if did in required:
                    raise DeviceError(f"must-include id {did} is unhealthy")
                continue  # never recommend a chip Allocate would reject
            coords[did] = chip.coord
            chip_of[did] = index
        healthy_avail = [d for d in avail if d in coords]
        if size > len(healthy_avail):
            raise DeviceError(
                f"only {len(healthy_avail)} healthy devices for size {size}"
            )

        broken = set(self._ti.link_faults())

        def affinity(a: str, b: str) -> int:
            # Two shares of one chip beat mesh neighbors: zero-hop co-location.
            if chip_of[a] == chip_of[b]:
                return 2
            if coords[a] not in self._mesh.neighbors(coords[b]):
                return 0
            # a dead ICI link is no affinity at all — recommending chips
            # joined only by it would hand the pod a degraded pair
            if canonical_link(coords[a], coords[b]) in broken:
                return 0
            return 1

        chosen: list[str] = list(required)
        while len(chosen) < size:
            best, best_score = None, (-1, 0)
            for cand in healthy_avail:
                if cand in chosen:
                    continue
                adj = sum(affinity(cand, other) for other in chosen)
                # tie-break deterministically by available-list position
                score = (adj, -healthy_avail.index(cand))
                if best is None or score > best_score:
                    best, best_score = cand, score
            assert best is not None
            chosen.append(best)
        return chosen

    # -- telemetry ---------------------------------------------------------
    def telemetry_snapshot(self) -> list:
        """One per-chip telemetry sample set (obs.health.ChipTelemetry):
        health, HBM occupancy, duty cycle, and a cumulative ICI
        link-error counter. The sim backend SYNTHESIZES occupancy/duty
        deterministically from (tick, chip index) — enough signal for
        the sampler's rolling windows and the /metrics series to be
        exercised end to end; the real backend reports zeros there
        (libtpu exposes no public per-chip utilization counters) while
        health and link errors stay truthful. Link errors accumulate
        one count per poll per downed link endpoint on the chip — a
        counter shaped like a real lane-error counter, so the
        Prometheus rate() alert on it behaves identically on sim and
        real clusters."""
        from tpukube.obs.health import ChipTelemetry

        chips = self.chips()
        bad_ends: dict[TopologyCoord, int] = {}
        for a, b in self._ti.link_faults():
            for end in (TopologyCoord.of(a), TopologyCoord.of(b)):
                bad_ends[end] = bad_ends.get(end, 0) + 1
        sim = self._config.backend == "sim"
        out: list[ChipTelemetry] = []
        with self._lock:
            self._telemetry_ticks += 1
            tick = self._telemetry_ticks
            for c in chips:
                down = bad_ends.get(c.coord, 0)
                if down:
                    self._link_error_counts[c.index] = (
                        self._link_error_counts.get(c.index, 0) + down
                    )
                if sim and c.health is Health.HEALTHY:
                    duty = 55.0 + (tick * 7 + c.index * 13) % 40
                    hbm_used = c.hbm_bytes * (
                        35 + (tick * 3 + c.index * 5) % 50
                    ) // 100
                else:
                    duty, hbm_used = 0.0, 0
                out.append(ChipTelemetry(
                    device_id=c.device_id(),
                    index=c.index,
                    coord=c.coord,
                    health=c.health,
                    hbm_total_bytes=c.hbm_bytes,
                    hbm_used_bytes=hbm_used,
                    duty_cycle_percent=duty,
                    ici_link_errors=self._link_error_counts.get(c.index, 0),
                    links_down=down,
                ))
        return out

    # -- health / faults ---------------------------------------------------
    def inject_fault(self, chip_index: int, healthy: bool = False) -> None:
        """Sim-only: flip chip health (the NVML XID event analog)."""
        self._ti.inject_fault(chip_index, healthy)

    def inject_link_fault(self, a, b, up: bool = False) -> None:
        """Sim-only: drop (or restore) the ICI link between adjacent coords
        ``a``/``b`` — the NVLink lane-error analog (SURVEY.md §6)."""
        self._ti.inject_link_fault(a, b, up)

    def link_faults(self) -> list:
        """Downed ICI links visible to this session (canonical pairs)."""
        return self._ti.link_faults()

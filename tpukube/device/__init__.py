"""Device abstraction (L2): TPU discovery, device minting, allocation env."""

from tpukube.device.tpu import DeviceError, TpuDeviceManager  # noqa: F401

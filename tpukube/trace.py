"""Scheduling decision trace + deterministic replay.

SURVEY.md §6 ("Tracing / profiling"): the reference lineage has only glog
leveled logging; the blueprint adds an "optional JSON trace dump of
scheduling decisions for replay". This module is that subsystem.

Every webhook decision (filter / prioritize / bind) and every pod release
is recorded at the protocol boundary — the exact request JSON in, the
exact response JSON out — as one event. The stream is therefore a complete
transcript of the control plane: replaying it against a FRESH Extender
must reproduce byte-identical responses, because the extender is a pure
function of (pod, node annotations, ledger) and the ledger is itself built
only from these events. ``replay()`` performs that check, which doubles as
a determinism/regression harness: capture a trace from a live incident,
re-run it against a patched scheduler, diff the divergence point.

Events live in a bounded in-memory ring (this is a daemon) and optionally
stream to a JSONL file sink for post-mortem replay across restarts.
"""

from __future__ import annotations

import contextvars
import io
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

#: Cross-process trace context (the federated observability plane). The
#: shard router stamps a trace id + parent span id on every fanned call
#: (``X-Tpukube-Trace: <trace>/<span>``); the worker's HTTP layer sets
#: this contextvar for the request task, and ``record()`` below tags
#: events with it so ``tpukube-obs timeline --merge`` can nest worker
#: spans under the router's fan-out spans. A contextvar (not a thread
#: local): the worker handles requests on asyncio tasks, where
#: concurrent requests share one thread but never one context. Unset
#: (the None default) means no tagging at all — the N=1 in-process
#: event stream stays byte-identical to the pre-federation captures.
TRACE_CONTEXT: contextvars.ContextVar[Optional[dict]] = \
    contextvars.ContextVar("tpukube_trace_context", default=None)

# Event kinds. filter/prioritize/bind carry the webhook request/response
# verbatim; release carries the pod key (the apiserver-side pod deletion
# the extender observed); reconcile carries a kubelet device-id divergence
# report being folded into the ledger (apiserver.AllocReconcileLoop);
# upsert_node carries a node-annotation refresh applied outside any
# webhook (apiserver.NodeTopologyRefreshLoop — nodeCacheCapable mode's
# out-of-band topology channel), recorded so captures replay with the
# same node state the live extender saw; victim_gone carries an eviction
# victim's confirmed deletion (EvictionExecutor / lifecycle watch) —
# recorded because it unblocks gated gang binds, so replay must apply it
# at the same point in the stream.
KINDS = ("filter", "prioritize", "bind", "release", "reconcile",
         "upsert_node", "upsert_nodes", "victim_gone")

# Annotation kinds: pure observability markers (tpukube.obs.timeline
# span hooks — gang reserve, preemption plan, gang commit, plugin
# Allocate/intent-match). They mutate NOTHING and replay skips them;
# they exist so the per-pod timeline can show where time went between
# the decision events.
ANNOTATION_KINDS = ("span",)


class JsonlSink:
    """Size-capped JSONL file sink with a dedicated drain thread —
    shared by :class:`DecisionTrace` and the event journal
    (``tpukube.obs.events``).

    ``write()`` only enqueues (a deque append + condition notify): the
    file I/O happens on the sink's own daemon thread, so a stalled disk
    can never block an emitter — and emitters call from inside the gang
    manager's lock and the extender's decision paths, where one blocked
    write syscall would freeze every concurrent webhook. Lines are
    written in enqueue order (single drain thread). ``max_bytes`` caps
    the file: at the cap it rotates once to ``<path>.1`` (replacing the
    previous rotation) so incident captures on a long-lived daemon
    cannot fill the disk. ``close()`` drains what is queued, then joins
    the thread — call it before reading the file for a complete view.
    """

    def __init__(self, path: str, max_bytes: int = 0) -> None:
        self.path = path
        self.max_bytes = max_bytes
        self._cond = threading.Condition()
        self._pending: deque[str] = deque()
        self._closed = False
        # explicit utf-8: event/trace text embeds arbitrary runtime
        # strings (PJRT errors); a C-locale node must not drop a whole
        # drain batch to UnicodeEncodeError
        self._file: Optional[io.TextIOBase] = open(
            path, "a", buffering=1, encoding="utf-8"
        )
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        self._rotations = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tpukube-jsonl-sink",
        )
        self._thread.start()

    def write(self, line: str) -> None:
        """Enqueue one line (non-blocking; dropped after close)."""
        with self._cond:
            if self._closed:
                return
            self._pending.append(line)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                lines = list(self._pending)
                self._pending.clear()
                closing = self._closed
            try:
                self._write_out(lines)
            except Exception:
                # a sink failure must never kill the drain thread while
                # the daemon keeps emitting
                logging.getLogger("tpukube.trace").exception(
                    "JSONL sink write failed (%s)", self.path
                )
            if closing:
                return

    def _write_out(self, lines: list[str]) -> None:
        f = self._file
        if f is None:
            return
        for line in lines:
            # count ENCODED bytes: the cap guards disk, and multi-byte
            # text counted as characters would overshoot max_bytes 4x
            # (it is also what the getsize() seed above measures)
            nbytes = len(line.encode("utf-8"))
            if (self.max_bytes > 0 and self._bytes > 0
                    and self._bytes + nbytes > self.max_bytes):
                f.close()
                try:
                    os.replace(self.path, f"{self.path}.1")
                except OSError:
                    pass  # worst case we truncate in place below
                f = self._file = open(self.path, "w", buffering=1,
                                      encoding="utf-8")
                with self._cond:
                    self._bytes = 0
                    self._rotations += 1
            f.write(line)
            with self._cond:
                self._bytes += nbytes

    def stats(self) -> tuple[int, int]:
        """(bytes in the live file, rotations so far)."""
        with self._cond:
            return self._bytes, self._rotations

    def close(self) -> None:
        """Flush the queue, stop the drain thread, close the file.
        Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=10.0)
        if self._file is not None:
            self._file.close()
            self._file = None


@dataclass
class DecisionTrace:
    """Bounded ring of decision events, with an optional JSONL file sink.

    The sink is a :class:`JsonlSink`: recording only ENQUEUES the
    serialized line (under the ring lock, preserving seq order); the
    file write happens on the sink's drain thread, so disk latency
    never reaches the decision path. ``max_sink_bytes`` caps the sink
    file with one ``<path>.1`` rotation generation.
    """

    capacity: int = 65536
    path: Optional[str] = None
    max_sink_bytes: int = 0  # 0 = unlimited
    _events: deque = field(init=False)
    # default_factory resolves threading.Lock at INSTANCE creation (the
    # lambda), not at class definition: the dynamic lock-order monitor
    # (tpukube.analysis.lockgraph) patches the module attribute, and a
    # factory captured at import time would silently escape it
    _lock: threading.Lock = field(init=False,
                                  default_factory=lambda: threading.Lock())
    _seq: int = field(init=False, default=0)
    _sink: Optional[JsonlSink] = field(init=False, default=None)

    def __post_init__(self) -> None:
        self._events = deque(maxlen=self.capacity)
        if self.path:
            self._sink = JsonlSink(self.path, max_bytes=self.max_sink_bytes)

    def record(self, kind: str, request: Any, response: Any) -> dict:
        assert kind in KINDS or kind in ANNOTATION_KINDS, kind
        ctx = TRACE_CONTEXT.get()
        with self._lock:
            self._seq += 1
            ev = {
                "seq": self._seq,
                "ts": time.time(),
                "kind": kind,
                "request": request,
                "response": response,
            }
            if ctx is not None:
                # router-originated request: tag the event so merged
                # timelines can parent this decision under the fan-out
                # span (absent entirely outside sharded mode — replay
                # ignores it, goldens never see it)
                ev["ctx"] = dict(ctx)
            self._events.append(ev)
            if self._sink is not None:
                # enqueue under the ring lock so sink order IS seq order
                self._sink.write(json.dumps(ev, sort_keys=True) + "\n")
        return ev

    def span(self, name: str, pod_key: str, **fields: Any) -> dict:
        """Record one observability span marker attributed to a pod (the
        timeline correlates these with the decision events by pod key).
        ``fields`` must be JSON-able."""
        request = {"name": name, "pod_key": pod_key}
        request.update(fields)
        return self.record("span", request, None)

    def events(self, since_seq: int = 0) -> list[dict]:
        with self._lock:
            return [e for e in self._events if e["seq"] > since_seq]

    def stats(self) -> dict:
        """Ring statistics for /statusz: occupancy, total recorded, and
        how many events the bounded ring has already dropped (non-zero
        means an incident capture should use a file sink)."""
        sink_bytes, rotations = (
            self._sink.stats() if self._sink is not None else (None, 0)
        )
        with self._lock:
            return {
                "enabled": True,
                "capacity": self.capacity,
                "events": len(self._events),
                "last_seq": self._seq,
                "dropped": max(0, self._seq - len(self._events)),
                "sink_path": self.path or None,
                "sink_bytes": sink_bytes,
                "sink_rotations": rotations,
            }

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()


def load(path: str) -> list[dict]:
    """Read a JSONL trace file back into an event list. Undecodable
    lines are skipped (counted in a log warning): a daemon that crashed
    mid-write leaves a torn final line, and the capture's other ten
    thousand events are exactly what the incident investigation needs."""
    out: list[dict] = []
    bad = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
    if bad:
        logging.getLogger("tpukube.trace").warning(
            "%s: skipped %d undecodable line(s)", path, bad
        )
    return out


@dataclass
class Divergence:
    seq: int
    kind: str
    recorded: Any
    replayed: Any

    def __str__(self) -> str:  # human-readable diff summary for the CLI
        return (
            f"divergence at seq {self.seq} ({self.kind}):\n"
            f"  recorded: {json.dumps(self.recorded, sort_keys=True)[:400]}\n"
            f"  replayed: {json.dumps(self.replayed, sort_keys=True)[:400]}"
        )


def replay(
    events: Iterable[dict],
    extender: Optional[Any] = None,
    config: Optional[Any] = None,
    stop_on_divergence: bool = True,
) -> list[Divergence]:
    """Re-run a recorded decision stream against a fresh Extender and
    return every point where the replayed response differs.

    An empty result proves the scheduler is a deterministic function of
    its request stream (time-dependent behavior — gang TTL sweeps — only
    fires on inactivity gaps longer than the TTL, which a replay never
    reproduces, so a clean capture replays clean).
    """
    # local import: trace must stay importable from the extender module
    from tpukube.core.config import load_config
    from tpukube.sched.extender import Extender

    if extender is None:
        from dataclasses import replace as _dc_replace

        cfg = config or load_config(env={})
        # replay must not record (or append to the live trace/event
        # sinks!) — the replayed extender is a scratch instance
        extender = Extender(_dc_replace(
            cfg, trace_capacity=0, trace_path="", events_path="",
            decisions_path="",
        ))
    divergences: list[Divergence] = []

    def _check(ev: dict, replayed: Any) -> bool:
        if _canon(replayed) != _canon(ev["response"]):
            divergences.append(
                Divergence(ev["seq"], ev["kind"], ev["response"], replayed)
            )
            return stop_on_divergence
        return False

    for ev in events:
        kind, req = ev["kind"], ev["request"]
        if kind in ANNOTATION_KINDS:
            continue  # observability markers: nothing to re-dispatch
        if kind not in KINDS:  # newer trace format: report, don't crash
            divergences.append(Divergence(ev.get("seq", -1), kind, ev, None))
            if stop_on_divergence:
                break
            continue
        # replay through the SAME dispatch the live daemon uses (the
        # scratch extender has tracing disabled, so nothing re-records)
        try:
            replayed = extender.handle(kind, req)
        except Exception as e:  # tpukube: allow(exception-hygiene) the replay error IS the output — it lands in the divergence report the caller prints
            replayed = {"replayError": f"{type(e).__name__}: {e}"}
        if kind == "release":
            continue  # releases have no response to compare
        if _check(ev, replayed):
            break
    return divergences


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True)

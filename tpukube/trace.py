"""Scheduling decision trace + deterministic replay.

SURVEY.md §6 ("Tracing / profiling"): the reference lineage has only glog
leveled logging; the blueprint adds an "optional JSON trace dump of
scheduling decisions for replay". This module is that subsystem.

Every webhook decision (filter / prioritize / bind) and every pod release
is recorded at the protocol boundary — the exact request JSON in, the
exact response JSON out — as one event. The stream is therefore a complete
transcript of the control plane: replaying it against a FRESH Extender
must reproduce byte-identical responses, because the extender is a pure
function of (pod, node annotations, ledger) and the ledger is itself built
only from these events. ``replay()`` performs that check, which doubles as
a determinism/regression harness: capture a trace from a live incident,
re-run it against a patched scheduler, diff the divergence point.

Events live in a bounded in-memory ring (this is a daemon) and optionally
stream to a JSONL file sink for post-mortem replay across restarts.
"""

from __future__ import annotations

import io
import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

# Event kinds. filter/prioritize/bind carry the webhook request/response
# verbatim; release carries the pod key (the apiserver-side pod deletion
# the extender observed); reconcile carries a kubelet device-id divergence
# report being folded into the ledger (apiserver.AllocReconcileLoop);
# upsert_node carries a node-annotation refresh applied outside any
# webhook (apiserver.NodeTopologyRefreshLoop — nodeCacheCapable mode's
# out-of-band topology channel), recorded so captures replay with the
# same node state the live extender saw; victim_gone carries an eviction
# victim's confirmed deletion (EvictionExecutor / lifecycle watch) —
# recorded because it unblocks gated gang binds, so replay must apply it
# at the same point in the stream.
KINDS = ("filter", "prioritize", "bind", "release", "reconcile",
         "upsert_node", "victim_gone")

# Annotation kinds: pure observability markers (tpukube.obs.timeline
# span hooks — gang reserve, preemption plan, gang commit, plugin
# Allocate/intent-match). They mutate NOTHING and replay skips them;
# they exist so the per-pod timeline can show where time went between
# the decision events.
ANNOTATION_KINDS = ("span",)


@dataclass
class DecisionTrace:
    """Bounded ring of decision events, with an optional JSONL file sink."""

    capacity: int = 65536
    path: Optional[str] = None
    _events: deque = field(init=False)
    _lock: threading.Lock = field(init=False, default_factory=threading.Lock)
    _seq: int = field(init=False, default=0)
    _sink: Optional[io.TextIOBase] = field(init=False, default=None)

    def __post_init__(self) -> None:
        self._events = deque(maxlen=self.capacity)
        if self.path:
            self._sink = open(self.path, "a", buffering=1)  # line-buffered

    def record(self, kind: str, request: Any, response: Any) -> dict:
        assert kind in KINDS or kind in ANNOTATION_KINDS, kind
        with self._lock:
            self._seq += 1
            ev = {
                "seq": self._seq,
                "ts": time.time(),
                "kind": kind,
                "request": request,
                "response": response,
            }
            self._events.append(ev)
            if self._sink is not None:
                self._sink.write(json.dumps(ev, sort_keys=True) + "\n")
        return ev

    def span(self, name: str, pod_key: str, **fields: Any) -> dict:
        """Record one observability span marker attributed to a pod (the
        timeline correlates these with the decision events by pod key).
        ``fields`` must be JSON-able."""
        request = {"name": name, "pod_key": pod_key}
        request.update(fields)
        return self.record("span", request, None)

    def events(self, since_seq: int = 0) -> list[dict]:
        with self._lock:
            return [e for e in self._events if e["seq"] > since_seq]

    def stats(self) -> dict:
        """Ring statistics for /statusz: occupancy, total recorded, and
        how many events the bounded ring has already dropped (non-zero
        means an incident capture should use a file sink)."""
        with self._lock:
            return {
                "enabled": True,
                "capacity": self.capacity,
                "events": len(self._events),
                "last_seq": self._seq,
                "dropped": max(0, self._seq - len(self._events)),
                "sink_path": self.path or None,
            }

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


def load(path: str) -> list[dict]:
    """Read a JSONL trace file back into an event list."""
    out: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


@dataclass
class Divergence:
    seq: int
    kind: str
    recorded: Any
    replayed: Any

    def __str__(self) -> str:  # human-readable diff summary for the CLI
        return (
            f"divergence at seq {self.seq} ({self.kind}):\n"
            f"  recorded: {json.dumps(self.recorded, sort_keys=True)[:400]}\n"
            f"  replayed: {json.dumps(self.replayed, sort_keys=True)[:400]}"
        )


def replay(
    events: Iterable[dict],
    extender: Optional[Any] = None,
    config: Optional[Any] = None,
    stop_on_divergence: bool = True,
) -> list[Divergence]:
    """Re-run a recorded decision stream against a fresh Extender and
    return every point where the replayed response differs.

    An empty result proves the scheduler is a deterministic function of
    its request stream (time-dependent behavior — gang TTL sweeps — only
    fires on inactivity gaps longer than the TTL, which a replay never
    reproduces, so a clean capture replays clean).
    """
    # local import: trace must stay importable from the extender module
    from tpukube.core.config import load_config
    from tpukube.sched.extender import Extender

    if extender is None:
        from dataclasses import replace as _dc_replace

        cfg = config or load_config(env={})
        # replay must not record (or append to the live sink!) — the
        # replayed extender is a scratch instance, not a daemon
        extender = Extender(_dc_replace(cfg, trace_capacity=0, trace_path=""))
    divergences: list[Divergence] = []

    def _check(ev: dict, replayed: Any) -> bool:
        if _canon(replayed) != _canon(ev["response"]):
            divergences.append(
                Divergence(ev["seq"], ev["kind"], ev["response"], replayed)
            )
            return stop_on_divergence
        return False

    for ev in events:
        kind, req = ev["kind"], ev["request"]
        if kind in ANNOTATION_KINDS:
            continue  # observability markers: nothing to re-dispatch
        if kind not in KINDS:  # newer trace format: report, don't crash
            divergences.append(Divergence(ev.get("seq", -1), kind, ev, None))
            if stop_on_divergence:
                break
            continue
        # replay through the SAME dispatch the live daemon uses (the
        # scratch extender has tracing disabled, so nothing re-records)
        try:
            replayed = extender.handle(kind, req)
        except Exception as e:  # a recorded request must re-dispatch cleanly
            replayed = {"replayError": f"{type(e).__name__}: {e}"}
        if kind == "release":
            continue  # releases have no response to compare
        if _check(ev, replayed):
            break
    return divergences


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True)

"""tpukube-lint — lock-discipline static analysis + runtime race detection.

The control plane is a genuinely concurrent system: RLock-guarded
ledger/gang/extender state mutated from ~10 daemon threads, with the
standing invariant that emitters only ENQUEUE and never do I/O under the
scheduling locks. Nothing used to enforce any of that — one careless
``with self._lock:`` block that writes a file, or that acquires locks in
the wrong order, silently reintroduces the stalled-disk/deadlock bug
class the sink/drain and two-phase-preemption work engineered out. This
package is the enforcement, in the spirit of lockdep and the Go race
detector: machine-checked concurrency discipline, run on every tier-1
invocation and exposed as the ``tpukube-lint`` console script.

Static passes (AST-based, see the per-module docstrings):

  lock-discipline   no blocking I/O lexically inside the scheduling
                    locks of gang.py / extender.py / state.py
  lock-order        lock acquisitions against the declared partial
                    order decision -> pending -> gang -> ledger
  shared-state      registry-declared attributes mutated from daemon
                    threads must be touched under their declared lock
  name-consistency  event reasons, metric series names, and
                    deploy/prometheus-rules.yaml references must
                    resolve against the declared enums/registries
  exception-hygiene broad ``except Exception`` must log, emit an
                    event, re-raise, or carry a justified waiver

Two further passes run on a per-function CFG + path-dataflow engine
(``cfg.py``: branches, loops, try/except/finally, with regions,
return/raise/break/continue edges — ISSUE 7):

  epoch-discipline  every declared mutation seam in
                    sched/{state,gang}.py (the epoch owners) is
                    followed by an epoch bump on every path before the
                    enclosing lock's ``with`` exits (``epochs.py``;
                    the snapshot cache keys on those epochs)
  reservation-leak  every path from a reservation/preemption-plan
                    acquire in sched/{gang,extender}.py to function
                    exit reaches commit, rollback, or a hand-off —
                    exception edges included (``leaks.py``)

The runtime counterpart of epoch-discipline is the snapshot audit
sentinel (``sched/snapshot.py``, config ``snapshot_audit_rate``):
sampled cache hits rebuild from the ledger and raise on divergence.

Waivers: ``# tpukube: allow(<rule>[, <rule>]) <justification>`` on the
flagged line (or the line above). A waiver without a justification is
itself a lint error (``bare-waiver``), and one that suppresses zero
findings in a full run is stale (``unused-waiver``).
``tpukube-lint tpukube/ --changed[=REF]`` lints only files changed vs
a git ref for the fast pre-commit loop.

The dynamic half (``lockgraph``) instruments ``threading.Lock``/
``RLock`` creation behind the ``lock_monitor`` config flag, records
acquisition-order edges per thread during sim scenarios and stress
tests, and reports cycles (potential deadlocks) as a lock graph —
lockdep's class-based aggregation, keyed by lock creation site.
"""

from tpukube.analysis.base import (  # noqa: F401
    ALL_RULES,
    Finding,
    SourceFile,
    run_all,
)

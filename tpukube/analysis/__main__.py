"""``python -m tpukube.analysis`` — the uninstalled-checkout spelling
of the ``tpukube-lint`` console script (tools/check.sh uses it)."""

from tpukube.analysis.cli import main

raise SystemExit(main())

"""name-consistency: every event reason, metric series name, and
``deploy/prometheus-rules.yaml`` metric reference must resolve against
the DECLARED registries — ``tpukube.obs.events.REASONS`` and
``tpukube.obs.registry.DECLARED_SERIES``.

This extends the exposition-time promlint (tests/test_promlint.py, which
scrapes live /metrics) to the SOURCE level: a typo'd ``emit("GangComited")``
or a renamed series fails lint before any process runs, and a rules-file
expression referencing a series nobody renders fails before the alert
silently goes blind. Only string LITERALS are checked — forwarding
wrappers passing a ``reason`` variable are the call sites' problem, and
the call sites are literals.

The cross-check runs BOTH directions (ISSUE 18): the forward pass
above catches a constructor naming an undeclared series; the reverse
pass (``_check_registry_rot``) catches registry rot — a series or
reason that stays DECLARED after its last render/emit site was
deleted. A rotted declaration is worse than a missing one: the
rules-file check keeps passing (the name resolves), so the alert
reading it goes blind without any lint noise. Audited against the
registries the federated-observability and capacity PRs grew
(``tpukube_replica_*``, ``tpukube_capacity_*``,
``tpukube_cycle_queue_age_seconds``): all declared entries have live
reference sites as of this pass's introduction.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from tpukube.analysis.base import Finding, SourceFile

#: call names whose first str-literal arg (or reason=) is an event reason
EMIT_CALLS = {"emit", "_emit", "_emit_event"}

#: registry builder methods / metric constructors whose first
#: str-literal arg is a series family name
METRIC_CALLS = {
    "counter", "gauge", "summary", "histogram",
    "Counter", "Gauge", "Summary", "Histogram",
}

#: suffixes a TYPE'd family implies (rules expressions reference these)
DERIVED_SUFFIXES = ("_bucket", "_count", "_sum")

#: the only modules allowed to construct occupancy grids / sweeps:
#: the epoch-cached snapshot (which owns the per-cycle instances and
#: the one ad-hoc seam, ``sweep_for``) and slicefit itself (the
#: primitive definitions plus their grid-based thin wrappers)
SNAPSHOT_HOME = ("sched/snapshot.py", "sched/slicefit.py")

#: constructor names the snapshot-discipline pass polices
SWEEP_CONSTRUCTORS = frozenset({"occupancy_grid", "_Sweep"})

#: the batch planner (ISSUE 8): its whole contract is ONE pinned
#: snapshot per cycle, taken through the ``_pin_snapshot`` seam — any
#: other SnapshotCache read (or ad-hoc sweep) inside it forks the
#: cluster view mid-batch and the plan silently stops being the thing
#: /filter, /prioritize, and /bind answer from
CYCLE_HOME = "sched/cycle.py"
CYCLE_PIN_SEAM = "_pin_snapshot"

#: call names that read a SnapshotCache (checked only when invoked on
#: an attribute chain mentioning ``snapshots``, so e.g. a histogram's
#: ``observe()`` is not confused for a cache read)
CYCLE_CACHE_READS = frozenset({"current", "observe"})

#: the ad-hoc grid seam — flagged in cycle.py wherever it appears
CYCLE_GRID_BUILDERS = frozenset({"sweep_for"})


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _literal_arg(call: ast.Call, kwarg: str) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == kwarg and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


#: (path suffix, declared-registry variable) -> what its entries are.
#: The reverse audit fires when linting the DECLARING file and scans
#: the package tree (the declaring file's grandparent directory) for
#: reference sites.
_REGISTRY_DECLS: dict[str, tuple[str, str]] = {
    "obs/registry.py": ("DECLARED_SERIES", "metric series"),
    "obs/events.py": ("REASONS", "event reason"),
}


def _declared_entries(sf: SourceFile, var: str) -> list[tuple[int, str]]:
    """(line, value) per string literal inside the module-level
    ``var = (... | {...})`` declaration — parsed from the AST, not
    imported, so fixture registries work."""
    for node in sf.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == var
                   for t in targets):
            continue
        return [
            (n.lineno, n.value) for n in ast.walk(node.value)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        ]
    return []


def _check_registry_rot(sf: SourceFile) -> list[Finding]:
    """The reverse cross-check: every declared series/reason must have
    at least one string-literal reference SOMEWHERE ELSE in the package
    tree. Deleting a render/emit site without retiring the declaration
    leaves dashboards and prometheus-rules reading a name nothing
    serves — the rules-file check alone cannot catch that (the name
    still resolves against the registry)."""
    decl = None
    for sfx, (var, what) in _REGISTRY_DECLS.items():
        if sf.in_scope((sfx,)):
            decl = (var, what)
            break
    if decl is None:
        return []
    var, what = decl
    entries = _declared_entries(sf, var)
    if not entries:
        return []
    root = sf.path.resolve().parent.parent
    own = sf.path.resolve()
    referenced: set = set()
    for f in sorted(root.rglob("*.py")):
        if f.resolve() == own or f.name.endswith("_pb2.py"):
            continue
        try:
            tree = ast.parse(f.read_text())
        except (SyntaxError, ValueError, UnicodeDecodeError):
            continue  # parse-error findings are the runner's job
        for n in ast.walk(tree):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                referenced.add(n.value)
    findings: list[Finding] = []
    for line, value in entries:
        if value not in referenced:
            findings.append(Finding(
                "name-consistency", sf.rel, line,
                f"{what} {value!r} is declared in {var} but no module "
                f"in the package references it — the render/emit site "
                f"is gone; retire the declaration (a rotted entry keeps "
                f"rules-file expressions resolving against a series "
                f"nothing serves)",
            ))
    return findings


def check_names(sf: SourceFile) -> list[Finding]:
    from tpukube.obs.events import REASONS
    from tpukube.obs.registry import DECLARED_SERIES

    findings: list[Finding] = list(_check_registry_rot(sf))
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in EMIT_CALLS:
            reason = _literal_arg(node, "reason")
            if reason is not None and reason not in REASONS:
                findings.append(Finding(
                    "name-consistency", sf.rel, node.lineno,
                    f"event reason {reason!r} is not declared in "
                    f"tpukube.obs.events.REASONS — add it there (and to "
                    f"the journal docstring) or fix the typo",
                ))
        elif name in METRIC_CALLS:
            series = _literal_arg(node, "name")
            if series is not None and series not in DECLARED_SERIES:
                findings.append(Finding(
                    "name-consistency", sf.rel, node.lineno,
                    f"metric series {series!r} is not declared in "
                    f"tpukube.obs.registry.DECLARED_SERIES — declare it "
                    f"(dashboards and prometheus-rules key off the "
                    f"registry) or fix the typo",
                ))
    return findings


def check_snapshot_discipline(sf: SourceFile) -> list[Finding]:
    """Constructing ``occupancy_grid``/``_Sweep`` outside
    ``sched/snapshot.py`` (and slicefit's own wrappers) is a finding:
    the whole point of the epoch-cached scheduling snapshot (ISSUE 5)
    is that webhook cycles share ONE derived-state build per epoch — a
    call site quietly rebuilding sweeps per request reintroduces the
    O(volume x shapes x origins) hot path without failing any test.
    Route cluster-state sweeps through ``SnapshotCache.current()`` and
    request-specific grids through ``snapshot.sweep_for`` (tests are
    not linted and stay exempt).

    The batch planner (``sched/cycle.py``, ISSUE 8) is held to a
    STRICTER contract: a batch-plan consumer may not construct any
    ad-hoc snapshot view at all — no ``SnapshotCache.current()`` /
    ``observe()`` read and no ``sweep_for()`` grid outside the one
    pinning seam (``_pin_snapshot``). The whole point of a cycle is
    that every pod in the batch plans against ONE epoch-pinned
    snapshot; a second read mid-module forks the cluster view and the
    plan silently stops being what the webhooks answer from."""
    if sf.in_scope(SNAPSHOT_HOME):
        return []
    if sf.in_scope((CYCLE_HOME,)):
        return _check_cycle_snapshot_reads(sf)
    findings: list[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in SWEEP_CONSTRUCTORS:
            findings.append(Finding(
                "snapshot-discipline", sf.rel, node.lineno,
                f"{name}() constructed outside sched/snapshot.py — "
                f"read the epoch-cached snapshot "
                f"(SnapshotCache.current()) or build request-specific "
                f"grids through snapshot.sweep_for() so the per-cycle "
                f"cache cannot silently rot",
            ))
    return findings


def _check_cycle_snapshot_reads(sf: SourceFile) -> list[Finding]:
    """The cycle-module arm of snapshot-discipline: walk with the
    enclosing function tracked, flagging sweep constructors AND cache
    reads everywhere except the pinning seam."""
    findings: list[Finding] = []

    def on_snapshots(call: ast.Call) -> bool:
        fn = call.func
        while isinstance(fn, ast.Attribute):
            fn = fn.value
            if isinstance(fn, ast.Attribute) and fn.attr == "snapshots":
                return True
        return isinstance(fn, ast.Name) and fn.id == "snapshots"

    def visit(node: ast.AST, func: Optional[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            bad = (name in SWEEP_CONSTRUCTORS
                   or name in CYCLE_GRID_BUILDERS
                   or (name in CYCLE_CACHE_READS
                       and on_snapshots(node)
                       and func != CYCLE_PIN_SEAM))
            if bad:
                findings.append(Finding(
                    "snapshot-discipline", sf.rel, node.lineno,
                    f"{name}() in the batch planner outside the "
                    f"{CYCLE_PIN_SEAM} seam — batch-plan consumers must "
                    f"use the cycle's ONE pinned snapshot; a second "
                    f"cache read or ad-hoc sweep mid-batch forks the "
                    f"cluster view the plan was built against",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(sf.tree, None)
    return findings


def check_rules_file(path) -> list[Finding]:
    """Every metric name a prometheus-rules.yaml expression reads must
    be a declared series (or a declared family's _bucket/_count/_sum).
    Recording-rule names (containing ':') are skipped by the shared
    PromQL name extractor in tpukube.obs.slo."""
    import yaml

    from tpukube.obs.registry import DECLARED_SERIES
    from tpukube.obs.slo import referenced_metric_names

    path = Path(path)
    text = path.read_text()
    findings: list[Finding] = []
    for doc in yaml.safe_load_all(text):
        if not isinstance(doc, dict):
            continue
        for group in (doc.get("spec") or {}).get("groups", ()):
            for rule in group.get("rules", ()):
                expr = rule.get("expr", "")
                for name in sorted(referenced_metric_names(expr)):
                    base = name
                    for suffix in DERIVED_SUFFIXES:
                        if name.endswith(suffix) \
                                and name[: -len(suffix)] in DECLARED_SERIES:
                            base = name[: -len(suffix)]
                            break
                    if base in DECLARED_SERIES:
                        continue
                    # anchor to the first textual occurrence for a
                    # clickable location
                    idx = text.find(name)
                    line = text.count("\n", 0, idx) + 1 if idx >= 0 else 1
                    findings.append(Finding(
                        "name-consistency", str(path), line,
                        f"rule {rule.get('record') or rule.get('alert')!r}"
                        f" references series {name!r}, which is not in "
                        f"tpukube.obs.registry.DECLARED_SERIES — no "
                        f"registry renders it, so the rule reads nothing",
                    ))
    return findings

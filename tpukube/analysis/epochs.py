"""epoch-discipline: every mutation of a declared snapshot seam must be
followed by an epoch bump on every path before the enclosing lock's
``with`` exits.

PR 5 made scheduling correctness hinge on a manual invariant: the
epoch-cached :class:`~tpukube.sched.snapshot.SnapshotCache` keys its
validity on ``ClusterState.epoch()`` / ``GangManager.epoch()``, so a
mutation path that forgets ``self._epoch += 1`` serves STALE PLACEMENTS
— silently, because the example-based invalidation tests only cover the
seams that existed when they were written. This pass machine-checks the
invariant over the registry below: a new mutation seam added without a
bump is a lint failure at review time, not a stale-cache heisenbug in
production. The runtime counterpart (``snapshot_audit_rate``, the
SnapshotCache audit sentinel) catches whatever the registry itself
misses.

What counts as a seam event inside a registered class:

  * a write (assign / augmented assign / ``del``) to a declared seam
    attribute of ``self`` — plain or subscripted
    (``self._allocs[k] = v``);
  * a mutating method call on a declared seam attribute
    (``self._reservations.pop(...)``); reads (``.get``, ``.values``,
    iteration) are not events;
  * a call to a registered mutator method name on ANY receiver
    (``res.record_assignment(...)``, ``view.add_ids(...)``) — these
    mutate reservation/occupancy state the snapshot derives from.

The bump is ``self._epoch += 1``. The enclosing region is the outermost
``with self.<lock>`` containing the seam (re-entrant locks release at
the outermost exit); in a ``*_locked`` helper — documented as called
with the lock held — the region is the whole function body, so the
bump must dominate every function exit instead. A seam outside both is
itself a finding (the epoch contract is only sound under the lock).

Helper methods that bump INTERNALLY (``_rollback_locked``,
``_evict_and_mask_locked``, ``ClusterState.commit``) are deliberately
NOT registered as mutators: their callers need no second bump, and
their own bodies are checked like any other function.

Since ISSUE 18 the bump predicate is interprocedural ONE level via
:mod:`tpukube.analysis.callgraph`: a statement calling an intra-class
helper whose own DIRECT statements bump on every exit counts as a
bump for the caller — ``self._register_and_bump_locked(...)``
satisfies the seam it follows. The helper summary uses direct bumps
only, so a two-level chain (helper delegating to a sub-helper that
bumps) is rejected by design.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from tpukube.analysis import callgraph, cfg
from tpukube.analysis.base import Finding, SourceFile

#: methods that mutate the receiver when called on a seam attribute
MUTATING_METHODS = frozenset({
    "pop", "popitem", "append", "appendleft", "add", "discard", "remove",
    "clear", "update", "setdefault", "extend", "insert",
    "difference_update", "intersection_update",
    "symmetric_difference_update",
})


@dataclass(frozen=True)
class SeamSpec:
    """One class's epoch contract."""

    lock_attr: str
    seam_attrs: frozenset[str]
    mutator_calls: frozenset[str]
    bump_attr: str = "_epoch"


#: (path suffix, class) -> SeamSpec. Growing ClusterState/GangManager a
#: new snapshot-feeding structure means declaring it here — the pass
#: then enforces the bump discipline everywhere it is mutated.
#:
#: sched/snapshot.py joined the registry the day it grew a mutation-
#: application seam (ISSUE 10, the promise PR 6 recorded): the delta
#: advance WRITES the cached-snapshot slot, and every such write must
#: pair with a ``_snap_gen`` bump under the cache's leaf mutex — the
#: statically-proven invariant that the cached slot never changes
#: without its generation (and therefore the observably-served key)
#: moving in the same locked region. The cache still owns no EPOCH of
#: its own; ``_snap_gen`` is the slot-generation counter its stats
#: report.
EPOCH_REGISTRY: dict[tuple[str, str], SeamSpec] = {
    ("sched/state.py", "ClusterState"): SeamSpec(
        lock_attr="_lock",
        # _cordoned joined with the drain plane (ISSUE 19): the cordon
        # set feeds the snapshot's placement mask, so a cordon flip
        # without a bump serves stale sweeps exactly like a node write
        seam_attrs=frozenset({"_nodes", "_allocs", "_slices",
                              "_cordoned"}),
        mutator_calls=frozenset({"add_ids", "remove_ids"}),
    ),
    ("sched/gang.py", "GangManager"): SeamSpec(
        lock_attr="_lock",
        seam_attrs=frozenset({"_reservations", "_terminating_coords"}),
        mutator_calls=frozenset({"record_assignment", "drop_assignment"}),
    ),
    ("sched/snapshot.py", "SnapshotCache"): SeamSpec(
        lock_attr="_lock",
        seam_attrs=frozenset({"_snap"}),
        mutator_calls=frozenset(),
        bump_attr="_snap_gen",
    ),
}

def flatten_targets(targets: list) -> list[ast.AST]:
    """Assignment targets with tuple/list/starred unpacking expanded:
    ``self._reservations[k], old = ...`` writes the seam exactly like
    the plain form and must not evade the pass."""
    out: list[ast.AST] = []
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            out.append(t)
    return out


def _seam_write_target(t: ast.AST, attrs: frozenset[str]) -> Optional[str]:
    """self.<attr> or self.<attr>[...] as an assignment/delete target."""
    if isinstance(t, ast.Subscript):
        t = t.value
    return cfg._self_attr(t) if cfg._self_attr(t) in attrs else None


def seam_events(stmt: ast.AST, spec: SeamSpec) -> list[str]:
    """Human-readable descriptions of the seam mutations one statement
    performs (empty = not a seam). Never descends into nested defs."""
    out: list[str] = []
    for n in cfg.shallow_walk(stmt):
        targets: list[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.Delete):
            targets = list(n.targets)
        for t in flatten_targets(targets):
            attr = _seam_write_target(t, spec.seam_attrs)
            if attr is not None:
                out.append(f"write to self.{attr}")
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            fn = n.func
            if fn.attr in spec.mutator_calls:
                out.append(f"{fn.attr}() call")
            if fn.attr in MUTATING_METHODS:
                recv = cfg._self_attr(fn.value)
                if recv in spec.seam_attrs:
                    out.append(f"self.{recv}.{fn.attr}()")
    return out


def _is_bump(stmt: ast.AST, spec: SeamSpec) -> bool:
    for n in cfg.shallow_walk(stmt):
        if (isinstance(n, ast.AugAssign)
                and isinstance(n.op, ast.Add)
                and cfg._self_attr(n.target) == spec.bump_attr):
            return True
    return False


def check_epochs(sf: SourceFile,
                 registry: Optional[dict] = None) -> list[Finding]:
    table = registry if registry is not None else EPOCH_REGISTRY
    specs = {cls: spec for (sfx, cls), spec in table.items()
             if sf.in_scope((sfx,))}
    if not specs:
        return []
    findings: list[Finding] = []
    emitted: set[tuple[int, str]] = set()

    def emit(line: int, message: str) -> None:
        # finally-instantiated duplicates report the same (line, msg)
        if (line, message) not in emitted:
            emitted.add((line, message))
            findings.append(Finding("epoch-discipline", sf.rel, line,
                                    message))

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        spec = specs.get(cls_node.name)
        if spec is None:
            continue
        cg = callgraph.ClassGraph(cls_node)
        for fn in cls_node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue  # no concurrency yet; the seed writes are free
            g = cfg.build_cfg(fn, lock_attrs={spec.lock_attr})
            seams = [(n, seam_events(n.stmt, spec)) for n in g.nodes
                     if n.stmt is not None]
            seams = [(n, ev) for n, ev in seams if ev]
            if not seams:
                continue

            # one-level delegation: a call to an intra-class helper
            # whose direct statements bump on every exit is a bump
            lifted = callgraph.delegating_satisfier(
                cg, lambda stmt: _is_bump(stmt, spec),
                exclude=(fn.name,))

            def bump(node: cfg.Node) -> bool:
                return node.stmt is not None and lifted(node.stmt)

            for node, events in seams:
                what = " + ".join(sorted(set(events)))
                rid = g.outermost_region(node, spec.lock_attr)
                if rid is None:
                    if fn.name.endswith("_locked"):
                        rets, rzs = cfg.escapes_function(g, node, bump)
                        for w in rets + rzs:
                            emit(node.line, (
                                f"mutation seam ({what}) in "
                                f"{cls_node.name}.{fn.name} is not "
                                f"followed by `self.{spec.bump_attr} += 1`"
                                f" on every path to function exit "
                                f"(escape near line {w.line}) — a missed "
                                f"bump serves stale snapshots"))
                            break
                    else:
                        emit(node.line, (
                            f"mutation seam ({what}) outside `with "
                            f"self.{spec.lock_attr}` in "
                            f"{cls_node.name}.{fn.name} — the epoch "
                            f"contract is only sound under the lock "
                            f"(or in a *_locked helper)"))
                    continue
                escapes = cfg.escapes_region(g, node, rid, bump)
                if escapes:
                    u, _ = escapes[0]
                    emit(node.line, (
                        f"mutation seam ({what}) in "
                        f"{cls_node.name}.{fn.name} is not followed by "
                        f"`self.{spec.bump_attr} += 1` on every path "
                        f"before the `with self.{spec.lock_attr}` region "
                        f"(line {g.regions[rid].line}) exits (escape "
                        f"near line {u.line}) — a missed bump serves "
                        f"stale snapshots"))
    return findings

"""The ``tpukube-lint`` console script.

    tpukube-lint tpukube/              # all passes, exit 1 on findings
    tpukube-lint --rules lock-order,shared-state tpukube/sched/
    tpukube-lint --json tpukube/       # machine-readable findings
    tpukube-lint --list-rules

Exit status: 0 = clean (every finding fixed or carries a justified
waiver), 1 = unwaived findings, 2 = usage error. tools/check.sh runs
this before the tier-1 suite.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from tpukube.analysis.base import ALL_RULES, run_all

_RULE_DOCS = {
    "lock-discipline": "no blocking I/O under the scheduling locks",
    "lock-order": "acquisitions follow decision -> pending -> gang -> "
                  "ledger",
    "shared-state": "registry-declared attributes touched under their "
                    "declared lock",
    "name-consistency": "event reasons / metric series / "
                        "prometheus-rules refs resolve against the "
                        "declared registries",
    "snapshot-discipline": "occupancy_grid/_Sweep built only in "
                           "sched/snapshot.py (+ slicefit wrappers) — "
                           "hot paths read the epoch cache",
    "exception-hygiene": "broad excepts must log, emit, re-raise, or "
                         "carry a justified waiver",
    "epoch-discipline": "every declared mutation seam is followed by "
                        "an epoch bump on every path before the "
                        "enclosing lock's `with` exits (CFG dataflow)",
    "reservation-leak": "every path from a reservation/preemption-plan "
                        "acquire to function exit reaches commit, "
                        "rollback, or a hand-off — exception edges "
                        "included (CFG dataflow)",
    "decision-provenance": "every refusal/denial seam (tenancy gate, "
                           "degraded gate, filter errors) records a "
                           "DecisionRecord",
    "seam-triple": "every epoch bump in the ledger/gang pairs with a "
                   "delta note AND a journal note on every path before "
                   "the lock region exits; each replayed WAL kind is "
                   "still written somewhere (CFG dataflow)",
    "flag-discipline": "feature-gated subsystems built only under "
                       "their config flag; every seam dereference is "
                       "None-guarded (off-is-off)",
    "unused-waiver": "a waiver that suppressed zero findings is stale "
                     "and must be deleted",
    "bare-waiver": "waiver pragmas must name known rules and carry a "
                   "justification",
}


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpukube-lint",
        description="lock-discipline / concurrency / name-consistency "
                    "static analysis over the tpukube tree",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: the "
                        "tpukube package next to this install)")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="comma-separated subset of rules to run")
    p.add_argument("--rules-file", default=None, metavar="YAML",
                   help="prometheus-rules.yaml to cross-check (default: "
                        "auto-discover deploy/prometheus-rules.yaml "
                        "next to the linted tree)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only files changed vs a git ref "
                        "(worktree + index + untracked). Write the ref "
                        "as --changed=REF — a bare `--changed` before a "
                        "path would swallow the path as its ref — or "
                        "put paths first: `tpukube-lint tpukube/ "
                        "--changed`. Default ref: HEAD. The fast "
                        "pre-commit loop; tools/check.sh still runs "
                        "the full tree")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one JSON object per finding")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule names and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule:20s} {_RULE_DOCS[rule]}")
        return 0

    paths = args.paths
    if not paths:
        import tpukube

        paths = [tpukube.__path__[0]]
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    import yaml

    try:
        rules_file = args.rules_file
        if args.changed is not None:
            from tpukube.analysis.base import changed_paths, find_rules_file

            if rules_file is None:
                # discover deploy/prometheus-rules.yaml from the
                # ORIGINAL path arguments: the changed-file list that
                # replaces them below has no deploy/ sibling, and the
                # rules cross-check must not silently vanish in
                # changed-only mode
                rules_file = find_rules_file(paths)
            paths = changed_paths(paths, ref=args.changed)
            if not paths and rules_file is None:
                print(f"tpukube-lint: no lintable files changed vs "
                      f"{args.changed}")
                return 0
            # an empty .py list still cross-checks the rules file:
            # "only deploy/prometheus-rules.yaml changed" is exactly
            # when the name-consistency rules check matters most
        findings = run_all(paths, rules=rules, rules_file=rules_file)
    except (ValueError, OSError, yaml.YAMLError) as e:
        # unknown rule names, an unreadable path/--rules-file, or a
        # malformed rules yaml are USAGE errors (exit 2), distinct from
        # lint findings (exit 1) — CI wrappers key on the difference
        print(f"tpukube-lint: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        for f in findings:
            print(json.dumps(f.as_dict(), sort_keys=True))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        print(f"tpukube-lint: {n} finding(s)" if n else
              "tpukube-lint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Intra-class call graph: one-level delegation summaries for the
lint passes.

The per-function passes (locks, epochs, leaks) historically trusted
naming conventions at function boundaries: a ``*_locked`` helper is
ASSUMED to run under the lock, a caller's bump is ASSUMED to cover the
helper it delegates to. This module makes the boundary checkable ONE
level deep:

  * :func:`class_graph` indexes every method of a class and every
    intra-class ``self.<method>(...)`` call site, with the ``with
    self.<lock>`` attrs lexically held at each site;
  * :func:`always_satisfies` summarizes a helper body — "does every
    exit pass a statement the predicate accepts?" — using only the
    helper's DIRECT statements, so a two-level chain (caller ->
    helper -> sub-helper that actually bumps) is deliberately NOT
    accepted: one level is auditable by eye, arbitrary transitive
    chains are how conventions rot.

Closed-world caveat: "every call site" means every call site INSIDE
the class body. A method invoked from outside its class (another
module, a thread target) is not proven by its callers here — the
passes only use caller-proofs to ACCEPT code the per-function lexical
check would flag, never to flag code the lexical check accepts, so
the caveat can only cost a waiver, not hide a bug the old passes
caught.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from tpukube.analysis import cfg

FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def methods_of(cls_node: ast.ClassDef) -> dict:
    """name -> def for the class's directly-declared methods."""
    return {fn.name: fn for fn in cls_node.body
            if isinstance(fn, FuncDef)}


def self_calls(stmt: ast.AST) -> set[str]:
    """Method names invoked as ``self.<m>(...)`` within one statement
    (never descending into nested defs)."""
    out: set[str] = set()
    for n in cfg.shallow_walk(stmt):
        if (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and cfg._self_attr(n.func) is not None):
            out.add(n.func.attr)
    return out


@dataclass(frozen=True)
class Site:
    """One intra-class ``self.<method>(...)`` call site."""

    caller: ast.AST          # the enclosing FunctionDef
    call: ast.Call
    method: str
    #: ``with self.<attr>`` lock attrs lexically held at the call
    held: frozenset


class ClassGraph:
    """Method index + intra-class call sites for one class."""

    def __init__(self, cls_node: ast.ClassDef,
                 lock_attrs: Iterable[str] = ()):
        self.cls = cls_node
        self.methods = methods_of(cls_node)
        self._sites: dict[str, list[Site]] = {}
        track = frozenset(lock_attrs)
        for fn in self.methods.values():
            self._collect(fn, track)

    def _collect(self, fn, track: frozenset) -> None:
        sites = self._sites

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.held: list[str] = []

            def _visit_with(self, node) -> None:
                acquired = 0
                for item in node.items:
                    self.visit(item.context_expr)
                    a = cfg._self_attr(item.context_expr)
                    if a in track:
                        self.held.append(a)
                        acquired += 1
                for stmt in node.body:
                    self.visit(stmt)
                del self.held[len(self.held) - acquired:]

            visit_With = _visit_with
            visit_AsyncWith = _visit_with

            def visit_Call(self, node: ast.Call) -> None:
                if (isinstance(node.func, ast.Attribute)
                        and cfg._self_attr(node.func) is not None):
                    sites.setdefault(node.func.attr, []).append(Site(
                        caller=fn, call=node, method=node.func.attr,
                        held=frozenset(self.held),
                    ))
                self.generic_visit(node)

        V().visit(fn)

    def sites_of(self, method: str) -> list[Site]:
        """Every intra-class call site of ``self.<method>(...)``."""
        return self._sites.get(method, [])


def always_satisfies(fn, satisfies: Callable[[ast.AST], bool],
                     raise_paths: bool = True) -> bool:
    """True when every path through ``fn`` passes a DIRECT statement
    the predicate accepts before any function exit — the one-level
    helper summary. With ``raise_paths`` (the default) exception exits
    count too, which is the conservative reading: a helper that can
    raise before doing its duty does not discharge the caller's
    obligation on that path."""
    g = cfg.build_cfg(fn)

    def sat(node: cfg.Node) -> bool:
        return node.stmt is not None and satisfies(node.stmt)

    rets, rzs = cfg.escapes_function(g, g.entry, sat)
    if rets:
        return False
    return not (raise_paths and rzs)


def delegating_satisfier(
    cg: ClassGraph, satisfies: Callable[[ast.AST], bool],
    exclude: Iterable[str] = (),
) -> Callable[[ast.AST], bool]:
    """Lift a direct statement predicate one call level: the returned
    predicate also accepts a statement that calls an intra-class
    helper whose OWN direct statements satisfy on every exit. Helper
    summaries use the base predicate only, so delegation never chains
    (two-level delegation is rejected by design). ``exclude`` names
    methods that must not count (typically the function under
    analysis, so recursion cannot vouch for itself)."""
    excluded = frozenset(exclude)
    summary: dict[str, bool] = {}

    def helper_ok(name: str) -> bool:
        if name in excluded or name not in cg.methods:
            return False
        if name not in summary:
            summary[name] = always_satisfies(cg.methods[name], satisfies)
        return summary[name]

    def lifted(stmt: ast.AST) -> bool:
        if satisfies(stmt):
            return True
        return any(helper_ok(m) for m in self_calls(stmt))

    return lifted


def guard_mentions(test: ast.AST, names: Iterable[str]) -> bool:
    """Does a condition expression mention any of the given names —
    as a bare name, a ``self.<name>``, or a ``<recv>.<name>``
    attribute? The lexical "is this gated on the flag/holder" test
    the flag pass and caller-proofs share."""
    wanted = set(names)
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in wanted:
            return True
        if isinstance(n, ast.Attribute) and n.attr in wanted:
            return True
    return False


def find_class(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None

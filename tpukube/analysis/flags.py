"""flag-discipline: feature-gated subsystems are constructed only
under their config flag, and every use of their seam attribute is
None-guarded.

The repo's off-is-off contract (every PR since ISSUE 9) is a pair of
hand-maintained idioms:

  * construction — ``self.X = None`` then ``if config.X_enabled:
    self.X = Ctor(...)`` (or the ternary form ``Ctor(...) if
    config.X_enabled else None``), so a disabled flag builds NOTHING:
    no thread, no ring, no series, byte-identical exposition;
  * consumption — every later ``self.X.method(...)`` sits under an
    ``is None`` guard (or inside a block whose test mentions the flag),
    because with the flag off the attribute IS ``None`` and an
    unguarded seam crashes exactly the configuration the parity
    goldens promise is untouched.

Both idioms rot silently: a new call site added two PRs after the flag
landed has no test running with the flag OFF on that path. This pass
machine-checks them over the registry below.

Scope and honesty: the guard check is lexical, not path-sensitive —
a block whose test MENTIONS the seam attribute (or its flag) counts
as guarded regardless of polarity, and an early-out ``if self.X is
None: return`` guards the rest of the enclosing block. Aliased access
(``dlog = self.decisions`` then ``if dlog is not None``) is invisible
and therefore trivially clean — the alias read itself dereferences
nothing. The pass exists to catch the common failure (a bare
``self.X.y(...)`` with no guard in sight), not to prove the guard's
branch sense.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from tpukube.analysis import callgraph, cfg
from tpukube.analysis.base import Finding, SourceFile

FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclass(frozen=True)
class FlagSpec:
    """One feature gate's construction/consumption contract."""

    flag: str
    #: constructor call names; ``"mod.func"`` matches the dotted form
    ctors: frozenset
    #: files where any ctor call must sit under a flag check
    construct_scope: tuple
    #: the seam attribute the consumer classes hold (None = the flag
    #: has no per-instance seam — construction discipline only)
    attr: Optional[str] = None
    #: (path suffix, class) whose ``self.<attr>`` derefs are checked
    consumers: tuple = ()


FLAG_REGISTRY: tuple[FlagSpec, ...] = (
    FlagSpec(
        flag="decisions_enabled",
        ctors=frozenset({"DecisionLog"}),
        construct_scope=("sched/extender.py", "sched/shard.py"),
        attr="decisions",
        consumers=(("sched/extender.py", "Extender"),
                   ("sched/shard.py", "ShardRouter")),
    ),
    FlagSpec(
        flag="journal_enabled",
        ctors=frozenset({"StateJournal"}),
        construct_scope=("sched/extender.py",),
        attr="journal",
        consumers=(("sched/extender.py", "Extender"),),
    ),
    FlagSpec(
        flag="batch_enabled",
        ctors=frozenset({"SchedulingCycle", "_RouterCycle"}),
        construct_scope=("sched/extender.py", "sched/shard.py"),
        attr="cycle",
        consumers=(("sched/extender.py", "Extender"),
                   ("sched/shard.py", "ShardRouter")),
    ),
    FlagSpec(
        flag="tenancy_enabled",
        ctors=frozenset({"TenantPlane"}),
        construct_scope=("sched/extender.py",),
        attr="tenants",
        consumers=(("sched/extender.py", "Extender"),),
    ),
    FlagSpec(
        flag="capacity_enabled",
        ctors=frozenset({"CapacityRecorder"}),
        construct_scope=("sched/extender.py",),
        attr="capacity",
        consumers=(("sched/extender.py", "Extender"),),
    ),
    FlagSpec(
        flag="drain_enabled",
        ctors=frozenset({"DrainCoordinator"}),
        construct_scope=("sched/extender.py",),
        attr="drain",
        consumers=(("sched/extender.py", "Extender"),),
    ),
    FlagSpec(
        flag="autoscale_enabled",
        ctors=frozenset({"Autoscaler"}),
        construct_scope=("sched/extender.py",),
        attr="autoscaler",
        consumers=(("sched/extender.py", "Extender"),),
    ),
    FlagSpec(
        flag="lock_monitor",
        ctors=frozenset({"lockgraph.install"}),
        construct_scope=("tpukube/cli.py", "sim/harness.py",
                         "sched/shardworker.py"),
        # no seam attribute: consumers hold the returned monitor (or an
        # installed bool) themselves; construction discipline is the
        # whole contract — an ungated install() patches threading.Lock
        # for the entire process
    ),
)


def _call_names(call: ast.Call) -> set[str]:
    """Both spellings of a constructor call: bare name and one-level
    dotted (``lockgraph.install``)."""
    out: set[str] = set()
    f = call.func
    if isinstance(f, ast.Name):
        out.add(f.id)
    elif isinstance(f, ast.Attribute):
        out.add(f.attr)
        if isinstance(f.value, ast.Name):
            out.add(f"{f.value.id}.{f.attr}")
    return out


def _terminates(body: list) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _check_construction(sf: SourceFile,
                        specs: list[FlagSpec]) -> list[Finding]:
    """Every registered ctor call must sit under an enclosing test
    (``if`` / ternary / ``while`` / bool-op guard) that mentions the
    flag."""
    findings: list[Finding] = []

    def visit(node: ast.AST, gates: frozenset) -> None:
        if isinstance(node, ast.If):
            inner = gates | _gate_names(node.test)
            visit(node.test, gates)
            for s in node.body:
                visit(s, inner)
            for s in node.orelse:
                visit(s, inner)
            return
        if isinstance(node, ast.IfExp):
            inner = gates | _gate_names(node.test)
            visit(node.test, gates)
            visit(node.body, inner)
            visit(node.orelse, inner)
            return
        if isinstance(node, ast.While):
            inner = gates | _gate_names(node.test)
            visit(node.test, gates)
            for s in node.body:
                visit(s, inner)
            for s in node.orelse:
                visit(s, gates)
            return
        if isinstance(node, ast.Call):
            names = _call_names(node)
            for spec in specs:
                if names & spec.ctors and spec.flag not in gates:
                    ctor = sorted(names & spec.ctors)[0]
                    findings.append(Finding(
                        "flag-discipline", sf.rel, node.lineno,
                        f"`{ctor}(...)` constructed without a "
                        f"`{spec.flag}` check — flagged subsystems are "
                        f"built only under their config gate, so the "
                        f"flag-off run builds NOTHING (off-is-off; "
                        f"analysis/flags.py FLAG_REGISTRY)"))
        for child in ast.iter_child_nodes(node):
            visit(child, gates)

    flags = {s.flag for s in specs}

    def _gate_names(test: ast.AST) -> frozenset:
        return frozenset(f for f in flags
                         if callgraph.guard_mentions(test, {f}))

    visit(sf.tree, frozenset())
    return findings


def _derefs(node: ast.AST, attrs: frozenset) -> list[tuple[int, str]]:
    """``self.<attr>.<x>`` / ``self.<attr>[...]`` / calls through the
    seam — uses that crash when the attribute is None. A bare read of
    ``self.<attr>`` (alias, truthiness test, hand-off) is not a deref."""
    out: list[tuple[int, str]] = []
    for n in cfg.shallow_walk(node):
        base = None
        if isinstance(n, (ast.Attribute, ast.Subscript)):
            base = n.value
        if base is not None and cfg._self_attr(base) in attrs:
            out.append((n.lineno, cfg._self_attr(base)))
    return out


def _stmt_local_guard(stmt: ast.AST, names: set) -> bool:
    """A guard inside the statement itself: a ternary whose test
    mentions the seam, or an ``is (not) None`` comparison on it."""
    for n in cfg.shallow_walk(stmt):
        if (isinstance(n, ast.IfExp)
                and callgraph.guard_mentions(n.test, names)):
            return True
        if (isinstance(n, ast.Compare)
                and callgraph.guard_mentions(n, names)
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in n.comparators)):
            return True
    return False


def _check_consumer(sf: SourceFile, cls_node: ast.ClassDef,
                    attr_flags: dict) -> list[Finding]:
    findings: list[Finding] = []
    attrs = frozenset(attr_flags)
    names_of = {a: {a, attr_flags[a]} for a in attrs}

    def check_expr(stmt: ast.AST, guarded: set) -> None:
        for line, attr in _derefs(stmt, attrs - frozenset(guarded)):
            if _stmt_local_guard(stmt, names_of[attr]):
                continue
            findings.append(Finding(
                "flag-discipline", sf.rel, line,
                f"`self.{attr}.<...>` dereferenced without a "
                f"`self.{attr} is None` guard — with "
                f"`{attr_flags[attr]}` off the attribute IS None and "
                f"this seam crashes the flag-off path the parity "
                f"goldens promise is untouched (analysis/flags.py)"))

    def mentioned_in(test: ast.AST) -> set:
        return {a for a in attrs
                if callgraph.guard_mentions(test, names_of[a])}

    def walk(stmts: list, guarded: set) -> None:
        g = set(guarded)
        for stmt in stmts:
            if isinstance(stmt, (*FuncDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                m = mentioned_in(stmt.test)
                check_expr(stmt.test, g | m)
                walk(stmt.body, g | m)
                walk(stmt.orelse, g | m)
                if m and _terminates(stmt.body):
                    g |= m
            elif isinstance(stmt, ast.While):
                m = mentioned_in(stmt.test)
                check_expr(stmt.test, g | m)
                walk(stmt.body, g | m)
                walk(stmt.orelse, g)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                check_expr(stmt.iter, g)
                walk(stmt.body, g)
                walk(stmt.orelse, g)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    check_expr(item.context_expr, g)
                walk(stmt.body, g)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, g)
                for h in stmt.handlers:
                    walk(h.body, g)
                walk(stmt.orelse, g)
                walk(stmt.finalbody, g)
            elif isinstance(stmt, ast.Match):
                check_expr(stmt.subject, g)
                for case in stmt.cases:
                    walk(case.body, g)
            else:
                check_expr(stmt, g)

    for fn in cls_node.body:
        if isinstance(fn, FuncDef):
            walk(fn.body, set())
    return findings


def check_flags(sf: SourceFile,
                registry: Optional[tuple] = None) -> list[Finding]:
    table = registry if registry is not None else FLAG_REGISTRY
    findings: list[Finding] = []

    ctor_specs = [s for s in table if sf.in_scope(s.construct_scope)]
    if ctor_specs:
        findings.extend(_check_construction(sf, ctor_specs))

    by_class: dict[str, dict] = {}
    for spec in table:
        if spec.attr is None:
            continue
        for sfx, cls in spec.consumers:
            if sf.in_scope((sfx,)):
                by_class.setdefault(cls, {})[spec.attr] = spec.flag
    for cls, attr_flags in by_class.items():
        cls_node = callgraph.find_class(sf.tree, cls)
        if cls_node is not None:
            findings.extend(_check_consumer(sf, cls_node, attr_flags))

    # registry rot check: every declared flag must exist as a config
    # field — a renamed flag would otherwise quietly gate nothing
    if sf.in_scope(("core/config.py",)):
        fields = {
            n.target.id
            for cls in sf.tree.body if isinstance(cls, ast.ClassDef)
            for n in cls.body
            if isinstance(n, ast.AnnAssign)
            and isinstance(n.target, ast.Name)
        }
        for spec in table:
            if spec.flag not in fields:
                findings.append(Finding(
                    "flag-discipline", sf.rel, 1,
                    f"flag `{spec.flag}` in analysis/flags.py "
                    f"FLAG_REGISTRY is not a config field — the "
                    f"registry entry gates nothing; rename or remove "
                    f"it"))
    return findings

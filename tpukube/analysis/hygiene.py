"""exception-hygiene: broad ``except Exception`` (or bare ``except:``)
handlers in the daemons' hot paths must DO something an operator can
see — log, emit a journal event, or re-raise — or carry a justified
``# tpukube: allow(exception-hygiene) <why>`` waiver. A silent broad
except in a scheduling or plugin path is how a real fault class
(apiserver flake, codec skew, kubelet restart) becomes an invisible
capacity leak.
"""

from __future__ import annotations

import ast

from tpukube.analysis.base import Finding, SourceFile

#: a call to any of these attribute names counts as "the handler
#: surfaced the error": stdlib logger methods + the journal emitters
LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}
EMIT_METHODS = {"emit", "_emit", "_emit_event"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in LOG_METHODS | EMIT_METHODS):
            return True
    return False


def check_exceptions(sf: SourceFile) -> list[Finding]:
    findings = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _is_broad(node) and not _handles(node):
            findings.append(Finding(
                "exception-hygiene", sf.rel, node.lineno,
                "broad except swallows the error silently — log it, "
                "emit a journal event, re-raise, or waive with "
                "`# tpukube: allow(exception-hygiene) <why>`",
            ))
    return findings

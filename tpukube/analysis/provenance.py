"""decision-provenance: every refusal/denial seam must record a
DecisionRecord (or carry a justified waiver).

ISSUE 12's explain layer is only trustworthy if refusals can never be
silent in it: a pod refused by the tenancy gate, the degraded gate, or
a filter error must leave a provenance stage, else `tpukube-obs
explain` answers "unknown" for exactly the pods operators ask about.
This pass holds the refusal seams to that contract SOURCE-level, the
same way name-consistency holds emit() reasons:

  * any function that emits (``emit``/``_emit``/``_emit_event``) or
    delegates (``_refuse``) a REFUSAL reason literal
    (``TenantQuotaDenied``, ``TenantAdmissionShed``, ``DegradedMode``)
    must also contain a provenance record call — a ``.record(...)`` or
    ``.refusal(...)`` invoked on a ``decisions``/``dlog`` receiver —
    or itself delegate to ``_refuse`` (the tenancy plane's recording
    choke point);
  * the registered seam functions (``SEAMS``) are held to the same
    contract even without a literal in their body — ``_refuse``
    forwards its reason as a variable, and ``filter_response`` serves
    planned refusals without emitting at all.

Scoped to the modules that own refusal seams (``sched/extender.py``,
``sched/cycle.py``, ``tenancy/core.py``); new refusal seams elsewhere
join by emitting one of the refusal reasons (name-consistency already
forces the reason into the declared enum).
"""

from __future__ import annotations

import ast
from typing import Optional

from tpukube.analysis.base import Finding, SourceFile

#: event reasons that ARE refusals — emitting one marks the enclosing
#: function as a refusal seam
REFUSAL_REASONS = frozenset({
    "TenantQuotaDenied", "TenantAdmissionShed", "DegradedMode",
})

#: call names whose first literal arg (or reason=) names an event
#: reason (the same surface name-consistency checks) plus the tenancy
#: plane's refusal choke point, which takes the reason first too
REFUSAL_EMITTERS = frozenset({"emit", "_emit", "_emit_event", "_refuse"})

#: a provenance record call: one of these method names ...
RECORD_METHODS = frozenset({"record", "refusal"})
#: ... invoked on a receiver whose trailing name is one of these
#: (``self.decisions.record(...)``, ``dlog.record(...)``, or the
#: extender-qualified ``ext.decisions.record(...)``)
RECORD_RECEIVERS = frozenset({"decisions", "dlog"})

#: calling a recording choke point counts as recording: the tenancy
#: plane's _refuse and the extender's guarded _note_decision helper
#: both record by contract (and both contain a literal record call, so
#: the contract bottoms out)
DELEGATES = frozenset({"_refuse", "_note_decision"})

SCOPE = ("sched/extender.py", "sched/cycle.py", "tenancy/core.py")

#: functions that are refusal seams by REGISTRATION (their reasons are
#: variables, or they answer refusals without emitting): path suffix ->
#: function names that must contain a record call
SEAMS: dict[str, frozenset[str]] = {
    "tenancy/core.py": frozenset({"_refuse"}),
    "sched/cycle.py": frozenset({"filter_response"}),
}


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _literal_reason(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "reason" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _is_record_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in DELEGATES
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr in DELEGATES:
        return True
    if fn.attr not in RECORD_METHODS:
        return False
    recv = fn.value
    if isinstance(recv, ast.Name):
        return recv.id in RECORD_RECEIVERS
    if isinstance(recv, ast.Attribute):
        return recv.attr in RECORD_RECEIVERS
    return False


def check_provenance(sf: SourceFile) -> list[Finding]:
    if not sf.in_scope(SCOPE):
        return []
    posix = sf.path.as_posix()
    registered: frozenset[str] = frozenset()
    for suffix, names in SEAMS.items():
        if posix.endswith(suffix):
            registered = names
            break
    findings: list[Finding] = []

    def visit_function(fn: ast.AST) -> None:
        emits_refusal: Optional[int] = None  # first offending line
        records = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_record_call(node):
                records = True
            name = _call_name(node)
            if name in REFUSAL_EMITTERS:
                reason = _literal_reason(node)
                if reason in REFUSAL_REASONS and emits_refusal is None:
                    emits_refusal = node.lineno
        is_seam = fn.name in registered
        if (emits_refusal is not None or is_seam) and not records:
            line = emits_refusal if emits_refusal is not None \
                else fn.lineno
            findings.append(Finding(
                "decision-provenance", sf.rel, line,
                f"{fn.name}() is a refusal seam but records no "
                f"DecisionRecord — call decisions.record()/.refusal() "
                f"(or delegate to _refuse) so `tpukube-obs explain` "
                f"can answer why-denied for the refused pod",
            ))

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_function(node)
    return findings

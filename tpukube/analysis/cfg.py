"""Per-function control-flow graphs + path-dataflow queries — the
engine under tpukube-lint's ``epoch-discipline`` and
``reservation-leak`` passes.

The lexical passes (``locks.py``) see only nesting; the invariants PR 4
and PR 5 introduced are PATH properties: "every write to a mutation
seam is followed by an epoch bump on every path before the enclosing
lock's ``with`` exits", "every path from a reservation acquire to
function exit reaches commit, rollback, or a hand-off — exception
edges included". This module builds a small CFG per function (branches,
loops, ``try/except/finally``, ``with`` regions, ``return`` / ``raise``
/ ``break`` / ``continue`` edges) and answers exactly those two query
shapes:

  * :func:`escapes_region` — edges leaving a lock-holding ``with``
    region reachable from a start node without passing a satisfying
    node ("B occurs before region exit on every path from A");
  * :func:`escapes_function` — function exits (normal return vs
    exception) reachable from a start node without passing a
    satisfying node ("A dominates a commit-or-cleanup on all exits").

Exception modeling is deliberately low-noise:

  * an explicit ``raise`` always takes the exception edge (through
    every enclosing ``finally`` to the innermost handler, or out of
    the function);
  * a statement lexically inside a ``try`` that HAS ``except``
    handlers gets an implicit exception edge to those handlers — the
    try exists precisely because exceptions are expected there;
  * statements under handler-less ``try/finally``, or under no try at
    all, are assumed not to raise. Anything else makes the queries
    unsatisfiable: a mutation followed by its epoch bump would always
    carry a phantom exception path BETWEEN the two statements.
  * a dispatch to handlers is treated as fully caught (no "unmatched
    type" propagation edge) — a handler that re-raises does so with an
    explicit ``raise``, which IS modeled.

``finally`` bodies are instantiated once per abrupt edge that crosses
them (plus once for normal completion), so ``return`` inside
``try/finally`` correctly runs the cleanup nodes before reaching the
return exit — the fixture class tests/test_cfg.py locks down.

Nested ``def`` / ``lambda`` / ``class`` bodies do not execute inline:
they appear as single definition nodes and :func:`shallow_walk` (the
helper the passes use to evaluate predicates over one statement) never
descends into them.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable, Iterator, Optional


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def shallow_walk(root: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that never enters nested function / lambda / class
    bodies (they do not execute at the statement's program point).
    A def/class root therefore yields nothing — the definition
    statement itself performs none of its body's effects."""
    stack = [root]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class Node:
    """One CFG node: a statement (or expression evaluation point, for
    branch tests / loop iterables / with items) plus synthetic
    entry/exit/join nodes. ``regions`` is the set of lock-region ids
    active at this node; ``stmt`` is the AST the passes evaluate
    predicates over (None for synthetic nodes)."""

    __slots__ = ("idx", "line", "desc", "stmt", "succ", "regions", "kind")

    def __init__(self, idx: int, line: Optional[int], desc: str,
                 stmt: Optional[ast.AST] = None,
                 regions: tuple[int, ...] = (), kind: str = "stmt"):
        self.idx = idx
        self.line = line
        self.desc = desc
        self.stmt = stmt
        self.succ: list["Node"] = []
        self.regions = frozenset(regions)
        self.kind = kind

    def __repr__(self) -> str:  # debugging aid only
        return f"<{self.idx}:{self.desc}@{self.line}>"


class Region:
    """A lock-holding ``with`` region (one per matching with-item)."""

    __slots__ = ("rid", "lock_attr", "line")

    def __init__(self, rid: int, lock_attr: str, line: int):
        self.rid = rid
        self.lock_attr = lock_attr
        self.line = line


class FunctionCFG:
    """The CFG of one function. Build with :func:`build_cfg`."""

    def __init__(self, func, lock_attrs: Iterable[str] = ()):
        self.func = func
        self.lock_attrs = frozenset(lock_attrs)
        self.nodes: list[Node] = []
        self.regions: dict[int, Region] = {}
        #: active lock-region ids, innermost last
        self._active: tuple[int, ...] = ()
        #: frames, innermost last: ("loop", head, after) |
        #: ("finally", finalbody, frames_len, active_regions) |
        #: ("except", dispatch_node)
        self._frames: list[tuple] = []
        self.return_exit = self._new(None, "<return-exit>",
                                     kind="return_exit")
        self.raise_exit = self._new(None, "<raise-exit>", kind="raise_exit")
        self.entry = self._new(func.lineno, "<entry>", kind="entry")
        frontier = self._build_body(func.body, [self.entry])
        for n in frontier:  # falling off the end = implicit `return None`
            self._edge(n, self.return_exit)

    # -- graph primitives ----------------------------------------------------
    def _new(self, line: Optional[int], desc: str,
             stmt: Optional[ast.AST] = None, kind: str = "stmt",
             regions: Optional[tuple[int, ...]] = None) -> Node:
        n = Node(len(self.nodes), line, desc, stmt=stmt,
                 regions=self._active if regions is None else regions,
                 kind=kind)
        self.nodes.append(n)
        return n

    @staticmethod
    def _edge(u: Node, v: Node) -> None:
        if v not in u.succ:
            u.succ.append(v)

    def _stmt_node(self, stmt: ast.stmt, desc: Optional[str] = None) -> Node:
        return self._new(stmt.lineno, desc or type(stmt).__name__, stmt=stmt)

    # -- abrupt-completion routing -------------------------------------------
    def _chain_finally(self, pred: Node, frame: tuple) -> Optional[Node]:
        """Instantiate a ``finally`` body for one abrupt edge: build its
        statements fresh in the context saved at the try statement,
        entered from ``pred``. Returns the join node the abrupt edge
        continues from — or None when the finally body itself completes
        abruptly on every path (it hijacked control)."""
        _, finalbody, flen, factive = frame
        saved_frames, saved_active = self._frames, self._active
        self._frames, self._active = list(saved_frames[:flen]), factive
        try:
            frontier = self._build_body(finalbody, [pred])
            if not frontier:
                return None
            join = self._new(finalbody[0].lineno, "<finally-join>",
                             kind="join")
        finally:
            self._frames, self._active = saved_frames, saved_active
        for n in frontier:
            self._edge(n, join)
        return join

    def _route_return(self, src: Node) -> None:
        cur: Optional[Node] = src
        for fr in reversed(self._frames):
            if fr[0] == "finally":
                cur = self._chain_finally(cur, fr)
                if cur is None:
                    return
        self._edge(cur, self.return_exit)

    def _route_exception(self, src: Node) -> None:
        cur: Optional[Node] = src
        for fr in reversed(self._frames):
            if fr[0] == "finally":
                cur = self._chain_finally(cur, fr)
                if cur is None:
                    return
            elif fr[0] == "except":
                self._edge(cur, fr[1])
                return
        self._edge(cur, self.raise_exit)

    def _implicit_raise(self, src: Node) -> None:
        """Exception edge for a statement inside a handler-bearing try
        body. No-op when no enclosing try has handlers — see the module
        docstring's exception model."""
        if any(fr[0] == "except" for fr in self._frames):
            self._route_exception(src)

    def _route_loop_jump(self, src: Node, kind: str) -> None:
        cur: Optional[Node] = src
        for fr in reversed(self._frames):
            if fr[0] == "finally":
                cur = self._chain_finally(cur, fr)
                if cur is None:
                    return
            elif fr[0] == "loop":
                self._edge(cur, fr[1] if kind == "continue" else fr[2])
                return
        # break/continue outside a loop is a SyntaxError upstream;
        # treat defensively as function exit
        self._edge(cur, self.raise_exit)

    # -- statement builders ---------------------------------------------------
    def _build_body(self, stmts: list, frontier: list[Node]) -> list[Node]:
        for stmt in stmts:
            if not frontier:
                break  # unreachable tail (after return/raise/break)
            frontier = self._build_stmt(stmt, frontier)
        return frontier

    def _build_stmt(self, stmt: ast.stmt, frontier: list[Node]) -> list[Node]:
        if isinstance(stmt, ast.If):
            return self._build_if(stmt, frontier)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._build_loop(stmt, frontier)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._build_with(stmt, frontier)
        if isinstance(stmt, ast.Try) or (
                hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)):
            return self._build_try(stmt, frontier)
        if isinstance(stmt, ast.Return):
            n = self._stmt_node(stmt)
            self._connect(frontier, n)
            self._route_return(n)
            return []
        if isinstance(stmt, ast.Raise):
            n = self._stmt_node(stmt)
            self._connect(frontier, n)
            self._route_exception(n)
            return []
        if isinstance(stmt, ast.Break):
            n = self._stmt_node(stmt)
            self._connect(frontier, n)
            self._route_loop_jump(n, "break")
            return []
        if isinstance(stmt, ast.Continue):
            n = self._stmt_node(stmt)
            self._connect(frontier, n)
            self._route_loop_jump(n, "continue")
            return []
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            return self._build_match(stmt, frontier)
        # simple statement (incl. nested def/class, which contribute no
        # inline effects — shallow_walk keeps predicates out of them)
        n = self._stmt_node(stmt)
        self._connect(frontier, n)
        self._implicit_raise(n)
        return [n]

    def _connect(self, frontier: list[Node], n: Node) -> None:
        for u in frontier:
            self._edge(u, n)

    def _build_if(self, stmt: ast.If, frontier: list[Node]) -> list[Node]:
        test = self._new(stmt.lineno, "if-test", stmt=stmt.test)
        self._connect(frontier, test)
        self._implicit_raise(test)
        then_f = self._build_body(stmt.body, [test])
        else_f = (self._build_body(stmt.orelse, [test])
                  if stmt.orelse else [test])
        return then_f + else_f

    def _build_loop(self, stmt, frontier: list[Node]) -> list[Node]:
        head_expr = stmt.test if isinstance(stmt, ast.While) else stmt.iter
        head = self._new(stmt.lineno, "loop-head", stmt=head_expr)
        after = self._new(stmt.lineno, "<loop-exit>", kind="join")
        self._connect(frontier, head)
        self._implicit_raise(head)
        self._frames.append(("loop", head, after))
        try:
            body_f = self._build_body(stmt.body, [head])
        finally:
            self._frames.pop()
        for n in body_f:
            self._edge(n, head)
        if stmt.orelse:
            for n in self._build_body(stmt.orelse, [head]):
                self._edge(n, after)
        else:
            self._edge(head, after)
        return [after]

    def _build_with(self, stmt, frontier: list[Node]) -> list[Node]:
        saved_active = self._active
        for item in stmt.items:
            # runtime order for `with A, B:`: A's expr, acquire A, B's
            # expr (under A), acquire B — matching locks.py's model
            n = self._new(stmt.lineno, "with-item", stmt=item.context_expr)
            self._connect(frontier, n)
            self._implicit_raise(n)
            frontier = [n]
            attr = _self_attr(item.context_expr)
            if attr in self.lock_attrs:
                rid = len(self.regions)
                self.regions[rid] = Region(rid, attr, stmt.lineno)
                self._active = self._active + (rid,)
        try:
            body_f = self._build_body(stmt.body, frontier)
        finally:
            self._active = saved_active
        # edges from body_f to whatever follows naturally leave the
        # region (successors carry the restored, smaller region set)
        return body_f

    def _build_try(self, stmt, frontier: list[Node]) -> list[Node]:
        dispatch = None
        flen = len(self._frames)
        if stmt.finalbody:
            self._frames.append(("finally", stmt.finalbody, flen,
                                 self._active))
        if stmt.handlers:
            dispatch = self._new(stmt.lineno, "<except-dispatch>",
                                 kind="join")
            self._frames.append(("except", dispatch))
        try:
            body_f = self._build_body(stmt.body, frontier)
        finally:
            if dispatch is not None:
                self._frames.pop()  # handlers do not catch their own raises
        if stmt.orelse:
            body_f = self._build_body(stmt.orelse, body_f)
        handler_f: list[Node] = []
        for h in stmt.handlers:
            hnode = self._new(h.lineno, "<except-entry>", kind="join")
            self._edge(dispatch, hnode)
            handler_f.extend(self._build_body(h.body, [hnode]))
        merged = body_f + handler_f
        if stmt.finalbody:
            self._frames.pop()  # the abrupt edges already instantiated theirs
            merged = self._build_body(stmt.finalbody, merged) if merged else []
        return merged

    def _build_match(self, stmt, frontier: list[Node]) -> list[Node]:
        subject = self._new(stmt.lineno, "match-subject", stmt=stmt.subject)
        self._connect(frontier, subject)
        self._implicit_raise(subject)
        out: list[Node] = [subject]  # no case may match
        for case in stmt.cases:
            out.extend(self._build_body(case.body, [subject]))
        return out

    # -- region helpers -------------------------------------------------------
    def outermost_region(self, node: Node,
                         lock_attr: str) -> Optional[int]:
        """The OUTERMOST region over ``lock_attr`` containing the node —
        outermost because the re-entrant lock is truly released only
        when the outermost ``with`` exits."""
        matching = [rid for rid in sorted(node.regions)
                    if self.regions[rid].lock_attr == lock_attr]
        return matching[0] if matching else None


def build_cfg(func, lock_attrs: Iterable[str] = ()) -> FunctionCFG:
    """CFG for one ``ast.FunctionDef`` / ``AsyncFunctionDef``.
    ``lock_attrs`` names the ``self.<attr>`` context managers whose
    ``with`` blocks become tracked lock regions."""
    return FunctionCFG(func, lock_attrs)


# -- the two path queries -----------------------------------------------------

def escapes_region(
    cfg: FunctionCFG, start: Node, rid: int,
    satisfies: Callable[[Node], bool],
) -> list[tuple[Node, Node]]:
    """Edges (u, v) that leave lock region ``rid`` and are reachable
    from ``start`` without passing through a node where
    ``satisfies(node)`` holds. Empty means: on every path from
    ``start``, a satisfying node occurs before the region exits —
    return / raise / fallthrough edges included. A satisfying node
    OUTSIDE the region does not help (the lock was already released
    when it runs), exactly as the epoch invariant requires."""
    seen = {start.idx}
    stack = [start]
    out: list[tuple[Node, Node]] = []
    while stack:
        u = stack.pop()
        for v in u.succ:
            if rid in v.regions:
                if v.idx in seen:
                    continue
                seen.add(v.idx)
                if satisfies(v):
                    continue
                stack.append(v)
            else:
                out.append((u, v))
    return out


def escapes_function(
    cfg: FunctionCFG, start: Node,
    satisfies: Callable[[Node], bool],
) -> tuple[list[Node], list[Node]]:
    """(return-exit witnesses, raise-exit witnesses): the last real
    node on each path from ``start`` that reaches a function exit
    without passing a satisfying node. Both lists empty means every
    path from ``start`` — exception edges included — settles first."""
    seen = {start.idx}
    stack = [start]
    returns: list[Node] = []
    raises: list[Node] = []
    while stack:
        u = stack.pop()
        for v in u.succ:
            if v.kind == "return_exit":
                returns.append(u)
                continue
            if v.kind == "raise_exit":
                raises.append(u)
                continue
            if v.idx in seen:
                continue
            seen.add(v.idx)
            if satisfies(v):
                continue
            stack.append(v)
    return returns, raises

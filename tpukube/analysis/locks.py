"""Lock passes: discipline (no blocking I/O under scheduling locks),
order (acquisitions against the declared partial order), and
shared-state (registry-declared attributes touched under their lock).

All three are LEXICAL analyses: a ``with self._lock:`` region covers
the statements (and nested defs) textually inside it. Cross-function
flows — handle() holding the decision lock while bind() runs — are the
dynamic detector's job (``tpukube.analysis.lockgraph``); these passes
catch what is visible in one function body, which is where the bug
class historically entered.

The codebase convention the passes understand: a method named
``*_locked`` is documented as called with its class's lock already held
and is exempt from shared-state checking (its CALLERS are checked for
holding the lock around the call's siblings instead).
"""

from __future__ import annotations

import ast
from typing import Optional

from tpukube.analysis.base import Finding, SourceFile

# -- lock-discipline ---------------------------------------------------------

#: the scheduling-critical modules whose locks serialize every webhook
DISCIPLINE_SCOPE = (
    "sched/gang.py", "sched/extender.py", "sched/state.py",
)

#: the scheduling locks themselves (self.<name>)
SCHED_LOCKS = {"_lock", "_decision_lock", "_pending_lock"}

#: method names that block on I/O regardless of receiver: file/socket
#: writes and flushes, socket traffic, HTTP round-trips, time.sleep.
#: The JSONL capture sinks are covered by write/flush — JsonlSink.write
#: only enqueues, but calling ANY .write under a scheduling lock is
#: banned so a refactor swapping the sink for a raw file fails lint.
BLOCKING_METHODS = {
    "write", "flush", "send", "sendall", "recv", "connect", "fsync",
    "request", "getresponse", "urlopen", "sleep",
}

#: bare-name calls that block (stdout IS a file)
BLOCKING_NAMES = {"open", "print"}

#: receiver-qualified calls: subprocess spawns, requests HTTP
BLOCKING_QUALIFIED = {
    "subprocess": {"run", "Popen", "call", "check_call", "check_output"},
    "requests": {"get", "post", "put", "delete", "head", "patch"},
    "socket": {"create_connection"},
    "os": {"replace", "rename", "unlink", "system"},
}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _blocking_desc(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in BLOCKING_NAMES:
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        if isinstance(recv, ast.Name):
            qualified = BLOCKING_QUALIFIED.get(recv.id)
            if qualified and fn.attr in qualified:
                return f"{recv.id}.{fn.attr}()"
        if fn.attr in BLOCKING_METHODS:
            return f".{fn.attr}()"
    return None


def check_lock_discipline(sf: SourceFile) -> list[Finding]:
    """Flag blocking operations lexically inside ``with self._lock`` /
    ``_decision_lock`` / ``_pending_lock`` regions of the scheduling
    modules: one stalled write syscall there freezes every concurrent
    webhook (the emitters-only-enqueue invariant)."""
    if not sf.in_scope(DISCIPLINE_SCOPE):
        return []
    findings: list[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.held: list[str] = []

        def _visit_with(self, node) -> None:
            # runtime order for `with A, B:`: A's expr, acquire A, B's
            # expr (under A), acquire B — so each item's context expr is
            # checked under the locks of the items before it
            acquired = 0
            for item in node.items:
                self.visit(item.context_expr)
                a = _self_attr(item.context_expr)
                if a in SCHED_LOCKS:
                    self.held.append(a)
                    acquired += 1
            for stmt in node.body:
                self.visit(stmt)
            del self.held[len(self.held) - acquired:]

        visit_With = _visit_with
        visit_AsyncWith = _visit_with

        def visit_Call(self, node: ast.Call) -> None:
            if self.held:
                desc = _blocking_desc(node)
                if desc is not None:
                    findings.append(Finding(
                        "lock-discipline", sf.rel, node.lineno,
                        f"blocking call {desc} inside `with "
                        f"self.{self.held[-1]}` — scheduling locks may "
                        f"only guard memory; enqueue and do the I/O "
                        f"outside the lock",
                    ))
            self.generic_visit(node)

    V().visit(sf.tree)
    return findings


# -- lock-order --------------------------------------------------------------

#: the declared partial order (smaller level = acquired first /
#: outermost): decision -> pending -> gang -> ledger. Acquiring a
#: SMALLER level while holding a larger one is an inversion.
LOCK_LEVELS = {"decision": 0, "pending": 1, "gang": 2, "ledger": 3}

#: (path suffix, class) -> {self lock attr: (name, level)}
ORDERED_LOCKS = {
    ("sched/extender.py", "Extender"): {
        "_decision_lock": ("decision", 0),
        "_pending_lock": ("pending", 1),
    },
    ("sched/gang.py", "GangManager"): {"_lock": ("gang", 2)},
    ("sched/state.py", "ClusterState"): {"_lock": ("ledger", 3)},
}

#: (path suffix, class) -> {self.<root>.<method>() call root: lock it
#: acquires}. Calls through these attributes take the mapped lock.
CALL_ROOTS = {
    ("sched/extender.py", "Extender"): {
        "gang": ("gang", 2), "state": ("ledger", 3),
        # SnapshotCache.current() takes the gang lock first (epoch
        # read + build), then the ledger lock — level it at its
        # smallest acquisition so calling it under the ledger lock
        # flags as an inversion
        "snapshots": ("gang", 2),
    },
    ("sched/gang.py", "GangManager"): {
        "_state": ("ledger", 3),
        "snapshots": ("gang", 2),
    },
}

#: (path suffix, class) -> {self.<method>() that re-enters a lock}
SELF_METHODS = {
    ("sched/extender.py", "Extender"): {
        "handle": ("decision", 0), "release": ("decision", 0),
    },
}


def _class_configs(sf: SourceFile, table: dict) -> dict[str, dict]:
    out = {}
    for (suffix, cls), cfg in table.items():
        if sf.in_scope((suffix,)):
            out[cls] = cfg
    return out


def check_lock_order(sf: SourceFile) -> list[Finding]:
    """Flag statically visible inversions of the declared lock order
    within the scheduling classes: a nested ``with`` on a lower-level
    lock, or a call through an attribute known to take one."""
    lock_cfg = _class_configs(sf, ORDERED_LOCKS)
    if not lock_cfg:
        return []
    root_cfg = _class_configs(sf, CALL_ROOTS)
    meth_cfg = _class_configs(sf, SELF_METHODS)
    findings: list[Finding] = []

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        locks = lock_cfg.get(cls_node.name)
        if locks is None:
            continue
        roots = root_cfg.get(cls_node.name, {})
        methods = meth_cfg.get(cls_node.name, {})

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                # held (attr, name, level), acquisition order
                self.held: list[tuple[str, str, int]] = []

            def _flag(self, lineno: int, name: str, level: int,
                      how: str) -> None:
                attr, hname, hlevel = max(self.held, key=lambda h: h[2])
                if level < hlevel:
                    findings.append(Finding(
                        "lock-order", sf.rel, lineno,
                        f"{how} acquires the {name} lock (level "
                        f"{level}) while holding the {hname} lock "
                        f"(level {hlevel}); the declared order is "
                        f"decision -> pending -> gang -> ledger",
                    ))

            def _visit_with(self, node) -> None:
                # items acquire left to right: each is checked (and then
                # held) against the ones before it, so a single-statement
                # `with self._pending_lock, self._decision_lock:` is the
                # same inversion as the nested spelling
                acquired = 0
                for item in node.items:
                    self.visit(item.context_expr)
                    attr = _self_attr(item.context_expr)
                    entry = locks.get(attr) if attr else None
                    if entry is None:
                        continue
                    name, level = entry
                    already = any(h[0] == attr for h in self.held)
                    if self.held and not already:
                        self._flag(node.lineno, name, level,
                                   f"`with self.{attr}`")
                    self.held.append((attr, name, level))
                    acquired += 1
                for stmt in node.body:
                    self.visit(stmt)
                del self.held[len(self.held) - acquired:]

            visit_With = _visit_with
            visit_AsyncWith = _visit_with

            def visit_Call(self, node: ast.Call) -> None:
                if self.held and isinstance(node.func, ast.Attribute):
                    fn = node.func
                    # self.<root>.<method>(...)
                    root = _self_attr(fn.value)
                    if root is not None and root in roots:
                        name, level = roots[root]
                        self._flag(node.lineno, name, level,
                                   f"call self.{root}.{fn.attr}()")
                    # self.<method>(...)
                    if _self_attr(fn) is not None and fn.attr in methods:
                        name, level = methods[fn.attr]
                        self._flag(node.lineno, name, level,
                                   f"call self.{fn.attr}()")
                self.generic_visit(node)

        V().visit(cls_node)
    return findings


# -- shared-state ------------------------------------------------------------

#: The guarded-attribute registry, seeded from the classes whose state
#: is mutated from threading.Thread targets (webhook loop, watchers,
#: eviction/lifecycle loops, sink drains): (path suffix, class) ->
#: {attribute: the self lock that must be held to touch it}. Growing a
#: class a new cross-thread structure means declaring it here — the
#: lint then enforces the locking everywhere the attribute appears.
GUARDED_ATTRS = {
    ("sched/state.py", "ClusterState"): {
        "_nodes": "_lock", "_slices": "_lock", "_allocs": "_lock",
        "_hosts_cache": "_lock", "_epoch": "_lock",
        "_occ_cache": "_lock",
        # bulk ingest + generation resync structures (ISSUE 15):
        # touched from webhook threads, the background warmer, and
        # resync loops alike
        "_lazy_payloads": "_lock",
        "_gen_log": "_lock", "_generation": "_lock",
    },
    ("sched/gang.py", "GangManager"): {
        "_reservations": "_lock", "_terminating_coords": "_lock",
        "_epoch": "_lock",
    },
    ("sched/extender.py", "Extender"): {
        "_pending": "_pending_lock",
        "_bind_gang_info": "_decision_lock",
    },
    ("obs/events.py", "EventJournal"): {
        "_ring": "_lock", "_live": "_lock", "_by_reason": "_lock",
        "_seq": "_lock", "_total": "_lock",
    },
    ("obs/health.py", "HealthSampler"): {
        "_latest": "_lock", "_states": "_lock", "_windows": "_lock",
        "_transition_counts": "_lock",
    },
    ("plugin/server.py", "AllocIntentCache"): {
        "_intents": "_lock", "_satisfied": "_lock",
    },
    ("plugin/server.py", "DevicePluginServer"): {
        "_watch_queues": "_watch_lock",
    },
}


def check_shared_state(sf: SourceFile,
                       registry: Optional[dict] = None) -> list[Finding]:
    """Every read/write of a registry-declared attribute must sit
    lexically inside ``with self.<declared lock>``. ``__init__`` (no
    concurrency yet) and ``*_locked`` helpers (documented as called
    under the lock) are exempt."""
    table = registry if registry is not None else GUARDED_ATTRS
    cfg = _class_configs(sf, table)
    if not cfg:
        return []
    findings: list[Finding] = []

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        guarded = cfg.get(cls_node.name)
        if guarded is None:
            continue
        for fn in cls_node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                continue

            class V(ast.NodeVisitor):
                def __init__(self) -> None:
                    self.held: list[str] = []

                def _visit_with(self, node) -> None:
                    acquired = 0
                    for item in node.items:
                        self.visit(item.context_expr)
                        a = _self_attr(item.context_expr)
                        if a in set(guarded.values()):
                            self.held.append(a)
                            acquired += 1
                    for stmt in node.body:
                        self.visit(stmt)
                    del self.held[len(self.held) - acquired:]

                visit_With = _visit_with
                visit_AsyncWith = _visit_with

                def visit_Attribute(self, node: ast.Attribute) -> None:
                    attr = _self_attr(node)
                    lock = guarded.get(attr) if attr else None
                    if lock is not None and lock not in self.held:
                        findings.append(Finding(
                            "shared-state", sf.rel, node.lineno,
                            f"self.{attr} touched outside `with "
                            f"self.{lock}` — declared guarded in the "
                            f"shared-state registry "
                            f"(analysis/locks.py GUARDED_ATTRS)",
                        ))
                    self.generic_visit(node)

            V().visit(fn)
    return findings

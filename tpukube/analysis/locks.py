"""Lock passes: discipline (no blocking I/O under scheduling locks),
order (acquisitions against the declared partial order), and
shared-state (registry-declared attributes touched under their lock).

The lexical core is unchanged — a ``with self._lock:`` region covers
the statements (and nested defs) textually inside it — but since
ISSUE 18 the passes follow ``self.<method>()`` delegation ONE level
through :mod:`tpukube.analysis.callgraph`:

  * shared-state accepts an unguarded method when EVERY intra-class
    call site lexically holds the required lock (the caller-proof
    that used to be a waiver on ``Extender.bind``);
  * a call to a ``*_locked`` helper is itself checked for holding the
    locks the helper's body directly needs — the other half of the
    naming convention, previously only documented;
  * lock-order derives re-entry levels from method bodies instead of
    trusting the hand-kept SELF_METHODS list alone.

Deeper cross-function flows remain the dynamic detector's job
(``tpukube.analysis.lockgraph``).
"""

from __future__ import annotations

import ast
from typing import Optional

from tpukube.analysis import callgraph
from tpukube.analysis import cfg as cfg_mod
from tpukube.analysis.base import Finding, SourceFile

# -- lock-discipline ---------------------------------------------------------

#: the scheduling-critical modules whose locks serialize every webhook
DISCIPLINE_SCOPE = (
    "sched/gang.py", "sched/extender.py", "sched/state.py",
)

#: the scheduling locks themselves (self.<name>)
SCHED_LOCKS = {"_lock", "_decision_lock", "_pending_lock"}

#: class-scoped discipline: (path suffix, class) -> lock attrs whose
#: regions ban blocking I/O. Unlike DISCIPLINE_SCOPE (file-wide, every
#: class), this names ONE class in a file where other classes hold
#: locks around I/O BY DESIGN — SubprocessTransport serializes a
#: kept-alive HTTP connection under its ``_lock``, which is the whole
#: point of that lock, while the router's fan-out lock one class over
#: must never wedge ``/filter`` on a stalled worker socket.
CLASS_DISCIPLINE = {
    ("sched/shard.py", "ShardRouter"): frozenset({"_lock"}),
    ("obs/capacity.py", "CapacityRecorder"): frozenset({"_lock"}),
}

#: method names that block on I/O regardless of receiver: file/socket
#: writes and flushes, socket traffic, HTTP round-trips, time.sleep.
#: The JSONL capture sinks are covered by write/flush — JsonlSink.write
#: only enqueues, but calling ANY .write under a scheduling lock is
#: banned so a refactor swapping the sink for a raw file fails lint.
BLOCKING_METHODS = {
    "write", "flush", "send", "sendall", "recv", "connect", "fsync",
    "request", "getresponse", "urlopen", "sleep",
}

#: bare-name calls that block (stdout IS a file)
BLOCKING_NAMES = {"open", "print"}

#: receiver-qualified calls: subprocess spawns, requests HTTP
BLOCKING_QUALIFIED = {
    "subprocess": {"run", "Popen", "call", "check_call", "check_output"},
    "requests": {"get", "post", "put", "delete", "head", "patch"},
    "socket": {"create_connection"},
    "os": {"replace", "rename", "unlink", "system"},
}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _blocking_desc(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in BLOCKING_NAMES:
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        if isinstance(recv, ast.Name):
            qualified = BLOCKING_QUALIFIED.get(recv.id)
            if qualified and fn.attr in qualified:
                return f"{recv.id}.{fn.attr}()"
        if fn.attr in BLOCKING_METHODS:
            return f".{fn.attr}()"
    return None


def _discipline_findings(sf: SourceFile, root: ast.AST,
                         lock_attrs, findings: list[Finding]) -> None:
    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.held: list[str] = []

        def _visit_with(self, node) -> None:
            # runtime order for `with A, B:`: A's expr, acquire A, B's
            # expr (under A), acquire B — so each item's context expr is
            # checked under the locks of the items before it
            acquired = 0
            for item in node.items:
                self.visit(item.context_expr)
                a = _self_attr(item.context_expr)
                if a in lock_attrs:
                    self.held.append(a)
                    acquired += 1
            for stmt in node.body:
                self.visit(stmt)
            del self.held[len(self.held) - acquired:]

        visit_With = _visit_with
        visit_AsyncWith = _visit_with

        def visit_Call(self, node: ast.Call) -> None:
            if self.held:
                desc = _blocking_desc(node)
                if desc is not None:
                    findings.append(Finding(
                        "lock-discipline", sf.rel, node.lineno,
                        f"blocking call {desc} inside `with "
                        f"self.{self.held[-1]}` — scheduling locks may "
                        f"only guard memory; enqueue and do the I/O "
                        f"outside the lock",
                    ))
            self.generic_visit(node)

    V().visit(root)


def check_lock_discipline(sf: SourceFile) -> list[Finding]:
    """Flag blocking operations lexically inside ``with self._lock`` /
    ``_decision_lock`` / ``_pending_lock`` regions of the scheduling
    modules — plus, class-scoped, the router fan-out lock and the
    capacity recorder's ledger lock: one stalled write syscall there
    freezes every concurrent webhook (the emitters-only-enqueue
    invariant)."""
    findings: list[Finding] = []
    if sf.in_scope(DISCIPLINE_SCOPE):
        _discipline_findings(sf, sf.tree, SCHED_LOCKS, findings)
    for (suffix, cls), attrs in CLASS_DISCIPLINE.items():
        if not sf.in_scope((suffix,)):
            continue
        cls_node = callgraph.find_class(sf.tree, cls)
        if cls_node is not None:
            _discipline_findings(sf, cls_node, attrs, findings)
    return findings


# -- lock-order --------------------------------------------------------------

#: the declared partial order (smaller level = acquired first /
#: outermost): decision -> pending -> gang -> ledger -> journal ->
#: router. Acquiring a SMALLER level while holding a larger one is an
#: inversion. The journal's condition sits ABOVE the ledger because
#: ``_note_journal_locked`` enqueues from inside the ledger/gang
#: locks; the router's map lock is the innermost leaf by its own
#: contract ("never nests around replica state on the mutation path").
LOCK_LEVELS = {"decision": 0, "pending": 1, "gang": 2, "ledger": 3,
               "journal": 4, "router": 5}

#: (path suffix, class) -> {self lock attr: (name, level)}
ORDERED_LOCKS = {
    ("sched/extender.py", "Extender"): {
        "_decision_lock": ("decision", 0),
        "_pending_lock": ("pending", 1),
    },
    ("sched/gang.py", "GangManager"): {"_lock": ("gang", 2)},
    ("sched/state.py", "ClusterState"): {"_lock": ("ledger", 3)},
    ("sched/journal.py", "StateJournal"): {"_cond": ("journal", 4)},
    ("sched/shard.py", "ShardRouter"): {"_lock": ("router", 5)},
}

#: (path suffix, class) -> {self.<root>.<method>() call root: lock it
#: acquires}. Calls through these attributes take the mapped lock.
CALL_ROOTS = {
    ("sched/extender.py", "Extender"): {
        "gang": ("gang", 2), "state": ("ledger", 3),
        # SnapshotCache.current() takes the gang lock first (epoch
        # read + build), then the ledger lock — level it at its
        # smallest acquisition so calling it under the ledger lock
        # flags as an inversion
        "snapshots": ("gang", 2),
    },
    ("sched/gang.py", "GangManager"): {
        "_state": ("ledger", 3),
        "snapshots": ("gang", 2),
        "_journal": ("journal", 4),
    },
    ("sched/state.py", "ClusterState"): {
        "_journal": ("journal", 4),
    },
    # a fan-out under the router map lock calls into replica
    # extenders, which start at the decision lock — level the replica
    # surface at decision so ANY replica call under `with self._lock`
    # flags as an inversion of the leaf contract
    ("sched/shard.py", "ShardRouter"): {
        "state": ("decision", 0),
        "events": ("decision", 0),
        "cycle": ("decision", 0),
        "replicas": ("decision", 0),
    },
}

#: (path suffix, class) -> {self.<method>() that re-enters a lock}
SELF_METHODS = {
    ("sched/extender.py", "Extender"): {
        "handle": ("decision", 0), "release": ("decision", 0),
    },
}


def _class_configs(sf: SourceFile, table: dict) -> dict[str, dict]:
    out = {}
    for (suffix, cls), cfg in table.items():
        if sf.in_scope((suffix,)):
            out[cls] = cfg
    return out


def check_lock_order(sf: SourceFile) -> list[Finding]:
    """Flag statically visible inversions of the declared lock order
    within the scheduling classes: a nested ``with`` on a lower-level
    lock, or a call through an attribute known to take one."""
    lock_cfg = _class_configs(sf, ORDERED_LOCKS)
    if not lock_cfg:
        return []
    root_cfg = _class_configs(sf, CALL_ROOTS)
    meth_cfg = _class_configs(sf, SELF_METHODS)
    findings: list[Finding] = []

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        locks = lock_cfg.get(cls_node.name)
        if locks is None:
            continue
        roots = root_cfg.get(cls_node.name, {})
        # self.<method>() re-entry levels: the hand-kept SELF_METHODS
        # entries plus one level derived from the class's own bodies —
        # a method whose statements take `with self.<ordered lock>`
        # re-enters that level when called, so a self-call to it under
        # a higher level is the same inversion as the inline `with`.
        # Derived entries carry the lock ATTR so re-entry on the very
        # lock already held (the RLock case) is not flagged.
        methods: dict[str, tuple[str, int, Optional[str]]] = {}
        for mname, mfn in callgraph.methods_of(cls_node).items():
            for stmt in mfn.body:
                for n in cfg_mod.shallow_walk(stmt):
                    if not isinstance(n, (ast.With, ast.AsyncWith)):
                        continue
                    for item in n.items:
                        a = _self_attr(item.context_expr)
                        entry = locks.get(a) if a else None
                        if entry is None:
                            continue
                        name, level = entry
                        prev = methods.get(mname)
                        if prev is None or level < prev[1]:
                            methods[mname] = (name, level, a)
        for mname, (name, level) in meth_cfg.get(cls_node.name,
                                                 {}).items():
            methods[mname] = (name, level, None)

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                # held (attr, name, level), acquisition order
                self.held: list[tuple[str, str, int]] = []

            def _flag(self, lineno: int, name: str, level: int,
                      how: str) -> None:
                attr, hname, hlevel = max(self.held, key=lambda h: h[2])
                if level < hlevel:
                    findings.append(Finding(
                        "lock-order", sf.rel, lineno,
                        f"{how} acquires the {name} lock (level "
                        f"{level}) while holding the {hname} lock "
                        f"(level {hlevel}); the declared order is "
                        f"decision -> pending -> gang -> ledger "
                        f"-> journal -> router",
                    ))

            def _visit_with(self, node) -> None:
                # items acquire left to right: each is checked (and then
                # held) against the ones before it, so a single-statement
                # `with self._pending_lock, self._decision_lock:` is the
                # same inversion as the nested spelling
                acquired = 0
                for item in node.items:
                    self.visit(item.context_expr)
                    attr = _self_attr(item.context_expr)
                    entry = locks.get(attr) if attr else None
                    if entry is None:
                        continue
                    name, level = entry
                    already = any(h[0] == attr for h in self.held)
                    if self.held and not already:
                        self._flag(node.lineno, name, level,
                                   f"`with self.{attr}`")
                    self.held.append((attr, name, level))
                    acquired += 1
                for stmt in node.body:
                    self.visit(stmt)
                del self.held[len(self.held) - acquired:]

            visit_With = _visit_with
            visit_AsyncWith = _visit_with

            def visit_Call(self, node: ast.Call) -> None:
                if self.held and isinstance(node.func, ast.Attribute):
                    fn = node.func
                    # self.<root>.<method>(...)
                    root = _self_attr(fn.value)
                    if root is not None and root in roots:
                        name, level = roots[root]
                        self._flag(node.lineno, name, level,
                                   f"call self.{root}.{fn.attr}()")
                    # self.<method>(...)
                    if _self_attr(fn) is not None and fn.attr in methods:
                        name, level, attr = methods[fn.attr]
                        if not (attr is not None
                                and any(h[0] == attr
                                        for h in self.held)):
                            self._flag(node.lineno, name, level,
                                       f"call self.{fn.attr}()")
                self.generic_visit(node)

        V().visit(cls_node)
    return findings


# -- shared-state ------------------------------------------------------------

#: The guarded-attribute registry, seeded from the classes whose state
#: is mutated from threading.Thread targets (webhook loop, watchers,
#: eviction/lifecycle loops, sink drains): (path suffix, class) ->
#: {attribute: the self lock that must be held to touch it}. Growing a
#: class a new cross-thread structure means declaring it here — the
#: lint then enforces the locking everywhere the attribute appears.
GUARDED_ATTRS = {
    ("sched/state.py", "ClusterState"): {
        "_nodes": "_lock", "_slices": "_lock", "_allocs": "_lock",
        "_hosts_cache": "_lock", "_epoch": "_lock",
        "_occ_cache": "_lock",
        # bulk ingest + generation resync structures (ISSUE 15):
        # touched from webhook threads, the background warmer, and
        # resync loops alike
        "_lazy_payloads": "_lock",
        "_gen_log": "_lock", "_generation": "_lock",
    },
    ("sched/gang.py", "GangManager"): {
        "_reservations": "_lock", "_terminating_coords": "_lock",
        "_epoch": "_lock",
    },
    ("sched/extender.py", "Extender"): {
        "_pending": "_pending_lock",
        "_bind_gang_info": "_decision_lock",
    },
    ("obs/events.py", "EventJournal"): {
        "_ring": "_lock", "_live": "_lock", "_by_reason": "_lock",
        "_seq": "_lock", "_total": "_lock",
    },
    ("obs/health.py", "HealthSampler"): {
        "_latest": "_lock", "_states": "_lock", "_windows": "_lock",
        "_transition_counts": "_lock",
    },
    ("plugin/server.py", "AllocIntentCache"): {
        "_intents": "_lock", "_satisfied": "_lock",
    },
    ("plugin/server.py", "DevicePluginServer"): {
        "_watch_queues": "_watch_lock",
    },
    # the sharded plane (ISSUE 18): the router's routing maps are
    # mutated from webhook threads, the fan-out pool's callbacks, and
    # the health/respawn loop alike — all behind the leaf map lock.
    # (_swept_at and the counters stay unregistered: single-writer or
    # deliberately lock-free "last seen" scalars.)
    ("sched/shard.py", "ShardRouter"): {
        "_slice_replica": "_lock", "_node_replica": "_lock",
        "_pod_replica": "_lock", "_gang_replica": "_lock",
        "_dcn": "_lock", "_pod_attempts": "_lock",
        "_aborted_dcn": "_lock", "_alloc_cache": "_lock",
        "_gauge_cache": "_lock", "_fit_cache": "_lock",
        "_rsv_cache": "_lock",
    },
    # the journal's enqueue surface: everything the drain thread and
    # the under-the-ledger note() path share rides the condition.
    # (_file/_bytes stay unregistered — drain-thread-owned, except the
    # pre-serving compact_wal, which holds the cond anyway.)
    ("sched/journal.py", "StateJournal"): {
        "_queue": "_cond", "_seq": "_cond", "_closed": "_cond",
        "_ckpt_wanted": "_cond", "_last_ckpt_req": "_cond",
    },
    # the capacity recorder's stranded ledger and its per-demand
    # classification memo: written from planner refusal seams, read
    # and expired from the observability listener's threads.
    ("obs/capacity.py", "CapacityRecorder"): {
        "_stranded": "_lock", "_classified_at": "_lock",
    },
}

#: attributes serialized by ANOTHER object's lock: (path suffix,
#: class) -> {holder attr: lock attr}. ``SchedulingCycle`` owns no
#: lock — the Extender serializes every touch under its decision
#: lock — so the checkable seam is the CALL SITE: every
#: ``self.cycle.<m>(...)`` in the Extender outside `with
#: self._decision_lock` is a finding (``__init__`` and ``*_locked``
#: exempt, like the attribute check).
EXTERNALLY_LOCKED_ROOTS = {
    ("sched/extender.py", "Extender"): {
        "cycle": "_decision_lock",
    },
}


def _unguarded_touches(fn, guarded: dict) -> list[tuple[int, str, str]]:
    """(line, attr, lock) for every registry-declared attribute touched
    outside a lexical ``with self.<lock>`` within one function body."""
    out: list[tuple[int, str, str]] = []
    lock_attrs = set(guarded.values())

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.held: list[str] = []

        def _visit_with(self, node) -> None:
            acquired = 0
            for item in node.items:
                self.visit(item.context_expr)
                a = _self_attr(item.context_expr)
                if a in lock_attrs:
                    self.held.append(a)
                    acquired += 1
            for stmt in node.body:
                self.visit(stmt)
            del self.held[len(self.held) - acquired:]

        visit_With = _visit_with
        visit_AsyncWith = _visit_with

        def visit_Attribute(self, node: ast.Attribute) -> None:
            attr = _self_attr(node)
            lock = guarded.get(attr) if attr else None
            if lock is not None and lock not in self.held:
                out.append((node.lineno, attr, lock))
            self.generic_visit(node)

    V().visit(fn)
    return out


def check_shared_state(sf: SourceFile,
                       registry: Optional[dict] = None) -> list[Finding]:
    """Every read/write of a registry-declared attribute must sit
    lexically inside ``with self.<declared lock>``. ``__init__`` (no
    concurrency yet) is exempt. Two interprocedural refinements ride
    the intra-class call graph (one level, closed-world):

      * a method whose touches are unguarded is ACCEPTED when every
        intra-class call site lexically holds the required lock — the
        Extender.bind pattern, previously a waiver;
      * a ``*_locked`` helper's own body stays exempt, but every call
        site of it must hold the locks the body's touches need — the
        other half of the naming contract.

    Plus the EXTERNALLY_LOCKED_ROOTS seam: calls through a holder
    attribute that another object's lock serializes (``self.cycle``
    under the decision lock) are checked at the call site."""
    table = registry if registry is not None else GUARDED_ATTRS
    cfg_tbl = _class_configs(sf, table)
    findings: list[Finding] = []

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        guarded = cfg_tbl.get(cls_node.name)
        if guarded is None:
            continue
        locks_all = frozenset(guarded.values())
        cg = callgraph.ClassGraph(cls_node, locks_all)

        def site_held(site: callgraph.Site) -> frozenset:
            c = site.caller
            if c.name == "__init__" or c.name.endswith("_locked"):
                # no concurrency yet / documented as under the lock
                return locks_all
            return site.held

        for fn in cls_node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue
            touches = _unguarded_touches(fn, guarded)
            if not touches:
                continue
            if fn.name.endswith("_locked"):
                # the body is exempt; its CALLERS must hold what the
                # body's direct touches need
                needed = sorted({lock for _, _, lock in touches})
                for site in cg.sites_of(fn.name):
                    missing = [lk for lk in needed
                               if lk not in site_held(site)]
                    if missing:
                        findings.append(Finding(
                            "shared-state", sf.rel, site.call.lineno,
                            f"self.{fn.name}() called without holding "
                            f"`self.{missing[0]}` — its body touches "
                            f"attributes declared guarded in the "
                            f"shared-state registry "
                            f"(analysis/locks.py GUARDED_ATTRS)",
                        ))
                continue
            # caller-proof, per lock: an unguarded touch is accepted
            # when every intra-class call site of this method holds
            # its lock (and at least one such site exists)
            sites = cg.sites_of(fn.name)
            proven = {
                lk for lk in {lock for _, _, lock in touches}
                if sites and all(lk in site_held(s) for s in sites)
            }
            for line, attr, lock in touches:
                if lock in proven:
                    continue
                findings.append(Finding(
                    "shared-state", sf.rel, line,
                    f"self.{attr} touched outside `with "
                    f"self.{lock}` — declared guarded in the "
                    f"shared-state registry "
                    f"(analysis/locks.py GUARDED_ATTRS)",
                ))

    for (suffix, cls), roots in EXTERNALLY_LOCKED_ROOTS.items():
        if not sf.in_scope((suffix,)):
            continue
        cls_node = callgraph.find_class(sf.tree, cls)
        if cls_node is None:
            continue
        for fn in cls_node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                continue
            for line, holder, lock in _holder_calls(fn, roots):
                findings.append(Finding(
                    "shared-state", sf.rel, line,
                    f"call through self.{holder} outside `with "
                    f"self.{lock}` — self.{holder} owns no lock and "
                    f"is serialized by the {cls} {lock} "
                    f"(analysis/locks.py EXTERNALLY_LOCKED_ROOTS)",
                ))
    return findings


def _holder_calls(fn, roots: dict) -> list[tuple[int, str, str]]:
    """(line, holder, lock) for every ``self.<holder>.<m>(...)`` call
    outside a lexical ``with self.<lock>`` within one function."""
    out: list[tuple[int, str, str]] = []
    lock_attrs = set(roots.values())

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.held: list[str] = []

        def _visit_with(self, node) -> None:
            acquired = 0
            for item in node.items:
                self.visit(item.context_expr)
                a = _self_attr(item.context_expr)
                if a in lock_attrs:
                    self.held.append(a)
                    acquired += 1
            for stmt in node.body:
                self.visit(stmt)
            del self.held[len(self.held) - acquired:]

        visit_With = _visit_with
        visit_AsyncWith = _visit_with

        def visit_Call(self, node: ast.Call) -> None:
            if isinstance(node.func, ast.Attribute):
                holder = _self_attr(node.func.value)
                lock = roots.get(holder) if holder else None
                if lock is not None and lock not in self.held:
                    out.append((node.lineno, holder, lock))
            self.generic_visit(node)

    V().visit(fn)
    return out

"""Dynamic lock-order detector — the runtime half of tpukube-lint.

Lockdep for the control plane: ``install()`` replaces the
``threading.Lock``/``threading.RLock`` factories with ones that wrap
locks created BY TPUKUBE CODE in a recording proxy (third-party and
stdlib-internal locks — grpc, logging, Condition/Event internals — stay
raw, so the graph holds exactly the locks the codebase declares).
Every acquisition records happens-before edges from each lock the
thread already holds to the one being acquired, aggregated by lock
CREATION SITE (``file:lineno`` — lockdep's lock-class notion: all
GangManager._lock instances are one node). A cycle in that graph means
two threads can acquire the same lock classes in opposite orders — a
potential deadlock, reported without ever having to hit it.

Off by default with zero overhead: nothing is patched until
``install()`` runs. The ``lock_monitor`` config flag turns it on for
``tpukube-sim`` (the result JSON gains a ``lock_graph`` key) and for
``SimCluster``; tests use the ``monitor()`` context manager directly.
Reentrant acquisitions of the same instance record no edge (RLocks);
distinct instances of one site DO edge, including self-edges — two
ClusterStates locked against each other is a real inversion class.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Optional

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_OWN_FILE = __file__

#: the default instrumentation scope: files under the tpukube package
#: directory itself — a PATH PREFIX, not a substring, so an install
#: under e.g. ~/src/tpukube/.venv/site-packages/ does not accidentally
#: instrument aiohttp/grpc internals (foreign lock orders would pollute
#: the graph with cycles unrelated to the declared scheduling locks)
PACKAGE_SCOPE = os.path.dirname(
    os.path.dirname(os.path.abspath(_OWN_FILE))
) + os.sep

# install()/uninstall() bookkeeping — guarded by a raw (never proxied)
# lock; ref-counted so nested installs (SimCluster inside a monitored
# test) share one monitor
_state_mu = _REAL_LOCK()
_active: Optional["LockOrderMonitor"] = None
_depth = 0


class _LockProxy:
    """Records acquire/release around a real lock. Everything else —
    including Condition's _release_save/_acquire_restore fast path —
    delegates to the wrapped lock via __getattr__."""

    def __init__(self, inner, site: str, monitor: "LockOrderMonitor"):
        self._inner = inner
        self.site = site
        self._monitor = monitor

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._monitor.on_acquired(self)
        return got

    def release(self) -> None:
        self._inner.release()  # raises before any bookkeeping if unowned
        self._monitor.on_released(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<LockProxy {self.site} of {self._inner!r}>"


class LockOrderMonitor:
    """The acquisition-order graph: nodes are lock creation sites,
    edges are observed held->acquired pairs, per thread."""

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        self._local = threading.local()
        self._edges: dict[tuple[str, str], int] = {}
        self._sites: dict[str, int] = {}  # site -> locks created there
        # id(proxy) -> the per-thread stack it is currently held on:
        # plain Locks may legally be RELEASED by a different thread
        # (handoff patterns), and the proxy must leave its acquiring
        # thread's stack either way — a stale entry would fabricate
        # held->acquired edges (and possibly cycles) forever after
        self._holder: dict[int, list] = {}
        self.acquisitions = 0

    # -- wrapping ----------------------------------------------------------
    def wrap(self, inner, site: str):
        with self._mu:
            self._sites[site] = self._sites.get(site, 0) + 1
        return _LockProxy(inner, site, self)

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def on_acquired(self, proxy: _LockProxy) -> None:
        st = self._stack()
        reentrant = any(h is proxy for h in st)
        if not reentrant:
            with self._mu:
                self.acquisitions += 1
                for held in list(st):
                    if held is proxy:
                        continue
                    key = (held.site, proxy.site)
                    self._edges[key] = self._edges.get(key, 0) + 1
                self._holder[id(proxy)] = st
        st.append(proxy)

    def on_released(self, proxy: _LockProxy) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is proxy:
                del st[i]
                if not any(h is proxy for h in st):
                    with self._mu:
                        self._holder.pop(id(proxy), None)
                return
        # released on a different thread than the acquirer (legal for
        # plain Locks): clear the proxy from ITS holder's stack so that
        # thread's future acquisitions record no phantom edges
        with self._mu:
            holder = self._holder.pop(id(proxy), None)
            if holder is not None:
                for i in range(len(holder) - 1, -1, -1):
                    if holder[i] is proxy:
                        del holder[i]
                        break

    # -- the graph ---------------------------------------------------------
    def edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def cycles(self) -> list[list[str]]:
        """Cycles in the site graph (Tarjan SCCs of size > 1, plus
        self-loops): each is a set of lock classes some pair of threads
        can acquire in opposite orders — a potential deadlock."""
        return self._cycles_of(self.edges())

    @staticmethod
    def _cycles_of(edges: dict[tuple[str, str], int]) -> list[list[str]]:
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # iterative Tarjan (daemon graphs are small, but recursion
            # limits are not a failure mode a linter should have)
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        out = []
        for scc in sccs:
            if len(scc) > 1 or (scc[0], scc[0]) in edges:
                out.append(sorted(scc))
        return sorted(out)

    def report(self) -> dict[str, Any]:
        """The lock graph as plain JSON: what `tpukube-sim` attaches
        under the ``lock_graph`` result key when lock_monitor is on.
        One consistent snapshot under the monitor's own lock — daemon
        threads may still be creating/acquiring locks while a live
        cluster is being inspected."""
        with self._mu:
            sites = dict(sorted(self._sites.items()))
            edges = dict(self._edges)
            acquisitions = self.acquisitions
        return {
            "sites": sites,
            "acquisitions": acquisitions,
            "edges": [
                {"from": a, "to": b, "count": n}
                for (a, b), n in sorted(edges.items())
            ],
            "cycles": self._cycles_of(edges),
        }


def _trim(filename: str) -> str:
    marker = "tpukube"
    idx = filename.rfind(marker)
    return filename[idx:] if idx >= 0 else filename


def _make_factory(real, scope: Optional[str]):
    def factory(*args, **kwargs):
        inner = real(*args, **kwargs)
        with _state_mu:
            mon = _active
        if mon is None:
            return inner
        frame = sys._getframe(1)
        filename = frame.f_code.co_filename
        if filename == _OWN_FILE:
            return inner  # never instrument the monitor's own locks
        if scope is not None \
                and not os.path.abspath(filename).startswith(scope):
            # only locks created DIRECTLY by in-scope code: stdlib
            # internals (Condition/Event/Thread plumbing) and
            # third-party libraries stay raw
            return inner
        return mon.wrap(inner, f"{_trim(filename)}:{frame.f_lineno}")
    return factory


def install(scope: Optional[str] = PACKAGE_SCOPE) -> LockOrderMonitor:
    """Patch the threading.Lock/RLock factories; ref-counted (nested
    installs share the first monitor). ``scope`` is the directory
    prefix lock-creating files must live under (default: the tpukube
    package; None = instrument everything except this module). The
    patch itself happens under the state mutex so concurrent
    install/uninstall cannot leave an active monitor with unpatched
    factories (or vice versa)."""
    global _active, _depth
    with _state_mu:
        if _depth > 0:
            _depth += 1
            assert _active is not None
            return _active
        _active = LockOrderMonitor()
        _depth = 1
        monitor = _active
        threading.Lock = _make_factory(_REAL_LOCK, scope)
        threading.RLock = _make_factory(_REAL_RLOCK, scope)
    return monitor


def active() -> Optional[LockOrderMonitor]:
    """The currently installed monitor, or None when the detector is
    off. The read-only accessor the federated surfaces use: a worker's
    ``replica_summary`` attaches its report when a monitor is live, and
    the router merges the fleet's edge sets into one cycle check —
    without either surface owning install/uninstall."""
    with _state_mu:
        return _active


def uninstall() -> None:
    """Undo one install(); the factories revert when the last nested
    install unwinds. Live proxies keep recording into their monitor —
    a daemon thread outliving the monitored window stays observed."""
    global _active, _depth
    with _state_mu:
        if _depth == 0:
            return
        _depth -= 1
        if _depth > 0:
            return
        _active = None
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK


class monitor:
    """Context manager: ``with lockgraph.monitor() as mon: ...`` then
    inspect ``mon.report()`` / ``mon.cycles()``."""

    def __init__(self, scope: Optional[str] = PACKAGE_SCOPE):
        self._scope = scope
        self._monitor: Optional[LockOrderMonitor] = None

    def __enter__(self) -> LockOrderMonitor:
        self._monitor = install(scope=self._scope)
        return self._monitor

    def __exit__(self, *exc) -> None:
        uninstall()

"""tpukube-lint core: findings, waiver pragmas, the source-file model,
and the pass runner.

Every pass is a function ``check(sf: SourceFile) -> list[Finding]``;
``run_all`` walks the requested paths, runs every (or a selected subset
of) pass, applies waivers, and appends the ``bare-waiver`` findings for
malformed pragmas. Passes scope themselves by path suffix (e.g.
lock-discipline only fires on ``sched/gang.py`` / ``sched/extender.py``
/ ``sched/state.py``), which is also what makes them testable against
fixture trees: a snippet written to ``<tmp>/sched/gang.py`` is in scope.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Optional

#: every rule name the runner knows
ALL_RULES: tuple[str, ...] = (
    "lock-discipline",
    "lock-order",
    "shared-state",
    "name-consistency",
    "snapshot-discipline",
    "exception-hygiene",
    "epoch-discipline",
    "reservation-leak",
    "decision-provenance",
    "seam-triple",
    "flag-discipline",
    "unused-waiver",
    "bare-waiver",
)

#: the meta rules lint the waiver mechanism itself — a malformed or
#: stale pragma cannot excuse itself, so neither is waivable
META_RULES: tuple[str, ...] = ("unused-waiver", "bare-waiver")

#: rules a waiver pragma may legitimately name — by NAME, not tuple
#: position: the old ``ALL_RULES[:-1]`` slice silently broke the
#: "known rules" message the day a rule was appended after bare-waiver
WAIVABLE_RULES: tuple[str, ...] = tuple(
    r for r in ALL_RULES if r not in META_RULES
)

WAIVER_RE = re.compile(
    r"#\s*tpukube:\s*allow\(\s*"
    r"(?P<rules>[a-z][a-z0-9-]*(?:\s*,\s*[a-z][a-z0-9-]*)*)\s*\)"
    r"\s*(?P<why>.*)$"
)


@dataclass(frozen=True)
class Finding:
    """One lint violation, anchored to a source line."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclass(frozen=True)
class Waiver:
    line: int
    rules: tuple[str, ...]
    justification: str


class SourceFile:
    """One parsed source file: AST + the waiver pragmas in its comments."""

    def __init__(self, path, text: Optional[str] = None,
                 rel: Optional[str] = None):
        self.path = Path(path)
        self.rel = rel if rel is not None else str(path)
        self.text = self.path.read_text() if text is None else text
        self.tree = ast.parse(self.text, filename=str(path))
        self.waivers: dict[int, Waiver] = {}
        for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = WAIVER_RE.search(tok.string)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(","))
            self.waivers[tok.start[0]] = Waiver(
                tok.start[0], rules, m.group("why").strip()
            )

    def in_scope(self, suffixes: Iterable[str]) -> bool:
        posix = self.path.as_posix()
        return any(posix.endswith(s) for s in suffixes)

    def waiver_for(self, rule: str, line: int) -> Optional[Waiver]:
        """The waiver covering a finding at ``line``: same line, or a
        waiver comment on the line directly above (long statements)."""
        for ln in (line, line - 1):
            w = self.waivers.get(ln)
            if w is not None and rule in w.rules:
                return w
        return None


def _passes() -> dict[str, Callable[[SourceFile], list[Finding]]]:
    # imported lazily: the pass modules import from base
    from tpukube.analysis import (
        consistency,
        epochs,
        flags,
        hygiene,
        leaks,
        locks,
        provenance,
        seams,
    )

    return {
        "lock-discipline": locks.check_lock_discipline,
        "lock-order": locks.check_lock_order,
        "shared-state": locks.check_shared_state,
        "name-consistency": consistency.check_names,
        "snapshot-discipline": consistency.check_snapshot_discipline,
        "exception-hygiene": hygiene.check_exceptions,
        "epoch-discipline": epochs.check_epochs,
        "reservation-leak": leaks.check_leaks,
        "decision-provenance": provenance.check_provenance,
        "seam-triple": seams.check_seam_triples,
        "flag-discipline": flags.check_flags,
    }


def iter_source_files(
    paths: Iterable,
) -> tuple[list[SourceFile], list[Finding]]:
    """Every lintable .py under the given files/directories, plus a
    ``parse-error`` finding per file that cannot be tokenized/parsed —
    an unparseable file (mid-edit, conflict markers) must surface as a
    pointed finding, not crash the whole lint run. Generated protobuf
    modules are excluded (not ours to discipline)."""
    out: list[SourceFile] = []
    errors: list[Finding] = []
    for p in paths:
        p = Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            if f.name.endswith("_pb2.py"):
                continue
            rel = os.path.relpath(f)
            try:
                out.append(SourceFile(f, rel=rel))
            except (SyntaxError, ValueError, UnicodeDecodeError,
                    tokenize.TokenError) as e:
                line = getattr(e, "lineno", None) or 1
                errors.append(Finding(
                    "parse-error", rel, line,
                    f"file does not parse, no pass can check it: {e}",
                ))
    return out, errors


def changed_paths(paths: Iterable, ref: str = "HEAD") -> list[Path]:
    """The lintable .py files under ``paths`` that differ from git
    ``ref`` (worktree + index) or are untracked — the fast pre-commit
    loop behind ``tpukube-lint --changed``. Raises ``ValueError`` on
    git trouble (not a repo, unknown ref): the CLI maps that to a
    usage error, distinct from findings."""
    import subprocess

    roots = [Path(p).resolve() for p in paths]
    start = roots[0] if roots[0].is_dir() else roots[0].parent

    def _git(cwd: Path, *args: str) -> list[str]:
        proc = subprocess.run(
            ["git", *args], cwd=cwd, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise ValueError(
                f"git {' '.join(args)} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        return [ln for ln in proc.stdout.splitlines() if ln]

    top = Path(_git(start, "rev-parse", "--show-toplevel")[0])
    # run the listings from the TOPLEVEL: ls-files --others prints
    # cwd-relative paths and, from a subdirectory, only that subtree —
    # joined to `top` below, a subdir cwd would silently drop exactly
    # the untracked files a pre-commit loop most needs to lint
    names = set(_git(top, "diff", "--name-only", ref, "--"))
    names |= set(_git(top, "ls-files", "--others", "--exclude-standard"))
    out: list[Path] = []
    for name in sorted(names):
        f = (top / name).resolve()
        if f.suffix != ".py" or f.name.endswith("_pb2.py"):
            continue
        if not f.exists():  # deleted vs ref: nothing to lint
            continue
        if any(f == r or r in f.parents for r in roots):
            out.append(f)
    return out


def find_rules_file(paths: Iterable) -> Optional[Path]:
    """Locate deploy/prometheus-rules.yaml relative to the linted tree
    (the deploy/ directory is the package directory's sibling)."""
    for p in paths:
        p = Path(p).resolve()
        for base in (p if p.is_dir() else p.parent, p.parent):
            cand = base / "deploy" / "prometheus-rules.yaml"
            if cand.exists():
                return cand
    return None


def waiver_findings(sf: SourceFile) -> list[Finding]:
    """The waiver mechanism's own lint: a waiver must carry a trailing
    justification and may only name known rules."""
    out = []
    for w in sf.waivers.values():
        if not w.justification:
            out.append(Finding(
                "bare-waiver", sf.rel, w.line,
                f"waiver for ({', '.join(w.rules)}) carries no "
                f"justification — say why the rule does not apply here",
            ))
        for rule in w.rules:
            if rule not in WAIVABLE_RULES:
                out.append(Finding(
                    "bare-waiver", sf.rel, w.line,
                    f"waiver names unknown or unwaivable rule {rule!r} "
                    f"(known: {', '.join(WAIVABLE_RULES)})",
                ))
    return out


def apply_waivers(sf: SourceFile, findings: Iterable[Finding],
                  used: Optional[set] = None) -> list[Finding]:
    """Drop findings covered by a waiver pragma; the meta rules
    (bare-waiver, unused-waiver) are never waivable — a malformed or
    stale pragma cannot excuse itself. ``used`` (when given) collects
    the ``(waiver line, rule)`` pairs that actually suppressed a
    finding — the input of the stale-waiver check."""
    kept: list[Finding] = []
    for f in findings:
        if f.rule in META_RULES:
            kept.append(f)
            continue
        w = sf.waiver_for(f.rule, f.line)
        if w is None:
            kept.append(f)
        elif used is not None:
            used.add((w.line, f.rule))
    return kept


def unused_waiver_findings(sf: SourceFile, used: set,
                           selected: set) -> list[Finding]:
    """Stale-waiver lint: a waiver whose rules all RAN in this
    invocation and suppressed nothing has outlived the code it excused
    — delete it (or fix the rule name). Waivers naming a rule that was
    deselected are skipped: a partial ``--rules`` run proves nothing
    about them. Waivers that only name unknown rules are bare-waiver's
    problem, not staleness."""
    out: list[Finding] = []
    for w in sf.waivers.values():
        considered = [r for r in w.rules
                      if r in WAIVABLE_RULES and r in selected]
        if not considered or len(considered) != len(
                [r for r in w.rules if r in WAIVABLE_RULES]):
            continue
        if any((w.line, r) in used for r in considered):
            continue
        out.append(Finding(
            "unused-waiver", sf.rel, w.line,
            f"waiver for ({', '.join(w.rules)}) suppressed no findings "
            f"in this run — the code it excused is gone; delete the "
            f"pragma so it cannot hide a future regression",
        ))
    return out


def run_all(paths: Iterable, rules: Optional[Iterable[str]] = None,
            rules_file=None) -> list[Finding]:
    """Run the selected passes (default: all) over ``paths`` plus the
    prometheus-rules cross-check, returning unwaived findings sorted by
    (path, line). ``rules_file`` overrides the deploy/ auto-discovery
    (which simply finds nothing on an isolated fixture tree)."""
    selected = set(rules) if rules is not None else set(ALL_RULES)
    unknown = selected - set(ALL_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
    passes = {k: v for k, v in _passes().items() if k in selected}
    sources, findings = iter_source_files(paths)
    for sf in sources:
        per_file: list[Finding] = []
        for check in passes.values():
            per_file.extend(check(sf))
        if "bare-waiver" in selected:
            per_file.extend(waiver_findings(sf))
        used: set = set()
        kept = apply_waivers(sf, per_file, used)
        if "unused-waiver" in selected:
            kept.extend(unused_waiver_findings(sf, used, selected))
        findings.extend(kept)
    if "name-consistency" in selected:
        from tpukube.analysis import consistency

        if rules_file is None:
            rules_file = find_rules_file(paths)
        if rules_file:
            findings.extend(consistency.check_rules_file(rules_file))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))

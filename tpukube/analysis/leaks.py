"""reservation-leak: every path from a reservation / preemption-plan
acquire to function exit must reach commit, rollback, or an explicit
hand-off — exception edges included.

PR 4's crash-safety story (scenarios 8-9: zero leaked reservations,
zero ledger divergence) rests on a handful of functions each upholding
"acquire then settle on ALL exits" by hand: ``Extender.bind`` releases
its ledger commit on every error path, ``_execute_pending_preemption``
never drops a claimed eviction plan, ``GangManager.restore`` ends every
restart in a reservation or ``rollback_all``. Those invariants are
path properties over exception edges — exactly what the example-based
chaos tests probe but cannot prove. This pass checks them per function
against the registry below, on the CFG engine (``analysis/cfg.py``).

Per registered function:

  * **acquire** — a call (matched by name) or a store to a declared
    attribute that takes ownership of the resource;
  * **settle** — a call or store that commits, rolls back, or hands it
    off;
  * ``on_return`` / ``on_raise`` — whether reaching the normal-return
    exit (resp. the exception exit) WITHOUT settling is a leak. A
    normal return is often the hand-off itself (``bind`` returns the
    committed alloc; ``ensure_reservation`` returns the stored
    reservation), so it is opt-in per function; exception exits are
    the classic leak edge and default to checked.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from tpukube.analysis import callgraph, cfg
from tpukube.analysis.base import Finding, SourceFile


@dataclass(frozen=True)
class LeakSpec:
    """One function's acquire/settle contract."""

    acquires: frozenset[str] = frozenset()
    acquire_stores: frozenset[str] = frozenset()
    settles: frozenset[str] = frozenset()
    settle_stores: frozenset[str] = frozenset()
    on_return: bool = False
    on_raise: bool = True
    why: str = ""


#: (path suffix, class, function) -> LeakSpec. These are the functions
#: whose hand-rolled settle-on-all-exits discipline the chaos suite's
#: zero-leak assertions depend on; add an entry when a new acquire
#: path appears.
LEAK_REGISTRY: dict[tuple[str, str, str], LeakSpec] = {
    ("sched/extender.py", "Extender", "bind"): LeakSpec(
        acquires=frozenset({"commit"}),
        settles=frozenset({"release"}),
        on_return=False, on_raise=True,
        why="an exception escaping after state.commit leaks the pod's "
            "chips until restart — release on every error path "
            "(the normal return hands the committed alloc off)",
    ),
    ("sched/extender.py", "Extender", "_execute_pending_preemption"): LeakSpec(
        acquires=frozenset({"take_pending_victims"}),
        settles=frozenset({"_apply_victims"}),
        on_return=True, on_raise=True,
        why="take_pending_victims atomically CLAIMS the eviction plan; "
            "a path that drops it leaves the reservation pending "
            "forever with victims that will never be evicted",
    ),
    ("sched/extender.py", "Extender", "_try_preemption"): LeakSpec(
        acquires=frozenset({"find_preemption_plan",
                            "_plan_split_preemption"}),
        settles=frozenset({"reserve_exact", "reserve_exact_split"}),
        on_return=True, on_raise=False,
        why="a preemption plan must be handed to reserve_exact[_split] "
            "so its victims ride the reservation (raising discards it "
            "safely — nothing was executed)",
    ),
    ("sched/gang.py", "GangManager", "restore"): LeakSpec(
        acquires=frozenset({"slice_of_node"}),
        settles=frozenset({"rollback_all"}),
        settle_stores=frozenset({"_reservations"}),
        on_return=True, on_raise=True,
        why="a restart restore must end in a stored reservation or "
            "rollback_all — anything else strands running gang members "
            "as individually evictable strays (partial gang death)",
    ),
    ("sched/gang.py", "GangManager", "ensure_reservation"): LeakSpec(
        acquire_stores=frozenset({"_reservations"}),
        on_return=False, on_raise=True,
        why="an exception after the reservation is stored masks its "
            "chips until TTL while the caller never learns it exists",
    ),
    ("sched/gang.py", "GangManager", "reserve_exact_split"): LeakSpec(
        acquire_stores=frozenset({"_reservations"}),
        on_return=False, on_raise=True,
        why="an exception after the preemption reservation is stored "
            "masks its chips until TTL while the caller never learns "
            "it exists",
    ),
}


def _call_names(stmt: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in cfg.shallow_walk(stmt):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute):
                out.add(n.func.attr)
            elif isinstance(n.func, ast.Name):
                out.add(n.func.id)
    return out


def _store_attrs(stmt: ast.AST, attrs: frozenset[str]) -> set[str]:
    from tpukube.analysis.epochs import flatten_targets

    out: set[str] = set()
    if not attrs:
        return out
    for n in cfg.shallow_walk(stmt):
        targets: list[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        # tuple/list unpacking stores the attr exactly like the plain
        # form — flatten so it cannot evade the acquire/settle match
        for t in flatten_targets(targets):
            if isinstance(t, ast.Subscript):
                t = t.value
            a = cfg._self_attr(t)
            if a in attrs:
                out.add(a)
    return out


def _acquire_desc(stmt: ast.AST, spec: LeakSpec) -> Optional[str]:
    calls = _call_names(stmt) & spec.acquires
    if calls:
        return f"{sorted(calls)[0]}()"
    stores = _store_attrs(stmt, spec.acquire_stores)
    if stores:
        return f"store to self.{sorted(stores)[0]}"
    return None


def check_leaks(sf: SourceFile,
                registry: Optional[dict] = None) -> list[Finding]:
    table = registry if registry is not None else LEAK_REGISTRY
    specs: dict[tuple[str, str], LeakSpec] = {
        (cls, func): spec for (sfx, cls, func), spec in table.items()
        if sf.in_scope((sfx,))
    }
    if not specs:
        return []
    findings: list[Finding] = []
    emitted: set[tuple[int, str]] = set()

    def emit(line: int, message: str) -> None:
        if (line, message) not in emitted:
            emitted.add((line, message))
            findings.append(Finding("reservation-leak", sf.rel, line,
                                    message))

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        cg = callgraph.ClassGraph(cls_node)
        for fn in cls_node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            spec = specs.get((cls_node.name, fn.name))
            if spec is None:
                continue
            g = cfg.build_cfg(fn)

            def _settle_stmt(stmt: ast.AST) -> bool:
                if _call_names(stmt) & spec.settles:
                    return True
                return bool(_store_attrs(stmt, spec.settle_stores))

            # one-level delegation: a call to an intra-class helper
            # whose direct statements settle on every exit settles
            # for the caller (a two-level chain does not)
            lifted = callgraph.delegating_satisfier(
                cg, _settle_stmt, exclude=(fn.name,))

            def settles(node: cfg.Node) -> bool:
                return node.stmt is not None and lifted(node.stmt)

            for node in g.nodes:
                if node.stmt is None:
                    continue
                desc = _acquire_desc(node.stmt, spec)
                if desc is None:
                    continue
                rets, rzs = cfg.escapes_function(g, node, settles)
                want = sorted(
                    spec.settles | {f"self.{a}[...] = ..."
                                    for a in spec.settle_stores}
                ) or ["(none declared — no exit may skip the hand-off)"]
                if spec.on_return and rets:
                    emit(node.line, (
                        f"path from {desc} in {cls_node.name}.{fn.name} "
                        f"reaches a normal return (near line "
                        f"{rets[0].line}) without settling via "
                        f"{', '.join(want)} — {spec.why}"))
                if spec.on_raise and rzs:
                    emit(node.line, (
                        f"exception path from {desc} in "
                        f"{cls_node.name}.{fn.name} escapes the function "
                        f"(near line {rzs[0].line}) without settling via "
                        f"{', '.join(want)} — {spec.why}"))
    return findings

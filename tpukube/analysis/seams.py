"""seam-triple: every epoch bump in the ledger/gang classes must pair
with a delta note AND a journal note on every CFG path before the
lock region exits.

PR 6 proved the epoch half (mutation -> bump); this pass proves the
other two thirds of the seam the tree actually writes today:

  * ``self._note_delta_locked(...)`` — the snapshot delta chain is
    CONTIGUOUS (``sched/snapshot.py`` returns None on the first gap),
    so an epoch increment without a delta note silently degrades every
    later cache hit into an O(chips) full rebuild: a performance bug
    no functional test fails on;
  * ``self._note_journal_locked(...)`` — a bump whose mutation never
    reaches the WAL is a recovery-divergence bug: the live process
    and its restarted twin disagree about state the epoch said
    changed.

Per bump (``self._epoch += 1``) in a registered class: find the
outermost ``with self.<lock>`` region (or, in a ``*_locked`` helper,
treat the whole body as the region) and require that every path from
the bump passes a delta-note call and a journal-note call before the
region exits. Replay/restore functions are journal-EXEMPT by
registry: they apply WAL records with the journal deliberately
detached, so noting would double-record — their bumps still owe
delta notes (the cache contract holds during replay too).

Raise-path escapes of the JOURNAL half are reported separately and
anchored at the raising statement: "mutated, bumped, then raised
before journaling" is occasionally a deliberate design decision
(a slice registered by an upsert that then fails validation), and the
waiver then sits on the raise, not on the bump — deleting the
normal-path journal note still fails the build.

The registry also names the journal KINDS each file must note at
least once (``REQUIRED_KINDS``): the replayer in ``sched/journal.py``
dispatches on these strings, so a kind it handles that nothing notes
any more is dead recovery code hiding a deleted seam — this is what
catches deleting a journal-only note (``gvtaken``, ``guncommit``)
that no bump sits next to.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from tpukube.analysis import cfg
from tpukube.analysis.base import Finding, SourceFile


@dataclass(frozen=True)
class TripleSpec:
    """One class's bump/delta/journal pairing contract."""

    lock_attr: str
    delta_call: str = "_note_delta_locked"
    journal_call: str = "_note_journal_locked"
    bump_attr: str = "_epoch"
    #: functions whose bumps owe no journal note (replay/restore:
    #: the journal is detached while they run)
    journal_exempt: frozenset = field(default_factory=frozenset)


#: (path suffix, class) -> TripleSpec
TRIPLE_REGISTRY: dict[tuple[str, str], TripleSpec] = {
    ("sched/state.py", "ClusterState"): TripleSpec(
        lock_attr="_lock",
        journal_exempt=frozenset({"restore_checkpoint"}),
    ),
    ("sched/gang.py", "GangManager"): TripleSpec(
        lock_attr="_lock",
        journal_exempt=frozenset({
            "restore_checkpoint", "apply_journal", "finish_replay",
            "_res_from_doc_locked",
        }),
    ),
}

#: path suffix -> journal kinds the file must note at least once —
#: the exact strings ``sched/journal.py``'s replayer dispatches on.
#: A kind handled there but noted nowhere is a deleted seam (or dead
#: recovery code); growing a new WAL kind means adding it here AND to
#: the replayer.
REQUIRED_KINDS: dict[str, frozenset] = {
    "sched/state.py": frozenset({"node", "nodes", "commit", "release",
                                 "cordon", "unnodes"}),
    "sched/gang.py": frozenset({
        "evict", "gre", "gdrop", "gterm", "gvgone", "gbound",
        "gmrel", "greas", "gvtaken", "guncommit",
    }),
}


def _is_bump(stmt: ast.AST, spec: TripleSpec) -> bool:
    for n in cfg.shallow_walk(stmt):
        if (isinstance(n, ast.AugAssign)
                and isinstance(n.op, ast.Add)
                and cfg._self_attr(n.target) == spec.bump_attr):
            return True
    return False


def _calls_method(stmt: ast.AST, method: str) -> bool:
    for n in cfg.shallow_walk(stmt):
        if (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and cfg._self_attr(n.func) is not None
                and n.func.attr == method):
            return True
    return False


def _next_bump(g: "cfg.FunctionCFG", start: cfg.Node, sat, bump_ids):
    """The first OTHER bump reachable from ``start`` without passing a
    satisfying (delta-note) statement — the delta chain records one
    delta PER epoch (``SnapshotDelta.epoch = self._epoch``), so two
    bumps with no note between them gap the chain at the first bump's
    epoch even when a later note covers the region exit."""
    seen: set[int] = set()
    stack = list(start.succ)
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if id(n) in bump_ids:
            return n
        if sat(n):
            continue
        stack.extend(n.succ)
    return None


def _noted_kinds(tree: ast.Module, spec: TripleSpec) -> set[str]:
    """String literals passed as the first argument of journal-note
    calls anywhere in the module."""
    out: set[str] = set()
    for n in ast.walk(tree):
        if (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == spec.journal_call
                and n.args
                and isinstance(n.args[0], ast.Constant)
                and isinstance(n.args[0].value, str)):
            out.add(n.args[0].value)
    return out


def check_seam_triples(sf: SourceFile,
                       registry: Optional[dict] = None) -> list[Finding]:
    table = registry if registry is not None else TRIPLE_REGISTRY
    specs = {cls: spec for (sfx, cls), spec in table.items()
             if sf.in_scope((sfx,))}
    if not specs:
        return []
    findings: list[Finding] = []
    emitted: set[tuple[int, str]] = set()

    def emit(line: int, message: str) -> None:
        if (line, message) not in emitted:
            emitted.add((line, message))
            findings.append(Finding("seam-triple", sf.rel, line, message))

    for cls_node in sf.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        spec = specs.get(cls_node.name)
        if spec is None:
            continue
        for fn in cls_node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue
            g = cfg.build_cfg(fn, lock_attrs={spec.lock_attr})
            bumps = [n for n in g.nodes
                     if n.stmt is not None and _is_bump(n.stmt, spec)]
            if not bumps:
                continue

            halves = [(spec.delta_call,
                       "breaks the contiguous snapshot delta chain — "
                       "every later cache hit degrades to an O(chips) "
                       "full rebuild")]
            if fn.name not in spec.journal_exempt:
                halves.append((spec.journal_call,
                               "is a recovery-divergence bug — a "
                               "restart replays a WAL that never saw "
                               "this mutation"))

            bump_ids = {id(n) for n in bumps}
            for node in bumps:
                rid = g.outermost_region(node, spec.lock_attr)

                def delta_sat(v: cfg.Node) -> bool:
                    return (v.stmt is not None
                            and _calls_method(v.stmt, spec.delta_call))

                nb = _next_bump(g, node, delta_sat, bump_ids)
                if nb is not None:
                    emit(node.line, (
                        f"`self.{spec.bump_attr} += 1` in "
                        f"{cls_node.name}.{fn.name} reaches the next "
                        f"bump (line {nb.line}) without "
                        f"`self.{spec.delta_call}(...)` in between — "
                        f"the delta chain records one delta PER epoch, "
                        f"so this bump's epoch gaps the chain and every "
                        f"later advance falls back to the O(chips) "
                        f"rebuild"))
                for call, why in halves:
                    def sat(v: cfg.Node, _c=call) -> bool:
                        return (v.stmt is not None
                                and _calls_method(v.stmt, _c))

                    if rid is None:
                        if not fn.name.endswith("_locked"):
                            # epoch-discipline already flags the
                            # bump-outside-lock shape; nothing sound
                            # to prove here
                            continue
                        rets, rzs = cfg.escapes_function(g, node, sat)
                        if rets:
                            emit(node.line, (
                                f"`self.{spec.bump_attr} += 1` in "
                                f"{cls_node.name}.{fn.name} reaches "
                                f"function exit without "
                                f"`self.{call}(...)` (near line "
                                f"{rets[0].line}) — a missed note "
                                f"{why}"))
                        for w in rzs:
                            emit(w.line if w.line is not None
                                 else node.line, (
                                f"exception path after "
                                f"`self.{spec.bump_attr} += 1` (line "
                                f"{node.line}) in "
                                f"{cls_node.name}.{fn.name} escapes "
                                f"without `self.{call}(...)` — a "
                                f"missed note {why}"))
                            break
                        continue
                    escapes = cfg.escapes_region(g, node, rid, sat)
                    normal = [(u, v) for u, v in escapes
                              if v.kind != "raise_exit"]
                    raising = [(u, v) for u, v in escapes
                               if v.kind == "raise_exit"]
                    if normal:
                        emit(node.line, (
                            f"`self.{spec.bump_attr} += 1` in "
                            f"{cls_node.name}.{fn.name} is not "
                            f"followed by `self.{call}(...)` on every "
                            f"path before the `with "
                            f"self.{spec.lock_attr}` region (line "
                            f"{g.regions[rid].line}) exits (escape "
                            f"near line {normal[0][0].line}) — a "
                            f"missed note {why}"))
                    seen_w: set[int] = set()
                    for u, _ in raising:
                        wl = u.line if u.line is not None else node.line
                        if wl in seen_w:
                            continue
                        seen_w.add(wl)
                        emit(wl, (
                            f"exception path after "
                            f"`self.{spec.bump_attr} += 1` (line "
                            f"{node.line}) in "
                            f"{cls_node.name}.{fn.name} leaves the "
                            f"`with self.{spec.lock_attr}` region "
                            f"without `self.{call}(...)` — a missed "
                            f"note {why}"))

        # journal-kind coverage: unique journal-only notes (no bump
        # beside them) are killed here when deleted
        required = None
        for sfx, kinds in REQUIRED_KINDS.items():
            if sf.in_scope((sfx,)):
                required = kinds
                break
        if required is not None:
            noted = _noted_kinds(sf.tree, spec)
            if not noted:
                # a module with ZERO journal notes does not participate
                # in the WAL seam (fixture skeletons, forks) — kind
                # coverage is a backstop against single-site deletions,
                # and any real deletion leaves the other notes behind
                continue
            for kind in sorted(required - noted):
                emit(cls_node.lineno, (
                    f"journal kind \"{kind}\" is handled by the "
                    f"replayer (sched/journal.py) but no "
                    f"`{spec.journal_call}(\"{kind}\", ...)` remains "
                    f"in {sf.rel} — a deleted WAL seam leaves "
                    f"recovery replaying records that are never "
                    f"written (analysis/seams.py REQUIRED_KINDS)"))
    return findings

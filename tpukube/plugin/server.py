"""Device-plugin node agent (L3).

SURVEY.md §2 C4/C5 and §4.1/§4.3/§4.4: the reference's Go daemon registers
with the kubelet over its unix socket, serves the five deviceplugin/v1beta1
RPCs, and runs a health loop (NVML XID events) that pushes shrunken device
lists on the ListAndWatch stream. This is the TPU rendering: libtpuinfo
health polls replace the blocking NVML event wait (libtpu has no event fd;
the poll interval is config), and Allocate returns TPU env instead of
/dev/nvidia* device nodes.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from concurrent import futures
from typing import Optional

import grpc

from tpukube.core.config import TpuKubeConfig
from tpukube.core.types import Health
from tpukube.device import DeviceError, TpuDeviceManager
from tpukube.plugin import stubs
from tpukube.plugin.proto import deviceplugin_pb2 as pb

log = logging.getLogger("tpukube.plugin")


class DevicePluginServer(stubs.DevicePluginServicer):
    """Serves one extended resource on one unix socket.

    A node runs exactly one instance: the device manager's sharing mode
    decides whether it advertises whole chips or vTPU shares (see
    tpukube/device/tpu.py module doc).
    """

    def __init__(self, config: TpuKubeConfig, device: TpuDeviceManager,
                 socket_path: Optional[str] = None):
        self._config = config
        self._device = device
        self._socket_path = socket_path or config.plugin_socket_path()
        self._server: Optional[grpc.Server] = None
        # Each active ListAndWatch stream gets its own update queue; the
        # health watcher broadcasts a refreshed device list to all of them.
        self._watch_queues: list[queue.SimpleQueue] = []
        self._watch_lock = threading.Lock()
        self._allocations = 0  # served Allocate calls (metrics)

    # -- lifecycle ---------------------------------------------------------
    @property
    def socket_path(self) -> str:
        return self._socket_path

    @property
    def config(self) -> TpuKubeConfig:
        return self._config

    @property
    def resource_name(self) -> str:
        return self._device.resource_name

    @property
    def allocation_count(self) -> int:
        return self._allocations

    def start(self) -> None:
        if self._server is not None:
            raise RuntimeError("plugin server already started")
        if os.path.exists(self._socket_path):
            os.unlink(self._socket_path)  # stale socket from a crashed agent
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        stubs.add_device_plugin_to_server(self, self._server)
        self._server.add_insecure_port(f"unix://{self._socket_path}")
        self._server.start()
        log.info("plugin serving %s on %s", self.resource_name, self._socket_path)

    def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None
        if os.path.exists(self._socket_path):
            os.unlink(self._socket_path)

    def restart(self, grace: float = 0.5) -> None:
        """Rebind the unix socket (kubelet wipes the device-plugin dir on
        restart, taking our socket file with it — a gRPC server holding a
        deleted socket's fd serves nobody kubelet can reach)."""
        self.stop(grace)
        self.start()

    def __enter__(self) -> "DevicePluginServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def register_with_kubelet(self, kubelet_socket: Optional[str] = None,
                              timeout: float = 5.0) -> None:
        """Dial the kubelet's Registration service and announce ourselves
        (SURVEY.md §4.1)."""
        ks = kubelet_socket or self._config.kubelet_socket_path()
        with grpc.insecure_channel(f"unix://{ks}") as channel:
            grpc.channel_ready_future(channel).result(timeout=timeout)
            stub = stubs.RegistrationStub(channel)
            stub.Register(
                pb.RegisterRequest(
                    version=stubs.API_VERSION,
                    endpoint=os.path.basename(self._socket_path),
                    resource_name=self.resource_name,
                    options=pb.DevicePluginOptions(
                        pre_start_required=False,
                        get_preferred_allocation_available=True,
                    ),
                ),
                timeout=timeout,
            )
        log.info("registered %s with kubelet at %s", self.resource_name, ks)

    # -- device list plumbing ---------------------------------------------
    def _current_devices(self) -> pb.ListAndWatchResponse:
        return pb.ListAndWatchResponse(
            devices=[
                pb.Device(ID=did, health=h.value)
                for did, h in self._device.device_list()
            ]
        )

    def push_update(self) -> None:
        """Broadcast the current device list to all ListAndWatch streams
        (called by the health watcher on any health transition)."""
        resp = self._current_devices()
        with self._watch_lock:
            for q in self._watch_queues:
                q.put(resp)

    # -- deviceplugin/v1beta1 RPCs -----------------------------------------
    def GetDevicePluginOptions(self, request, context) -> pb.DevicePluginOptions:
        return pb.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=True,
        )

    def ListAndWatch(self, request, context):
        """Initial full list, then a push per health transition — the
        long-lived stream the kubelet sizes node allocatable from."""
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._watch_lock:
            self._watch_queues.append(q)
        try:
            yield self._current_devices()
            while context.is_active():
                try:
                    yield q.get(timeout=0.2)
                except queue.Empty:
                    continue
        finally:
            with self._watch_lock:
                self._watch_queues.remove(q)

    def GetPreferredAllocation(self, request, context) -> pb.PreferredAllocationResponse:
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            try:
                chosen = self._device.preferred_allocation(
                    list(creq.available_deviceIDs),
                    list(creq.must_include_deviceIDs),
                    creq.allocation_size,
                )
            except DeviceError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            resp.container_responses.append(
                pb.ContainerPreferredAllocationResponse(deviceIDs=chosen)
            )
        return resp

    def Allocate(self, request, context) -> pb.AllocateResponse:
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            try:
                env = self._device.allocate_env(list(creq.devicesIDs))
            except DeviceError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            resp.container_responses.append(pb.ContainerAllocateResponse(envs=env))
        self._allocations += 1
        log.info("allocated %s", [list(c.devicesIDs) for c in request.container_requests])
        return resp

    def PreStartContainer(self, request, context) -> pb.PreStartContainerResponse:
        return pb.PreStartContainerResponse()


class HealthWatcher:
    """Polls device health and pushes ListAndWatch updates on transitions.

    The reference blocks in nvmlEventSetWait for XID events (SURVEY.md
    §4.4); libtpu exposes no event fd, so this polls libtpuinfo at a config
    interval — same contract (kubelet sees Unhealthy within one interval),
    different mechanism.
    """

    def __init__(self, device: TpuDeviceManager, server: DevicePluginServer,
                 poll_seconds: Optional[float] = None):
        self._device = device
        self._server = server
        if poll_seconds is None:
            poll_seconds = server.config.health_poll_seconds
        self._poll = poll_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last: dict[str, Health] = {}
        self.transitions = 0  # observed health flips (tests/metrics)

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("health watcher already started")
        self._last = self._device.health_snapshot()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpukube-health")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def check_once(self) -> bool:
        """One poll; returns True if a transition was pushed. Exposed so
        tests (and the sim harness) can step deterministically."""
        snap = self._device.health_snapshot()
        if snap != self._last:
            changed = {k for k in snap if snap[k] != self._last.get(k)}
            log.warning("health transition: %s", sorted(changed))
            self._last = snap
            self.transitions += 1
            self._server.push_update()
            return True
        return False

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_once()
            except Exception:
                log.exception("health poll failed")


class KubeletSessionWatcher:
    """Re-registers with a restarted kubelet (SURVEY.md §4.1 liveness).

    Kubelet clears its device-plugin directory on restart and expects every
    plugin to dial the fresh ``kubelet.sock`` and Register again — a plugin
    that does not is silently absent from the node's allocatable until its
    own next restart. The reference watches this with fsnotify; here we
    poll two facts at the health-watch cadence:

      * the kubelet socket's identity (st_ino/st_dev) — a change means a
        new kubelet is up: re-register;
      * our OWN socket file's existence — kubelet's restart wipe unlinks
        it, and a gRPC server holding a deleted socket's fd is
        unreachable: rebind, then re-register.
    """

    def __init__(self, server: DevicePluginServer,
                 poll_seconds: Optional[float] = None):
        self._server = server
        if poll_seconds is None:
            poll_seconds = server.config.health_poll_seconds
        self._poll = poll_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._kubelet_ident = self._ident()
        self.reregistrations = 0  # metrics/tests

    def _ident(self) -> Optional[tuple[int, int, int]]:
        try:
            st = os.stat(self._server.config.kubelet_socket_path())
            # st_ctime_ns guards against inode reuse: a deleted + recreated
            # socket can get the old inode back (tmpfs does this readily)
            return (st.st_ino, st.st_dev, st.st_ctime_ns)
        except OSError:
            return None

    def mark_unregistered(self) -> None:
        """Forget the observed kubelet identity so the next poll registers
        (the daemon calls this when its INITIAL registration fails — e.g. a
        DaemonSet pod that boots before kubelet — turning a would-be crash
        loop into convergence at the poll cadence)."""
        self._kubelet_ident = None

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("kubelet watcher already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpukube-kubelet-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def check_once(self) -> bool:
        """One poll; True if a re-registration happened. Exposed so tests
        step deterministically (same pattern as HealthWatcher)."""
        ident = self._ident()
        if ident is None:
            # kubelet down: nothing to register with; record None so its
            # return reads as a restart
            self._kubelet_ident = None
            return False
        kubelet_restarted = ident != self._kubelet_ident
        socket_gone = not os.path.exists(self._server.socket_path)
        if not (kubelet_restarted or socket_gone):
            return False
        if socket_gone:
            log.warning("plugin socket vanished (kubelet restart wipe); rebinding")
            self._server.restart()
        if kubelet_restarted:
            log.warning("kubelet socket identity changed; re-registering")
        self._server.register_with_kubelet()
        # commit the observed identity only AFTER registration succeeded —
        # a failed Register (new kubelet not serving yet) must leave the
        # restart event pending so the next poll retries
        self._kubelet_ident = ident
        self.reregistrations += 1
        return True

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_once()
            except Exception:
                log.exception("kubelet session poll failed")

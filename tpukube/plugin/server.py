"""Device-plugin node agent (L3).

SURVEY.md §2 C4/C5 and §4.1/§4.3/§4.4: the reference's Go daemon registers
with the kubelet over its unix socket, serves the five deviceplugin/v1beta1
RPCs, and runs a health loop (NVML XID events) that pushes shrunken device
lists on the ListAndWatch stream. This is the TPU rendering: libtpuinfo
health polls replace the blocking NVML event wait (libtpu has no event fd;
the poll interval is config), and Allocate returns TPU env instead of
/dev/nvidia* device nodes.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from concurrent import futures
from typing import Optional

import grpc

from tpukube.core.config import TpuKubeConfig
from tpukube.core.types import Health
from tpukube.device import DeviceError, TpuDeviceManager
from tpukube.plugin import stubs
from tpukube.plugin.proto import deviceplugin_pb2 as pb

log = logging.getLogger("tpukube.plugin")


class AllocIntentCache:
    """Planned device-id sets for pods bound to this node, fed from their
    ``tpu.qiniu.com/alloc`` annotations (apiserver.AllocIntentWatcher).

    The kubelet — not the extender — decides which advertised ids go into
    Allocate; these intents are how the extender's plan reaches that
    decision: GetPreferredAllocation answers with the matching planned set,
    and Allocate checks the kubelet's actual choice against it, reporting
    divergence for ledger reconciliation.

    Attribution limits: deviceplugin/v1beta1 carries no pod identity, so
    matching an Allocate to a pod is inference. A consumed intent is marked
    satisfied and never re-enters from the watcher's polls while its pod
    lives (a running pod's lifetime alloc annotation must not masquerade
    as a fresh plan). A divergent Allocate is attributed ONLY when exactly
    one unsatisfied same-size intent exists — ambiguity means no report,
    never a guess (the extender additionally refuses reconcile reports
    naming chips the ledger shows held by another pod, so a wrong guess
    after an agent restart cannot corrupt the ledger).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._intents: dict[str, list[str]] = {}  # pod_key -> planned ids
        self._satisfied: set[str] = set()  # pod_keys whose Allocate happened

    def sync(self, intents: dict[str, list[str]]) -> bool:
        """Replace the set from a watcher poll (satisfied pods excluded;
        vanished pods forgotten entirely). True if the live set changed."""
        with self._lock:
            fresh = {
                k: list(v) for k, v in intents.items()
                if k not in self._satisfied
            }
            self._satisfied &= set(intents)
            if fresh == self._intents:
                return False
            self._intents = fresh
            return True

    def put(self, pod_key: str, device_ids: list[str]) -> None:
        with self._lock:
            self._intents[pod_key] = list(device_ids)
            self._satisfied.discard(pod_key)

    def offer(self, pod_key: str, device_ids: list[str]) -> bool:
        """put() for watch-event paths: refuses to resurrect an intent the
        kubelet already consumed — a running pod's lifetime alloc
        annotation rides every subsequent MODIFIED event (and reconnect
        replay), and re-inserting it would let a stale plan masquerade as
        fresh for some later pod's Allocate."""
        with self._lock:
            if pod_key in self._satisfied:
                return False
            self._intents[pod_key] = list(device_ids)
            return True

    def remove(self, pod_key: str) -> None:
        with self._lock:
            self._intents.pop(pod_key, None)
            self._satisfied.discard(pod_key)

    def snapshot(self) -> dict[str, list[str]]:
        with self._lock:
            return {k: list(v) for k, v in self._intents.items()}

    def depth(self) -> int:
        """Pending (unconsumed) intents — exported on /metrics."""
        with self._lock:
            return len(self._intents)

    def preferred(
        self, available: list[str], required: list[str], size: int
    ) -> Optional[list[str]]:
        """The planned id set satisfying this preference query, if any:
        right size, inside the kubelet's available pool, containing every
        must-include id. Not consumed — the kubelet may ask repeatedly.

        The query carries no pod identity, so when MORE than one pending
        intent fits, any answer is a coin flip that can steer this pod
        onto the OTHER pod's plan — manufacturing divergences. Mirror
        consume()'s refuse-to-guess: return None and let the device
        manager's local heuristic decide."""
        avail = set(available)
        req = set(required)
        with self._lock:
            fits = [
                ids for ids in self._intents.values()
                if len(ids) == size and req <= set(ids) and set(ids) <= avail
            ]
        if len(fits) == 1:
            return list(fits[0])
        if fits:
            log.info(
                "preference query (size %d) matches %d pending intents; "
                "deferring to the local heuristic", size, len(fits),
            )
        return None

    def consume(
        self, allocated: list[str]
    ) -> tuple[Optional[str], Optional[list[str]], bool]:
        """Match an Allocate against the intents: exact id-set match wins
        (consumed, no divergence); otherwise a same-size intent is the
        diverged plan ONLY if it is unambiguous (see class docstring).
        Returns (pod_key, planned, diverged); (None, None, False) when no
        intent can safely be attributed."""
        got = set(allocated)
        with self._lock:
            for key, ids in self._intents.items():
                if set(ids) == got:
                    del self._intents[key]
                    self._satisfied.add(key)
                    return key, ids, False
            same = [
                (k, v) for k, v in self._intents.items()
                if len(v) == len(allocated)
            ]
            if len(same) == 1:
                key, ids = same[0]
                del self._intents[key]
                self._satisfied.add(key)
                return key, ids, True
            if same:
                log.warning(
                    "divergent Allocate %s matches %d same-size intents; "
                    "refusing to guess attribution",
                    sorted(allocated), len(same),
                )
        return None, None, False


class DevicePluginServer(stubs.DevicePluginServicer):
    """Serves one extended resource on one unix socket.

    A node runs exactly one instance: the device manager's sharing mode
    decides whether it advertises whole chips or vTPU shares (see
    tpukube/device/tpu.py module doc).
    """

    def __init__(self, config: TpuKubeConfig, device: TpuDeviceManager,
                 socket_path: Optional[str] = None):
        self._config = config
        self._device = device
        self._socket_path = socket_path or config.plugin_socket_path()
        self._server: Optional[grpc.Server] = None
        # Each active ListAndWatch stream gets its own update queue; the
        # health watcher broadcasts a refreshed device list to all of them.
        self._watch_queues: list[queue.SimpleQueue] = []
        self._watch_lock = threading.Lock()
        self._allocations = 0  # served Allocate calls (metrics)
        self.divergences = 0   # kubelet-vs-plan id divergences (metrics)
        # extender-planned device ids for pods bound here (see
        # AllocIntentCache); fed by apiserver.AllocIntentWatcher
        self.intents = AllocIntentCache()
        self._alloc_reporter = None  # divergence callback (apiserver chan)
        # observability span hook: called as span_sink(name, pod_key,
        # **fields) on Allocate / intent-match, when an Allocate can be
        # attributed to a pod. Wire a DecisionTrace.span here (the sim
        # harness does) and the per-pod timeline gains the node-agent leg
        # of the chain: filter -> gang_reserve -> bind -> allocate.
        self.span_sink = None
        # structured event journal (obs/events.py), wired by the daemon
        # main; the same seams as the span hooks emit typed events here
        self.events = None

    def _emit_event(self, reason: str, obj: str, message: str,
                    warning: bool = True) -> None:
        if self.events is None:
            return
        try:
            self.events.emit(
                reason, obj=obj, message=message,
                type="Warning" if warning else "Normal",
                node=self._device.host,
            )
        except Exception:
            log.exception("event emit failed: %s %s", reason, obj)

    def _span(self, name: str, pod_key: str, **fields) -> None:
        if self.span_sink is None:
            return
        try:
            self.span_sink(name, pod_key, **fields)
        except Exception:
            # observability must never fail an Allocate
            log.exception("span sink failed for %s/%s", name, pod_key)

    def set_alloc_reporter(self, reporter) -> None:
        """Install the divergence report channel: called as
        ``reporter(pod_key, planned_ids, actual_ids)`` when the kubelet
        allocates ids other than the planned intent
        (apiserver.alloc_divergence_reporter builds one)."""
        self._alloc_reporter = reporter

    # -- lifecycle ---------------------------------------------------------
    @property
    def socket_path(self) -> str:
        return self._socket_path

    @property
    def config(self) -> TpuKubeConfig:
        return self._config

    @property
    def resource_name(self) -> str:
        return self._device.resource_name

    @property
    def allocation_count(self) -> int:
        return self._allocations

    def start(self) -> None:
        if self._server is not None:
            raise RuntimeError("plugin server already started")
        if os.path.exists(self._socket_path):
            os.unlink(self._socket_path)  # stale socket from a crashed agent
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        stubs.add_device_plugin_to_server(self, self._server)
        self._server.add_insecure_port(f"unix://{self._socket_path}")
        self._server.start()
        log.info("plugin serving %s on %s", self.resource_name, self._socket_path)

    def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None
        if os.path.exists(self._socket_path):
            os.unlink(self._socket_path)

    def restart(self, grace: float = 0.5) -> None:
        """Rebind the unix socket (kubelet wipes the device-plugin dir on
        restart, taking our socket file with it — a gRPC server holding a
        deleted socket's fd serves nobody kubelet can reach)."""
        self.stop(grace)
        self.start()

    def __enter__(self) -> "DevicePluginServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def register_with_kubelet(self, kubelet_socket: Optional[str] = None,
                              timeout: float = 5.0) -> None:
        """Dial the kubelet's Registration service and announce ourselves
        (SURVEY.md §4.1)."""
        ks = kubelet_socket or self._config.kubelet_socket_path()
        with grpc.insecure_channel(f"unix://{ks}") as channel:
            grpc.channel_ready_future(channel).result(timeout=timeout)
            stub = stubs.RegistrationStub(channel)
            stub.Register(
                pb.RegisterRequest(
                    version=stubs.API_VERSION,
                    endpoint=os.path.basename(self._socket_path),
                    resource_name=self.resource_name,
                    options=pb.DevicePluginOptions(
                        pre_start_required=False,
                        get_preferred_allocation_available=True,
                    ),
                ),
                timeout=timeout,
            )
        log.info("registered %s with kubelet at %s", self.resource_name, ks)

    # -- device list plumbing ---------------------------------------------
    def _current_devices(self) -> pb.ListAndWatchResponse:
        return pb.ListAndWatchResponse(
            devices=[
                pb.Device(ID=did, health=h.value)
                for did, h in self._device.device_list()
            ]
        )

    def push_update(self) -> None:
        """Broadcast the current device list to all ListAndWatch streams
        (called by the health watcher on any health transition)."""
        resp = self._current_devices()
        with self._watch_lock:
            for q in self._watch_queues:
                q.put(resp)

    # -- deviceplugin/v1beta1 RPCs -----------------------------------------
    def GetDevicePluginOptions(self, request, context) -> pb.DevicePluginOptions:
        return pb.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=True,
        )

    def ListAndWatch(self, request, context):
        """Initial full list, then a push per health transition — the
        long-lived stream the kubelet sizes node allocatable from."""
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._watch_lock:
            self._watch_queues.append(q)
        try:
            yield self._current_devices()
            while context.is_active():
                try:
                    yield q.get(timeout=0.2)
                except queue.Empty:
                    continue
        finally:
            with self._watch_lock:
                self._watch_queues.remove(q)

    def GetPreferredAllocation(self, request, context) -> pb.PreferredAllocationResponse:
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            available = list(creq.available_deviceIDs)
            required = list(creq.must_include_deviceIDs)
            size = creq.allocation_size
            # the extender's planned ids outrank local adjacency: the gang
            # contiguity score was computed for exactly those chips
            chosen = self.intents.preferred(available, required, size)
            if chosen is None:
                try:
                    chosen = self._device.preferred_allocation(
                        available, required, size,
                    )
                except DeviceError as e:
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            resp.container_responses.append(
                pb.ContainerPreferredAllocationResponse(deviceIDs=chosen)
            )
        return resp

    def Allocate(self, request, context) -> pb.AllocateResponse:
        resp = pb.AllocateResponse()
        for creq in request.container_requests:
            ids = list(creq.devicesIDs)
            try:
                env = self._device.allocate_env(ids)
            except DeviceError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            resp.container_responses.append(pb.ContainerAllocateResponse(envs=env))
            pod_key, planned, diverged = self.intents.consume(ids)
            if pod_key is not None:
                if not diverged:
                    # the kubelet's choice matched the extender's plan
                    # exactly — the steering loop closed as designed
                    self._span("intent_match", pod_key,
                               devices=sorted(ids))
                self._span("allocate", pod_key,
                           devices=sorted(ids), diverged=diverged)
            if diverged and planned is not None and pod_key is not None:
                self.divergences += 1
                log.warning(
                    "kubelet allocated %s but %s was planned %s — reporting",
                    sorted(ids), pod_key, sorted(planned),
                )
                self._emit_event(
                    "AllocDiverged", f"pod/{pod_key}",
                    f"kubelet allocated {sorted(ids)} but the plan was "
                    f"{sorted(planned)}; reporting for reconcile",
                )
                if self._alloc_reporter is not None:
                    # off the kubelet's pod-start critical path: the report
                    # is an apiserver PATCH that may block seconds
                    threading.Thread(
                        target=self._alloc_reporter,
                        args=(pod_key, planned, ids),
                        daemon=True, name="tpukube-alloc-report",
                    ).start()
        self._allocations += 1
        log.info("allocated %s", [list(c.devicesIDs) for c in request.container_requests])
        return resp

    def PreStartContainer(self, request, context) -> pb.PreStartContainerResponse:
        return pb.PreStartContainerResponse()


class HealthWatcher:
    """Polls device health and pushes ListAndWatch updates on transitions.

    The reference blocks in nvmlEventSetWait for XID events (SURVEY.md
    §4.4); libtpu exposes no event fd, so this polls libtpuinfo at a config
    interval — same contract (kubelet sees Unhealthy within one interval),
    different mechanism.
    """

    def __init__(self, device: TpuDeviceManager, server: DevicePluginServer,
                 poll_seconds: Optional[float] = None,
                 on_transition=None):
        self._device = device
        self._server = server
        if poll_seconds is None:
            poll_seconds = server.config.health_poll_seconds
        self._poll = poll_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last: dict[str, Health] = {}
        self._last_links: list = []
        self.transitions = 0  # observed health flips (tests/metrics)
        # called (no args) after each pushed transition: the daemon hooks
        # its annotation-file rewrite here so the SCHEDULER learns about
        # dead chips too — the ListAndWatch push only reaches the kubelet,
        # but the extender reads the node-topology annotation
        self._on_transition = on_transition

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("health watcher already started")
        self._last = self._device.health_snapshot()
        self._last_links = self._device.link_fault_snapshot()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpukube-health")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def check_once(self) -> bool:
        """One poll; returns True if a transition was pushed. Exposed so
        tests (and the sim harness) can step deterministically."""
        try:
            # real backend: run the liveness canary first so the snapshot
            # below reflects current chip health (sim: no-op — health is
            # driven by inject_fault); a probe ERROR must not kill the
            # watch loop, the last snapshot simply persists
            self._device.probe()
        except Exception:
            log.exception("health probe failed; keeping last snapshot")
        snap = self._device.health_snapshot()
        links = self._device.link_fault_snapshot()
        health_changed = snap != self._last
        links_changed = links != self._last_links
        if not (health_changed or links_changed):
            return False
        if health_changed:
            changed = {k for k in snap if snap[k] != self._last.get(k)}
            log.warning("health transition: %s", sorted(changed))
            # the kubelet cares only about device health, not ICI links
            self._server.push_update()
        if links_changed:
            log.warning("ICI link-fault transition: %d downed link(s)",
                        len(links))
        self._last = snap
        self._last_links = links
        self.transitions += 1
        if self._on_transition is not None:
            try:
                self._on_transition()
            except Exception:
                # re-annotation failure must not kill the watch loop;
                # the kubelet-side shrink already went out
                log.exception("health re-annotation hook failed")
        return True

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_once()
            except Exception:
                log.exception("health poll failed")


class KubeletSessionWatcher:
    """Re-registers with a restarted kubelet (SURVEY.md §4.1 liveness).

    Kubelet clears its device-plugin directory on restart and expects every
    plugin to dial the fresh ``kubelet.sock`` and Register again — a plugin
    that does not is silently absent from the node's allocatable until its
    own next restart. The reference watches this with fsnotify; here we
    poll two facts at the health-watch cadence:

      * the kubelet socket's identity (st_ino/st_dev) — a change means a
        new kubelet is up: re-register;
      * our OWN socket file's existence — kubelet's restart wipe unlinks
        it, and a gRPC server holding a deleted socket's fd is
        unreachable: rebind, then re-register.
    """

    def __init__(self, server: DevicePluginServer,
                 poll_seconds: Optional[float] = None,
                 retrier=None):
        self._server = server
        if poll_seconds is None:
            poll_seconds = server.config.health_poll_seconds
        self._poll = poll_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._kubelet_ident = self._ident()
        self._needs_register = False
        self.reregistrations = 0  # metrics/tests
        self.events = None  # optional EventJournal (daemon main wires it)
        # registration attempts run under the unified retry policy
        # (config retry_* knobs): jittered exponential backoff with a
        # max-attempt cap INSIDE one poll, on top of the poll-cadence
        # outer retry the _needs_register flag already provides — a
        # kubelet that is up-but-not-serving-yet converges in hundreds
        # of ms instead of a whole poll interval per attempt
        if retrier is None:
            from tpukube.core import retry

            retrier = retry.Retrier(
                retry.policy_from_config(server.config),
                name="kubelet-register",
            )
        self.retrier = retrier

    def _ident(self) -> Optional[tuple[int, int, int]]:
        try:
            st = os.stat(self._server.config.kubelet_socket_path())
            # st_ctime_ns guards against inode reuse: a deleted + recreated
            # socket can get the old inode back (tmpfs does this readily)
            return (st.st_ino, st.st_dev, st.st_ctime_ns)
        except OSError:
            return None

    def mark_unregistered(self) -> None:
        """Forget the observed kubelet identity so the next poll registers
        (the daemon calls this when its INITIAL registration fails — e.g. a
        DaemonSet pod that boots before kubelet — turning a would-be crash
        loop into convergence at the poll cadence)."""
        self._kubelet_ident = None
        self._needs_register = True

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("kubelet watcher already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpukube-kubelet-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def check_once(self) -> bool:
        """One poll; True if a re-registration happened. Exposed so tests
        step deterministically (same pattern as HealthWatcher)."""
        ident = self._ident()
        if ident is None:
            # kubelet down: nothing to register with; record None so its
            # return reads as a restart
            self._kubelet_ident = None
            return False
        kubelet_restarted = ident != self._kubelet_ident
        socket_gone = not os.path.exists(self._server.socket_path)
        if not (kubelet_restarted or socket_gone or self._needs_register):
            return False
        if socket_gone:
            log.warning("plugin socket vanished (kubelet restart wipe); rebinding")
            self._server.restart()
        if kubelet_restarted:
            log.warning("kubelet socket identity changed; re-registering")
        # was this poll entered because an EARLIER registration failed
        # (initial-registration failure via mark_unregistered, or a
        # previous poll whose Register died)? The pre-existing flag —
        # read BEFORE this poll re-arms it — is that memory; success
        # below is then a recovery worth journaling as such.
        recovering = self._needs_register
        # registration state is tracked separately from kubelet identity:
        # after a rebind whose Register failed, the next poll sees the
        # socket present and the identity unchanged — only this flag makes
        # it retry instead of leaving the plugin silently unregistered
        self._needs_register = True
        self.retrier.journal = self.events
        self.retrier.call(self._server.register_with_kubelet)
        # commit the observed identity only AFTER registration succeeded —
        # a failed Register (new kubelet not serving yet) must leave the
        # restart event pending so the next poll retries
        self._kubelet_ident = ident
        self._needs_register = False
        self.reregistrations += 1
        if self.events is not None:
            attempts = self.retrier.last_attempts
            if recovering:
                msg = "registration recovered after earlier failure"
            else:
                msg = "kubelet restarted; plugin re-registered"
            if attempts > 1:
                msg += f" (succeeded on attempt {attempts})"
            try:
                self.events.emit(
                    "KubeletReregistered",
                    obj=f"node/{self._server._device.host}",
                    message=msg,
                    node=self._server._device.host,
                )
            except Exception:
                log.exception("event emit failed: KubeletReregistered")
        return True

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_once()
            except Exception:
                log.exception("kubelet session poll failed")

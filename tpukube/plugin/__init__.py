"""Node agent (L3): deviceplugin/v1beta1 gRPC server + health watch."""

from tpukube.plugin.server import DevicePluginServer, HealthWatcher  # noqa: F401
from tpukube.plugin.fake_kubelet import FakeKubelet  # noqa: F401

"""Node agent (L3): deviceplugin/v1beta1 gRPC server + health watch."""

from tpukube.plugin.server import (  # noqa: F401
    DevicePluginServer,
    HealthWatcher,
    KubeletSessionWatcher,
)
from tpukube.plugin.fake_kubelet import FakeKubelet  # noqa: F401

"""In-process fake kubelet for the sim harness.

SURVEY.md §5: the reference's test trick is that "a cluster is just data" —
plugin tests run against a fake peer rather than a live kubelet. This fake
implements the kubelet side of the device-plugin contract faithfully:

  1. serves the Registration service on kubelet.sock,
  2. on Register, dials back to the plugin's endpoint (like the kubelet),
  3. opens the ListAndWatch stream and maintains a live device cache,
  4. exposes allocate() so tests/harness can play the container-start path.

BASELINE config 1 ("fake-device sim, CPU-only control plane") walks exactly
this object against a real DevicePluginServer over real unix sockets.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures
from dataclasses import dataclass, field
from typing import Optional

import grpc

from tpukube.plugin import stubs
from tpukube.plugin.proto import deviceplugin_pb2 as pb


@dataclass
class PluginHandle:
    """One registered plugin endpoint, as the kubelet tracks it."""

    resource_name: str
    endpoint: str
    options: pb.DevicePluginOptions
    channel: grpc.Channel
    stub: stubs.DevicePluginStub
    devices: dict[str, str] = field(default_factory=dict)  # id -> health
    watch_thread: Optional[threading.Thread] = None
    stream_cancel: Optional[grpc.Future] = None


class FakeKubelet(stubs.RegistrationServicer):
    def __init__(self, device_plugin_dir: str):
        self._dir = device_plugin_dir
        self._socket_path = os.path.join(device_plugin_dir, "kubelet.sock")
        self._server: Optional[grpc.Server] = None
        self._plugins: dict[str, PluginHandle] = {}
        self._lock = threading.Lock()
        self._device_event = threading.Condition(self._lock)

    # -- lifecycle ---------------------------------------------------------
    @property
    def socket_path(self) -> str:
        return self._socket_path

    def start(self) -> None:
        if self._server is not None:
            raise RuntimeError("fake kubelet already started")
        os.makedirs(self._dir, exist_ok=True)
        if os.path.exists(self._socket_path):
            os.unlink(self._socket_path)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        stubs.add_registration_to_server(self, self._server)
        self._server.add_insecure_port(f"unix://{self._socket_path}")
        self._server.start()

    def stop(self) -> None:
        with self._lock:
            handles = list(self._plugins.values())
            self._plugins.clear()
        for h in handles:
            if h.stream_cancel is not None:
                h.stream_cancel.cancel()
            h.channel.close()
            if h.watch_thread is not None:
                h.watch_thread.join(timeout=5.0)
        if self._server is not None:
            self._server.stop(0.5).wait()
            self._server = None
        if os.path.exists(self._socket_path):
            os.unlink(self._socket_path)

    def __enter__(self) -> "FakeKubelet":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- Registration service ----------------------------------------------
    def Register(self, request: pb.RegisterRequest, context) -> pb.Empty:
        if request.version != stubs.API_VERSION:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"unsupported device plugin version {request.version}",
            )
        endpoint_path = os.path.join(self._dir, request.endpoint)
        channel = grpc.insecure_channel(f"unix://{endpoint_path}")
        handle = PluginHandle(
            resource_name=request.resource_name,
            endpoint=endpoint_path,
            options=request.options,
            channel=channel,
            stub=stubs.DevicePluginStub(channel),
        )
        with self._lock:
            old = self._plugins.get(request.resource_name)
            self._plugins[request.resource_name] = handle
        if old is not None:
            if old.stream_cancel is not None:
                old.stream_cancel.cancel()
            old.channel.close()
        # Like the kubelet: immediately open the ListAndWatch stream.
        handle.watch_thread = threading.Thread(
            target=self._watch, args=(handle,), daemon=True,
            name=f"fake-kubelet-watch-{request.resource_name}",
        )
        handle.watch_thread.start()
        return pb.Empty()

    def _watch(self, handle: PluginHandle) -> None:
        try:
            stream = handle.stub.ListAndWatch(pb.Empty())
            handle.stream_cancel = stream
            for resp in stream:
                with self._lock:
                    handle.devices = {d.ID: d.health for d in resp.devices}
                    self._device_event.notify_all()
        except grpc.RpcError:
            # Stream torn down. If the plugin died (vs. us replacing or
            # closing the handle), the kubelet marks its devices unhealthy
            # so the node stops advertising capacity it can't deliver.
            with self._lock:
                if self._plugins.get(handle.resource_name) is handle:
                    handle.devices = {d: "Unhealthy" for d in handle.devices}
                    self._device_event.notify_all()

    # -- kubelet-side queries the harness uses ------------------------------
    def resources(self) -> list[str]:
        with self._lock:
            return sorted(self._plugins)

    def devices(self, resource_name: str) -> dict[str, str]:
        with self._lock:
            h = self._plugins.get(resource_name)
            return dict(h.devices) if h else {}

    def allocatable(self, resource_name: str) -> int:
        """Healthy device count — what the node would report allocatable."""
        return sum(
            1 for h in self.devices(resource_name).values() if h == "Healthy"
        )

    def wait_for_devices(
        self, resource_name: str, count: int, timeout: float = 5.0
    ) -> dict[str, str]:
        """Block until the device cache for a resource reaches ``count``
        entries (any health)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                h = self._plugins.get(resource_name)
                if h is not None and len(h.devices) >= count:
                    return dict(h.devices)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    have = dict(h.devices) if h else {}
                    raise TimeoutError(
                        f"{resource_name}: wanted {count} devices, have {have}"
                    )
                self._device_event.wait(remaining)

    def wait_for_health(
        self, resource_name: str, device_id: str, health: str, timeout: float = 5.0
    ) -> None:
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                h = self._plugins.get(resource_name)
                if h is not None and h.devices.get(device_id) == health:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"{device_id} never became {health}: "
                        f"{h.devices if h else {}}"
                    )
                self._device_event.wait(remaining)

    # -- container-start path (SURVEY.md §4.3) ------------------------------
    def allocate(
        self, resource_name: str, device_ids: list[str], timeout: float = 5.0
    ) -> dict[str, str]:
        """Play the kubelet's Allocate for one container; returns the env."""
        with self._lock:
            h = self._plugins.get(resource_name)
        if h is None:
            raise KeyError(f"no plugin registered for {resource_name}")
        resp = h.stub.Allocate(
            pb.AllocateRequest(
                container_requests=[pb.ContainerAllocateRequest(devicesIDs=device_ids)]
            ),
            timeout=timeout,
        )
        return dict(resp.container_responses[0].envs)

    def preferred(
        self,
        resource_name: str,
        available: list[str],
        size: int,
        required: Optional[list[str]] = None,
        timeout: float = 5.0,
    ) -> list[str]:
        with self._lock:
            h = self._plugins.get(resource_name)
        if h is None:
            raise KeyError(f"no plugin registered for {resource_name}")
        resp = h.stub.GetPreferredAllocation(
            pb.PreferredAllocationRequest(
                container_requests=[
                    pb.ContainerPreferredAllocationRequest(
                        available_deviceIDs=available,
                        must_include_deviceIDs=required or [],
                        allocation_size=size,
                    )
                ]
            ),
            timeout=timeout,
        )
        return list(resp.container_responses[0].deviceIDs)

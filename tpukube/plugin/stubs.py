"""Hand-written gRPC service wiring for deviceplugin/v1beta1.

grpc_tools (the protoc gRPC plugin) is not available in this environment, so
the service scaffolding normally emitted into ``*_pb2_grpc.py`` is written by
hand here against the protoc-generated messages. The method paths and
serialization must match the upstream API exactly — the kubelet is the peer.
"""

from __future__ import annotations

import grpc

from tpukube.plugin.proto import deviceplugin_pb2 as pb

API_VERSION = "v1beta1"

_REGISTRATION = "v1beta1.Registration"
_DEVICE_PLUGIN = "v1beta1.DevicePlugin"


# -- Registration service (served by the kubelet; plugins are clients) -----

class RegistrationServicer:
    def Register(self, request: pb.RegisterRequest, context) -> pb.Empty:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Register not implemented")


def add_registration_to_server(servicer: RegistrationServicer, server: grpc.Server) -> None:
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_REGISTRATION, handlers),)
    )


class RegistrationStub:
    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{_REGISTRATION}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )


# -- DevicePlugin service (served by the plugin; kubelet is the client) ----

class DevicePluginServicer:
    def GetDevicePluginOptions(self, request: pb.Empty, context) -> pb.DevicePluginOptions:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")

    def ListAndWatch(self, request: pb.Empty, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")

    def GetPreferredAllocation(
        self, request: pb.PreferredAllocationRequest, context
    ) -> pb.PreferredAllocationResponse:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")

    def Allocate(self, request: pb.AllocateRequest, context) -> pb.AllocateResponse:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")

    def PreStartContainer(
        self, request: pb.PreStartContainerRequest, context
    ) -> pb.PreStartContainerResponse:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")


def add_device_plugin_to_server(servicer: DevicePluginServicer, server: grpc.Server) -> None:
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=pb.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=pb.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_DEVICE_PLUGIN, handlers),)
    )


class DevicePluginStub:
    def __init__(self, channel: grpc.Channel):
        self.GetDevicePluginOptions = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            f"/{_DEVICE_PLUGIN}/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            f"/{_DEVICE_PLUGIN}/PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )

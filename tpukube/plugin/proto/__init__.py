"""Generated protobuf messages for the kubelet device-plugin API.

Regenerate with: protoc --python_out=. deviceplugin.proto
"""

from tpukube.plugin.proto import deviceplugin_pb2  # noqa: F401
